"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric (BASELINE.json:2): training throughput, samples/sec/chip, for
the ResNet-18/CIFAR-10 config (config 1, the reference's own workload,
/root/reference/train_ddp.py) in bf16, measured on whatever devices are
present (one real TPU chip under the driver).

The reference publishes no numbers (`"published": {}`, BASELINE.json:13), so
`vs_baseline` reports the bf16-vs-fp32 speedup on identical hardware — the
"AMP-vs-FP32 speedup curve" the reference's README promises but never fills
in (README.md:31, :35).

Usage: python bench.py [--model resnet18] [--batch-size 2048] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax

# Persistent compilation cache: bench re-runs (and driver retries) skip the
# 20-40s XLA compile of each precision variant.
try:
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_config(model_name: str, per_device_batch: int, steps: int,
                 bf16: bool, repeats: int = 3) -> float:
    """Compiled-step training throughput (global samples/s), median of
    `repeats` windows (single timings on a tunneled chip are noisy)."""
    from distributed_pytorch_training_tpu.experiments.harness import (
        build_image_trainer, synth_image_batch, timed_steps,
    )

    trainer, state, mesh = build_image_trainer(jax.devices(), bf16, model_name)
    batch, global_batch = synth_image_batch(mesh, per_device_batch)
    _log(f"bench: compiling {model_name} bf16={bf16} b={global_batch}...")
    t0 = time.perf_counter()
    _, sps = timed_steps(trainer._train_step, state, batch, global_batch,
                         steps, repeats)
    _log(f"bench: bf16={bf16} done in {time.perf_counter() - t0:.1f}s "
         f"({sps:.0f} samples/s)")
    return sps


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18")
    p.add_argument("--batch-size", default=2048, type=int,
                   help="per-device batch; 2048 saturates the chip on CIFAR "
                        "shapes (the reference default 128 leaves it ~18x "
                        "underutilized, mostly dispatch-bound — see "
                        "experiments 'batch')")
    p.add_argument("--steps", default=20, type=int)
    p.add_argument("--repeats", default=3, type=int)
    args = p.parse_args(argv)

    n_chips = jax.device_count()
    fp32 = bench_config(args.model, args.batch_size, args.steps, bf16=False,
                        repeats=args.repeats)
    bf16 = bench_config(args.model, args.batch_size, args.steps, bf16=True,
                        repeats=args.repeats)

    result = {
        "metric": (f"{args.model}_cifar10_train_throughput_bf16"
                   f"_b{args.batch_size}"),
        "value": round(bf16 / n_chips, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(bf16 / fp32, 3),  # bf16-vs-fp32 speedup (AMP parity curve)
        "per_device_batch": args.batch_size,
        "fp32_samples_per_sec_chip": round(fp32 / n_chips, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
