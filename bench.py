"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric (BASELINE.json:2): training throughput, samples/sec/chip, for
the ResNet-18/CIFAR-10 config (config 1, the reference's own workload,
/root/reference/train_ddp.py) in bf16, measured on whatever devices are
present (one real TPU chip under the driver).

Self-verification: every config reports model-FLOPs utilization (MFU),
computed from XLA's cost analysis of the exact compiled step (cross-checked
against an analytic matmul/conv count) divided by the detected chip peak
(experiments/flops.py). An implied FLOP/s above the MXU peak aborts the
config instead of reporting it — the class of error that produced a
484 TFLOP/s "result" on a 197 TFLOP/s chip in round 2.

`vs_baseline` is the bf16-vs-fp32 speedup on identical hardware — the
"AMP-vs-FP32 speedup curve" the reference's README promises but never fills
in (README.md:31, :35). The fp32 arm runs under
`jax.default_matmul_precision("highest")` so it is *real* fp32: without that,
TPU fp32 matmuls default to bf16 MXU passes and the ratio is 1.0 by
construction.

Usage: python bench.py [--batch-size 2048] [--steps 20] [--quick]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import traceback
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def init_backend_with_retry(max_attempts: int = 5):
    """Initialize the JAX backend, retrying transient init failures.

    The round-1 bench died once with UNAVAILABLE during backend init (a
    flaky tunnel rendezvous); one lost round per flake is not acceptable, so:
    exponential backoff, diagnostics to stderr, and the caller emits an
    error-JSON line if every attempt fails.
    """
    import jax

    from distributed_pytorch_training_tpu.runtime import honor_platform_env

    honor_platform_env()  # JAX_PLATFORMS=cpu functional runs work as expected
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    last = None
    for attempt in range(1, max_attempts + 1):
        try:
            devices = jax.devices()
            _log(f"bench: backend up on attempt {attempt}: "
                 f"{len(devices)}x {devices[0].device_kind} "
                 f"[{devices[0].platform}]")
            return jax, devices
        except Exception as e:  # RuntimeError/XlaRuntimeError UNAVAILABLE etc.
            last = e
            wait = 2 ** attempt
            _log(f"bench: backend init attempt {attempt}/{max_attempts} "
                 f"failed: {type(e).__name__}: {e}")
            for lock in ("/tmp/libtpu_lockfile", "/tmp/tpu_logs"):
                if Path(lock).exists():
                    _log(f"bench: note: {lock} exists (possible stale holder "
                         "of the TPU from a crashed process)")
            if attempt < max_attempts:
                _log(f"bench: retrying in {wait}s...")
                time.sleep(wait)
    raise RuntimeError(
        f"backend init failed after {max_attempts} attempts: {last}")


def _parse(argv):
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", default=4096, type=int,
                   help="per-device batch for the ResNet headline; 4096 "
                        "saturates the chip on CIFAR shapes, ~13% over 2048 "
                        "— 466-471k samples/s/chip on v5e by this bench's "
                        "differenced-window measure (the reference default "
                        "128 is dispatch-bound — see experiments 'batch')")
    p.add_argument("--steps", default=20, type=int)
    p.add_argument("--repeats", default=3, type=int)
    p.add_argument("--quick", action="store_true",
                   help="headline config only (skip gpt2/bert extras)")
    p.add_argument("--deadline", default=2400, type=int,
                   help="hard wall-clock limit (s); a hung backend emits an "
                        "error-JSON line instead of eating the round")
    p.add_argument("--_inner", action="store_true", help=argparse.SUPPRESS)
    return p.parse_args(argv)


def main(argv=None):
    """Watchdog wrapper: run the real bench in a subprocess under a hard
    deadline. A backend that hangs in a TCP recv (observed on the tunneled
    device: `jax.devices()` blocked forever, no exception to retry on) can
    then never prevent the one JSON line the driver needs."""
    import subprocess

    args = _parse(argv)
    if args._inner:
        return _bench(args)

    cmd = [sys.executable, __file__, "--_inner",
           "--batch-size", str(args.batch_size), "--steps", str(args.steps),
           "--repeats", str(args.repeats)]
    if args.quick:
        cmd.append("--quick")
    err = None
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=args.deadline)
        lines = [l for l in proc.stdout.decode().splitlines()
                 if l.startswith("{")]
        if lines:
            print(lines[-1])
            return proc.returncode
        err = f"bench subprocess exited rc={proc.returncode} with no JSON"
    except subprocess.TimeoutExpired:
        err = f"bench exceeded {args.deadline}s deadline (hung backend?)"
    print(json.dumps({
        "metric": f"resnet18_cifar10_train_throughput_bf16_b{args.batch_size}",
        "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
        "error": err,
    }))
    return 1


def _bench(args):
    t_start = time.time()
    import os

    if os.environ.get("DPT_BENCH_TEST_HANG"):
        # test hook (tests/test_bench.py): simulate the observed failure
        # mode where jax.devices() blocks forever on a wedged tunnel — the
        # watchdog parent must still emit the error-JSON line
        time.sleep(10_000)
    try:
        jax, devices = init_backend_with_retry()
    except Exception as e:
        print(json.dumps({
            "metric": "resnet18_cifar10_train_throughput_bf16"
                      f"_b{args.batch_size}",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": f"backend init failed: {e}",
        }))
        return 1

    from distributed_pytorch_training_tpu.experiments.harness import (
        measure_config,
    )

    n_chips = jax.device_count()

    from distributed_pytorch_training_tpu.experiments.flops import (
        MeasurementError,
    )

    def run(name, **kw):
        _log(f"bench: === {name} {kw} ===")
        t0 = time.perf_counter()
        try:
            r = measure_config(name, repeats=args.repeats, **kw)
        except MeasurementError as e:
            # noisy tunnel windows: one escalation to much longer windows
            # before giving up on the config
            _log(f"bench: {name}: {e}; retrying with 5s windows")
            r = measure_config(name, repeats=args.repeats,
                               min_window_s=5.0, **kw)
        _log(f"bench: {name} done in {time.perf_counter() - t0:.1f}s: "
             f"{r['samples_per_sec_chip']:.0f} samples/s/chip, "
             f"mfu={r['mfu_pct']}%")
        return r

    # Headline: ResNet-18/CIFAR-10 (the reference's workload) in bf16 FIRST —
    # an fp32-arm failure (bigger memory footprint under HIGHEST precision)
    # must degrade vs_baseline to null, not forfeit the headline number.
    err = None
    headline = fp32 = None
    try:
        headline = run("resnet18", per_device_batch=args.batch_size,
                       steps=args.steps, bf16=True)
    except Exception as e:
        err = f"{type(e).__name__}: {e}"
        _log("bench: headline config failed:\n" + traceback.format_exc())
    if headline is not None:
        try:
            fp32 = run("resnet18", per_device_batch=args.batch_size,
                       steps=args.steps, bf16=False)
        except Exception:
            _log("bench: fp32 baseline arm failed (vs_baseline -> null):\n"
                 + traceback.format_exc())

    extras = []
    if headline is not None and not args.quick:
        # The rest of the BASELINE matrix, single-chip (BASELINE.json:9-12):
        # ResNet-50 + ViT-B/16 on ImageNet shapes, GPT-2 124M causal LM,
        # BERT-base MLM @ 512.
        for name, kw in (
            ("resnet50", dict(per_device_batch=128, image_hw=224,
                              num_classes=1000, steps=10)),
            ("vit_b16", dict(per_device_batch=64, image_hw=224,
                             num_classes=1000, steps=10)),
            ("gpt2_124m", dict(per_device_batch=8, seq_len=1024, steps=10)),
            ("bert_base", dict(per_device_batch=16, seq_len=512, steps=10)),
            # long-context (flash kernels) and expert-parallel coverage
            ("gpt2_124m", dict(per_device_batch=2, seq_len=4096, steps=10)),
            ("gpt2_moe", dict(per_device_batch=8, seq_len=1024, steps=10)),
        ):
            try:
                extras.append(run(name, bf16=True, **kw))
            except Exception:
                _log(f"bench: extra config {name} failed (continuing):\n"
                     + traceback.format_exc())

    if headline is None:
        print(json.dumps({
            "metric": f"resnet18_cifar10_train_throughput_bf16"
                      f"_b{args.batch_size}",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": err or "unknown",
        }))
        return 1

    result = {
        "metric": f"resnet18_cifar10_train_throughput_bf16_b{args.batch_size}",
        "value": headline["samples_per_sec_chip"],
        "unit": "samples/sec/chip",
        # True AMP curve: bf16 vs HIGHEST-precision fp32 on the same chip.
        "vs_baseline": (round(headline["samples_per_sec"]
                              / fp32["samples_per_sec"], 3)
                        if fp32 else None),
        "per_device_batch": args.batch_size,
        "n_chips": n_chips,
        "chip": devices[0].device_kind,
        "mfu_pct": headline["mfu_pct"],
        "chip_peak_tflops_bf16": headline["chip_peak_tflops_bf16"],
        "tflops_per_sec": headline["tflops_per_sec"],
        "fp32_samples_per_sec_chip": (fp32["samples_per_sec_chip"]
                                      if fp32 else None),
        "fp32_true_precision": fp32 is not None,
        "configs": [c for c in [headline, fp32] + extras if c],
        "bench_seconds": round(time.time() - t_start, 1),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
