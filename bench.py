"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric (BASELINE.json:2): training throughput, samples/sec/chip, for
the ResNet-18/CIFAR-10 config (config 1, the reference's own workload,
/root/reference/train_ddp.py) in bf16, measured on whatever devices are
present (one real TPU chip under the driver).

Self-verification: every config reports model-FLOPs utilization (MFU),
computed from XLA's cost analysis of the exact compiled step (cross-checked
against an analytic matmul/conv count) divided by the detected chip peak
(experiments/flops.py). An implied FLOP/s above the MXU peak aborts the
config instead of reporting it — the class of error that produced a
484 TFLOP/s "result" on a 197 TFLOP/s chip in round 2.

Failure envelope (sized against the driver budget after round 3 died rc=124):
the whole bench lives under a hard --deadline (default 840s, inside any
plausible driver timeout). Backend bring-up is probed in DISPOSABLE
subprocesses, each time-boxed to --probe-timeout (default 120s), under a
total --init-budget (default 300s): a wedged tunnel (observed live: one
jax.devices() attempt blocked ~25 minutes, BENCH_r03.json) costs one
error-JSON line, never the round. Processes are stopped with SIGTERM + grace
only — a SIGKILLed claim-holder can wedge the TPU for every later process.

`vs_baseline` is the bf16-vs-fp32 speedup on identical hardware — the
"AMP-vs-FP32 speedup curve" the reference's README promises but never fills
in (README.md:31, :35). The fp32 arm runs under
`jax.default_matmul_precision("highest")` so it is *real* fp32: without that,
TPU fp32 matmuls default to bf16 MXU passes and the ratio is 1.0 by
construction.

Every completed run appends its full result dict (all configs, not just the
headline line) to experiments/results/bench_history.jsonl with chip kind and
timestamp, so the README benchmark table is regenerable from committed JSON.

Usage: python bench.py [--batch-size 4096] [--steps 20] [--quick]
       python bench.py --only gpt2_124m,bert_base   # chunked provenance run
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import traceback
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

# The liveness layer (relay-port registry, probing, deathwatch) lives in
# resilience/heartbeat.py — extracted from here so bench and train share one
# source of truth for the 8082/8083/8087 port set and the ADVICE-r5 fixes
# (1.5s/3-miss lethal probe, bounded PJRT close on partial death) can never
# drift between two copies. heartbeat imports no jax at module scope, so
# this is safe before backend bring-up.
from distributed_pytorch_training_tpu.resilience.heartbeat import (  # noqa: E402
    Deathwatch, LivenessPolicy, port_listening as _port_listening,
    relay_ports as _relay_ports,
)
# Structured run telemetry (telemetry/, jax-free): the chip-probe failure
# diagnostics are recorded as typed events (not just stderr prints that
# die with the terminal), the headline row carries the stream's path, and
# a failed backend bring-up flushes a flight_<ts>.json postmortem.
from distributed_pytorch_training_tpu import telemetry as _telemetry  # noqa: E402

HISTORY_PATH = Path(__file__).resolve().parent / \
    "distributed_pytorch_training_tpu" / "experiments" / "results" / \
    "bench_history.jsonl"

# Non-headline configs of the BASELINE matrix: (label, model, est_s, kwargs).
# Labels are stable names for --only selection and for bench_history rows;
# est_s is the conservative wall-cost gate documented at the use site.
EXTRA_CONFIGS = (
    ("resnet50", "resnet50", 420,
     dict(per_device_batch=128, image_hw=224, num_classes=1000, steps=10)),
    ("vit_b16", "vit_b16", 420,
     dict(per_device_batch=64, image_hw=224, num_classes=1000, steps=10)),
    ("gpt2_124m", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10)),
    ("bert_base", "bert_base", 400,
     dict(per_device_batch=16, seq_len=512, steps=10)),
    # long-context (flash kernels) and expert-parallel coverage
    ("gpt2_124m_s4096", "gpt2_124m", 420,
     dict(per_device_batch=2, seq_len=4096, steps=10)),
    ("gpt2_moe", "gpt2_moe", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10)),
    # the BASELINE flagship architecture (config 5) at single-chip scale:
    # ~4.3GB params+moments fp32, fits v5e HBM at b=2
    ("gpt2_355m", "gpt2_355m", 420,
     dict(per_device_batch=2, seq_len=1024, steps=6)),
    # headline batch-scaling probe: b4096 was +13% over b2048; if b8192
    # measures higher still, it becomes the headline default (activations
    # ~2x the b4096 run; expected to fit 16G HBM on CIFAR shapes)
    ("resnet18_b8192", "resnet18", 420,
     dict(per_device_batch=8192, image_hw=32, num_classes=10, steps=20)),
    # true-fp32 arm of the GPT-2 config: extends the measured AMP-vs-FP32
    # curve (the reference's README:31 experiment) beyond the ResNet
    # headline to the LM family, same HIGHEST-precision semantics
    ("gpt2_124m_fp32", "gpt2_124m", 420,
     dict(per_device_batch=8, seq_len=1024, steps=10, bf16=False)),
    # ZeRO-1 sharded-weight-update arms (training/loop.py zero1): on one
    # chip the mode is an identity passthrough (same numbers as the plain
    # config — a cheap regression canary); on multi-chip meshes these rows
    # are the replicated-vs-sharded comparison the scaling target needs
    # (experiments/scaling.py `zero1` is the full instrumented arm)
    ("resnet18_zero1", "resnet18", 420,
     dict(per_device_batch=4096, image_hw=32, num_classes=10, steps=20,
          zero1=True)),
    ("gpt2_124m_zero1", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10, zero1=True)),
    # Explicit bucketed/compressed gradient sync (training/loop.py
    # bucket_cap_mb / wire_dtype; parallel/grad_sync.py): on one chip the
    # reducer is an identity passthrough (regression canary, like the
    # zero1 arms); on multi-chip meshes these rows carry the bucket census
    # + exposed-comm fraction, the overlap-efficiency numbers BENCH_*
    # history tracks across PRs (experiments/scaling.py `grad_sync` is the
    # full instrumented arm)
    ("resnet18_gsync", "resnet18", 420,
     dict(per_device_batch=4096, image_hw=32, num_classes=10, steps=20,
          grad_sync=dict(bucket_cap_mb=25.0))),
    ("gpt2_124m_gsync_bf16", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10,
          grad_sync=dict(bucket_cap_mb=25.0, wire_dtype="bf16"))),
    # DynamiQ-style multi-hop int8 wire (wire_dtype="int8_multihop"):
    # s8 all-to-all reduce-scatter + requantized s8 all-gather — exactly
    # 2 collectives/bucket and ~2 wire B/element at ANY DP degree (the
    # n-independent fix for the gather-form int8's (n-1)·S scaling);
    # rows carry wire_bytes_per_replica so the claim is a recorded number
    ("resnet18_gsync_mh", "resnet18", 420,
     dict(per_device_batch=4096, image_hw=32, num_classes=10, steps=20,
          grad_sync=dict(bucket_cap_mb=25.0, wire_dtype="int8_multihop"))),
    ("gpt2_124m_gsync_mh", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10,
          grad_sync=dict(bucket_cap_mb=25.0, wire_dtype="int8_multihop"))),
    # Two-tier topology-aware wire (wire_dtype="int8_hier"): exact fp32
    # reduce-scatter INSIDE a slice (fast ICI tier), the s8+EF multihop
    # exchange ACROSS slices (slow DCN tier — ~2 B/element per slice
    # independent of the slice count), exact intra-slice all-gather back.
    # The mesh_spec carries the slice factorization; needs >= 2 chips
    # (slice=2 on one device fails the mesh build loudly and the
    # per-config guard records the skip, like the _tp arm) — on a
    # slice-axis-of-1 mesh the trainer instead resolves to the flat fp32
    # passthrough (bit-identical). Rows record wire_bytes_per_replica
    # with the slow-tier term split out so the slice-count-independence
    # claim is a committed number.
    ("resnet18_gsync_hier", "resnet18", 420,
     dict(per_device_batch=4096, image_hw=32, num_classes=10, steps=20,
          grad_sync=dict(bucket_cap_mb=25.0, wire_dtype="int8_hier"),
          mesh_spec="slice=2,data=-1")),
    ("gpt2_124m_gsync_hier", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10,
          grad_sync=dict(bucket_cap_mb=25.0, wire_dtype="int8_hier"),
          mesh_spec="slice=2,data=-1")),
    # Explicit full-parameter FSDP (training/loop.py fsdp_explicit;
    # SimpleFSDP, PAPERS.md): params + moments flat-sharded 1/N at rest,
    # one just-in-time param all-gather per layer group, gradients
    # reduce-scattered straight into the shard layout. On one chip the
    # mode is an identity passthrough (regression canary); on multi-chip
    # meshes these rows carry the per-layer gather census, the at-rest
    # memory division, and the fsdp_gather_bytes wire term
    # (experiments/scaling.py `fsdp` is the full instrumented arm). The
    # _mh arm compresses BOTH wire directions (s8 scatter with EF + s8
    # param gathers — ~2 B/element total at any DP degree); the 355m arm
    # is the BASELINE flagship whose replicated params+moments cap the
    # v4-32 pod config — the model this mode exists to unlock.
    ("gpt2_124m_fsdp", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10,
          grad_sync=dict(fsdp_explicit=True))),
    ("gpt2_124m_fsdp_mh", "gpt2_124m", 400,
     dict(per_device_batch=8, seq_len=1024, steps=10,
          grad_sync=dict(fsdp_explicit=True,
                         wire_dtype="int8_multihop"))),
    ("gpt2_355m_fsdp", "gpt2_355m", 420,
     dict(per_device_batch=2, seq_len=1024, steps=6,
          grad_sync=dict(fsdp_explicit=True))),
    # Explicit TP x FSDP on the 2-D ("data","model") mesh (ISSUE 13): the
    # BASELINE flagship with megatron column/row-split blocks + the
    # vocab-parallel embedding inside the FSDP shard_map — params + AdamW
    # moments at rest 1/(N*M) for TP-split tensors, per-layer
    # gather/scatter wire 1/M per replica, one model-axis psum per
    # residual join. Rows carry tp_psum_bytes_per_replica next to the
    # data-axis terms and the tp-psum-signature contract verdict. Needs
    # >= 2 chips (model=2 on one device fails the mesh build loudly; the
    # per-config guard records the skip).
    ("gpt2_355m_fsdp_tp", "gpt2_355m", 420,
     dict(per_device_batch=2, seq_len=1024, steps=6,
          grad_sync=dict(fsdp_explicit=True),
          mesh_spec="data=-1,model=2")),
    # Serving offered-load arms (ISSUE 17): latency rows, not train
    # throughput — the `serving` marker routes them past measure_config to
    # run_serving (experiments/harness measure_serving /
    # measure_serving_continuous), and their value is tokens/sec. The
    # iteration/token pair at the SAME offered load and shapes is the
    # continuous-batching A/B the acceptance gate reads: token-granular
    # (slot pool + paged KV, requests join/leave between tokens) must beat
    # iteration-granular (form batch -> decode to completion -> repeat) on
    # BOTH tok/s and p99 — the p99 win is the point, a long request no
    # longer convoys the short ones behind it. The int8 arm adds the
    # paged-vs-dense KV byte ratio (>= 3x is the HBM claim); the fleet arm
    # runs 2 router-fronted replicas and KILLS one mid-run — every request
    # must still complete (seed-pinned resubmit) with zero recompiles.
    # mixed_want gives every request its own decode length (1..max_new,
    # seed-pinned identically on both arms) — the serving-shaped workload
    # where convoying actually hurts: the iteration arm must decode the
    # full max_new for the whole batch and only the wanted tokens count.
    ("serving_iter_gpt2", "gpt2_124m", 300,
     dict(serving=dict(kind="iteration", n_requests=24, offered_rps=16.0,
                       buckets=(8, 16), rows=8, max_new_tokens=8,
                       mixed_want=True))),
    ("serving_token_gpt2", "gpt2_124m", 300,
     dict(serving=dict(kind="token", n_requests=24, offered_rps=16.0,
                       buckets=(8, 16), rows=8, max_new_tokens=8,
                       mixed_want=True))),
    ("serving_token_int8", "gpt2_124m", 300,
     dict(serving=dict(kind="token", n_requests=24, offered_rps=16.0,
                       buckets=(8, 16), rows=8, max_new_tokens=8,
                       mixed_want=True, kv_dtype="int8", page_size=8))),
    ("serving_fleet2", "gpt2_124m", 360,
     dict(serving=dict(kind="token", n_requests=24, offered_rps=16.0,
                       buckets=(8, 16), rows=8, max_new_tokens=8,
                       mixed_want=True, replicas=2, kill_replica=True))),
)

# Probe script run in a disposable subprocess: succeeds iff the backend can
# actually enumerate devices. Lives out-of-process so a wedged tunnel (which
# blocks jax.devices() in a C-level recv no signal handler can interrupt)
# costs one SIGTERMed child, not the bench. honor_platform_env re-asserts
# JAX_PLATFORMS=cpu via the config API — the image's sitecustomize registers
# the accelerator plugin at interpreter startup, so the env var alone is
# not honored.
_PROBE_SRC = rf"""
import os, sys, time
if os.environ.get("DPT_BENCH_TEST_WEDGE"):
    time.sleep(10_000)  # test hook: simulate the observed wedged tunnel
sys.path.insert(0, {str(Path(__file__).resolve().parent)!r})
import jax
from distributed_pytorch_training_tpu.runtime import honor_platform_env
honor_platform_env()
d = jax.devices()
print(f"OK {{len(d)}} {{d[0].device_kind}} {{d[0].platform}}", flush=True)
"""


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _tunnel_status() -> "str | None":
    """Liveness of the tunneled backend's local relay ports, if any.

    On the tunneled single-chip environment, device RPCs and remote
    compilation ride localhost relay ports (8082/8083/...); a dead relay is
    indistinguishable from a "wedged chip" at the jax.devices() level (the
    client sleep-retries UNAVAILABLE for tens of minutes — observed live,
    CHIP_STATUS.md 2026-07-31 03:59: `/remote_compile: Connection refused`
    after a 40-minute retry loop). A 200ms TCP connect distinguishes the two
    failure classes. A dead relay and a machine that never had one look the
    same from here, so the all-closed/unconfigured case returns a string
    that says so explicitly; callers additionally gate the log note on
    hang-type failures (the dead-relay signature) so deterministic errors
    like an ImportError never carry a relay hint. Returns None only when
    DPT_RELAY_PORTS is set but contains no usable port numbers.
    """
    ports = _relay_ports()
    if not ports:
        return None
    status = {p: ("listening" if _port_listening(p) else "closed")
              for p in ports}
    if all(v == "closed" for v in status.values()):
        if "DPT_RELAY_PORTS" in os.environ:
            return "relay tunnel DOWN (all relay ports closed; no " \
                "client-side remedy — the outer harness must respawn it)"
        return "no local relay ports listening (not a tunneled " \
            "environment, or the relay tunnel is dead — a probe that " \
            "hangs in UNAVAILABLE retries means the latter)"
    if any(v == "closed" for v in status.values()):
        closed = [p for p, v in status.items() if v == "closed"]
        return f"relay tunnel PARTIALLY down (ports {closed} closed — " \
            "remote compilation will fail with UNAVAILABLE)"
    confident = "DPT_RELAY_PORTS" in os.environ
    return "relay ports listening (tunnel up; a hang past this point is a " \
        "stuck server-side grant, not a dead relay)" if confident else \
        "default relay ports (8082/8083/8087) have listeners — IF this " \
        "machine " \
        "is the tunneled environment the tunnel is up and a hang is a " \
        "stuck server-side grant; set DPT_RELAY_PORTS to make this check " \
        "authoritative"


def _start_relay_deathwatch(interval_s: "float | None" = None,
                            assume_tunneled: bool = False):
    """Abort the inner promptly when the local relay tunnel dies mid-run.

    The deathwatch itself (per-port 3-consecutive-miss counters probed with
    a 1.5s connect timeout, bounded best-effort PJRT close on PARTIAL death,
    `os._exit(70)`) now lives in resilience/heartbeat.py — the generalized
    liveness layer this bench seeded; see Deathwatch/LivenessPolicy for the
    full rationale (ADVICE r5 #1-#3, CHIP_STATUS.md incidents). This wrapper
    keeps bench's gating and plumbing: arm ONLY when DPT_RELAY_PORTS is
    explicitly set (default-port heuristics would let an unrelated dev
    service on 8082 of a non-tunneled machine kill a healthy run by
    restarting) or when the caller passes assume_tunneled=True after a
    successful backend probe CONFIRMED the tunnel; and before the abort,
    reap the in-flight backend probes — an orphaned probe mid-jax.devices()
    would keep the TPU claim past the inner's death. The parent's
    crash-salvage branch (inner rc=70) then records and reports any
    already-flushed measurement."""

    def reap_probes(dead_ports, alive_ports):
        # signal.signal is main-thread-only, so no group SIGTERM from the
        # watch thread; the live-probe registry names the children.
        # Flag-set is ordered against probe spawn by _PROBE_LOCK: after the
        # lock releases, every live probe is registered and no new one can
        # spawn (a probe launched in the reap-then-exit window would be
        # orphaned by the abort holding the TPU claim).
        _log("bench: flushed measurements are salvaged by the parent "
             "(inner rc=70)")
        with _PROBE_LOCK:
            _RELAY_DEAD.set()
        for p in list(_LIVE_PROBES):
            _stop_gently(p, grace_s=5.0)

    policy = LivenessPolicy(
        interval_s=interval_s if interval_s is not None else
        float(os.environ.get("DPT_RELAY_WATCH_INTERVAL", "30")))
    return Deathwatch.arm(assume_tunneled=assume_tunneled, policy=policy,
                          on_death=reap_probes,
                          log=lambda m: _log(f"bench: {m}"))


def _stop_gently(proc: subprocess.Popen, grace_s: float = 15.0,
                 group: bool = False) -> bool:
    """SIGTERM + grace, never SIGKILL: an abruptly killed process that holds
    the TPU claim can leave the chip unusable for hours (a dead claim-holder
    blocks every later jax.devices()). If SIGTERM can't reap it we leave the
    orphan and report, which is strictly safer than wedging the chip.
    With group=True the whole process group is signalled, so a probe
    grandchild mid-jax.devices() dies with its parent instead of being
    orphaned holding the chip claim. Returns True iff confirmed dead."""
    if proc.poll() is not None:
        return True
    try:
        if group:
            os.killpg(proc.pid, signal.SIGTERM)
        else:
            proc.terminate()
    except (ProcessLookupError, PermissionError):
        proc.terminate()
    try:
        proc.wait(timeout=grace_s)
        return True
    except subprocess.TimeoutExpired:
        _log(f"bench: WARNING: pid {proc.pid} survived SIGTERM {grace_s}s; "
             "leaving it (never SIGKILL a TPU claim-holder)")
        return False


# Live backend-probe subprocesses, registered so the relay deathwatch can
# SIGTERM them before it aborts the inner — an orphaned probe mid-
# jax.devices() would keep the TPU claim past the inner's death.
_LIVE_PROBES: "set[subprocess.Popen]" = set()
# Set by the deathwatch the moment it decides to abort: no NEW probe may
# spawn during the reap-then-exit window (a probe launched there would be
# orphaned by os._exit holding the TPU claim). _PROBE_LOCK orders probe
# spawn+registration against flag-set+sweep: whichever side takes the lock
# first, a spawned probe is either visible to the sweep or never spawned.
_RELAY_DEAD = threading.Event()
_PROBE_LOCK = threading.Lock()


def probe_backend(timeout_s: float):
    """Run one disposable backend probe. Returns (ok, detail, orphaned) —
    orphaned means the probe survived SIGTERM and may still hold the TPU
    claim, so further probes cannot succeed until it dies."""
    with _PROBE_LOCK:
        if _RELAY_DEAD.is_set():
            return False, "relay tunnel died (deathwatch firing)", False
        # spawn+register must be atomic vs the deathwatch sweep (that is
        # the lock's whole job); the slow part — communicate() — waits
        # outside the lock below
        proc = subprocess.Popen(  # analysis: disable=no-blocking-under-lock
            [sys.executable, "-c", _PROBE_SRC],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        _LIVE_PROBES.add(proc)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        died = _stop_gently(proc)
        return False, f"probe hung >{timeout_s:.0f}s (wedged backend?)", \
            not died
    finally:
        _LIVE_PROBES.discard(proc)
    out = out.decode(errors="replace")
    ok_line = next((l for l in out.splitlines() if l.startswith("OK ")), None)
    if proc.returncode == 0 and ok_line:
        return True, ok_line.strip(), False
    tail = err.decode(errors="replace").strip().splitlines()[-3:]
    return False, (f"probe rc={proc.returncode}: " + " | ".join(tail)), False


def init_backend_with_retry(init_budget_s: float = 300.0,
                            probe_timeout_s: float = 120.0):
    """Initialize the JAX backend within a hard time budget.

    Round 1 lost its round to an unguarded UNAVAILABLE; round 3 lost its
    round to the opposite failure: each in-process jax.devices() attempt
    blocked ~25 minutes on a wedged tunnel, so five retries outlived the
    driver (BENCH_r03.json). Now every attempt is a subprocess probe with
    its own timeout, and the TOTAL budget is capped: when it is gone we
    raise immediately so the caller prints the error-JSON line while the
    driver is still listening.
    """
    import jax

    from distributed_pytorch_training_tpu.runtime import honor_platform_env

    honor_platform_env()  # JAX_PLATFORMS=cpu functional runs work as expected

    deadline = time.monotonic() + init_budget_s
    attempt, last, same_fast_failures = 0, "no probe ran", 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 1.0:
            raise RuntimeError(
                f"backend init budget ({init_budget_s:.0f}s) exhausted after "
                f"{attempt} probe(s); last: {last}")
        attempt += 1
        t0 = time.monotonic()
        ok, detail, orphaned = probe_backend(min(probe_timeout_s, remaining))
        took = time.monotonic() - t0
        if ok:
            _log(f"bench: backend probe {attempt} up in {took:.1f}s: "
                 f"{detail}")
            _telemetry.emit("event", "chip_probe_ok", attempt=attempt,
                            took_s=round(took, 1), detail=detail)
            break
        _log(f"bench: backend probe {attempt} failed ({took:.1f}s): {detail}")
        # the recorded form of the diagnostic: a typed event in the
        # telemetry stream (and the flight ring), so a failed bring-up is
        # attributable after the fact instead of living only on stderr
        _telemetry.emit("event", "chip_probe_failure", attempt=attempt,
                        took_s=round(took, 1), detail=detail,
                        orphaned=orphaned)
        if "hung" in detail or "UNAVAILABLE" in detail:
            tunnel = _tunnel_status()
            if tunnel:
                _log(f"bench: note: {tunnel}")
                _telemetry.emit("event", "tunnel_status", status=tunnel)
        if orphaned:
            # An un-reapable probe may still hold the chip claim; more
            # probes can only fail against it. Fail fast instead of
            # burning the rest of the budget on doomed attempts.
            raise RuntimeError(
                f"backend probe survived SIGTERM and may hold the TPU "
                f"claim (after {attempt} probe(s); last: {detail})")
        # A deterministic failure (ImportError, bad env) repeats identically
        # and fast; retrying it for the whole budget just delays the
        # error-JSON. Timeouts and UNAVAILABLE flakes stay retryable.
        if detail == last and took < probe_timeout_s / 2:
            same_fast_failures += 1
            if same_fast_failures >= 2:
                raise RuntimeError(
                    f"backend init failing deterministically after "
                    f"{attempt} probe(s): {detail}")
        else:
            same_fast_failures = 0
        last = detail
        for lock in ("/tmp/libtpu_lockfile", "/tmp/tpu_logs"):
            if Path(lock).exists():
                _log(f"bench: note: {lock} exists (possible stale holder "
                     "of the TPU from a crashed process)")
        time.sleep(min(2.0, max(0.0, deadline - time.monotonic())))

    # The probe released its claim on exit; enumerate in-process (fast now —
    # and the parent watchdog's deadline still covers a pathological hang).
    # Retry transient UNAVAILABLE here too: the probe's success proved the
    # probe process's rendezvous, not this one's (round 1 lost a round to
    # exactly one such flake).
    while True:
        try:
            devices = jax.devices()
            break
        except Exception as e:
            if deadline - time.monotonic() <= 5.0:
                raise RuntimeError(
                    f"in-process device enumeration kept failing after a "
                    f"successful probe: {e}")
            _log(f"bench: in-process jax.devices() failed ({e}); retrying")
            time.sleep(2.0)
    _log(f"bench: backend up: {len(devices)}x {devices[0].device_kind} "
         f"[{devices[0].platform}]")
    # Now that the backend is provably up, point the persistent compile
    # cache at the repo-local dir (survives the host's /tmp-wiping reboots).
    # Self-gating on the RESOLVED backend: a silent fallback to XLA:CPU must
    # never get a persistent cache (unsafe reloads — runtime.dist docstring).
    from distributed_pytorch_training_tpu.runtime import (
        enable_persistent_compile_cache,
    )
    cache_enabled = enable_persistent_compile_cache(
        Path(__file__).resolve().parent / ".jax_cache")
    if cache_enabled:
        _log("bench: persistent compile cache at .jax_cache/")
    return jax, devices, cache_enabled


def _parse(argv):
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", default=4096, type=int,
                   help="per-device batch for the ResNet headline; 4096 "
                        "saturates the chip on CIFAR shapes, ~13% over 2048 "
                        "— 466-471k samples/s/chip on v5e by this bench's "
                        "differenced-window measure (the reference default "
                        "128 is dispatch-bound — see experiments 'batch')")
    p.add_argument("--steps", default=20, type=int)
    p.add_argument("--repeats", default=3, type=int)
    p.add_argument("--quick", action="store_true",
                   help="headline config only (skip gpt2/bert extras)")
    p.add_argument("--only", default=None,
                   help="comma-separated config labels to run, from "
                        "{headline, fp32} plus the EXTRA_CONFIGS labels "
                        "(e.g. --only resnet50,vit_b16). For chunked "
                        "provenance runs that each finish well inside one "
                        "deadline; every completed run still appends to "
                        "bench_history.jsonl")
    p.add_argument("--deadline", default=840, type=int,
                   help="hard wall-clock limit (s); must sit INSIDE the "
                        "driver's own timeout so a hung backend costs an "
                        "error-JSON line, not the round (r3 died rc=124 "
                        "when 2400s outlived the driver)")
    p.add_argument("--init-budget", default=300, type=int,
                   help="total seconds allowed for backend bring-up probes")
    p.add_argument("--probe-timeout", default=120, type=int,
                   help="seconds before one backend probe is SIGTERMed")
    p.add_argument("--_inner", action="store_true", help=argparse.SUPPRESS)
    return p.parse_args(argv)


def main(argv=None):
    """Watchdog wrapper: run the real bench in a subprocess under a hard
    deadline. A backend that hangs in a TCP recv (observed on the tunneled
    device: `jax.devices()` blocked forever, no exception to retry on) can
    then never prevent the one JSON line the driver needs."""
    args = _parse(argv)
    if args._inner:
        return _bench(args)

    cmd = [sys.executable, __file__, "--_inner",
           "--batch-size", str(args.batch_size), "--steps", str(args.steps),
           "--repeats", str(args.repeats),
           "--deadline", str(args.deadline),
           "--init-budget", str(args.init_budget),
           "--probe-timeout", str(args.probe_timeout)]
    if args.quick:
        cmd.append("--quick")
    if args.only:
        cmd += ["--only", args.only]
    def rc_for(line, fallback_rc):
        # A valid measured result that was flushed must count as success
        # even when the inner later crashed or was SIGTERMed; an inner
        # error-JSON keeps its nonzero rc.
        try:
            return fallback_rc if "error" in json.loads(line) else 0
        except Exception:
            return fallback_rc or 1

    err = None
    # Own process group: a deadline SIGTERM must take down the inner AND any
    # probe grandchild mid-jax.devices() — an orphaned probe would keep the
    # TPU claim and wedge the chip for every later process.
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=args.deadline)
        lines = [l for l in out.decode().splitlines() if l.startswith("{")]
        if lines:
            # An inner that crashed AFTER flushing a provisional line (OOM
            # kill mid-extras) never reached its own _record_history — the
            # measurement must be salvaged exactly like a deadline SIGTERM.
            line = lines[-1] if proc.returncode == 0 else \
                _finalize_salvaged(lines[-1], f"inner rc={proc.returncode}",
                                   args.only)
            print(line)
            return rc_for(line, proc.returncode)
        err = f"bench subprocess exited rc={proc.returncode} with no JSON"
    except subprocess.TimeoutExpired:
        died = _stop_gently(proc, group=True)
        # Drain whatever the inner managed to flush before the deadline —
        # it prints a provisional result right after the headline config,
        # so a SIGTERM mid-extras (or a hang in PJRT client teardown AFTER
        # the result printed) must not turn a measured round into an error.
        salvaged = None
        if died:
            try:
                out, _ = proc.communicate(timeout=10)
                lines = [l for l in out.decode().splitlines()
                         if l.startswith("{")]
                salvaged = lines[-1] if lines else None
            except Exception:
                pass
        if salvaged is not None:
            _log(f"bench: deadline hit but a result JSON was already "
                 f"flushed — reporting it")
            # A SIGTERMed inner usually never reached its own
            # _record_history: salvage appends the measurement so provenance
            # survives a deadline (the r5 full-matrix run lost its history
            # row this way before this branch existed); an inner that DID
            # record and then hung in PJRT teardown must not get a
            # duplicate row (finalize_salvaged's _history_has guard).
            salvaged = _finalize_salvaged(salvaged, "deadline SIGTERM",
                                          args.only)
            print(salvaged)
            return rc_for(salvaged, 1)
        err = f"bench exceeded {args.deadline}s deadline (hung backend?)"
    print(json.dumps({
        "metric": f"resnet18_cifar10_train_throughput_bf16_b{args.batch_size}",
        "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
        "error": err,
    }))
    return 1


def _finalize_salvaged(line: str, how: str, only_arg: "str | None") -> str:
    """A measured line the INNER flushed but never finalized itself
    (deadline SIGTERM, crash, OOM-kill mid-extras): resolve any
    "<provisional>" marker, append to history exactly once, and return the
    RESOLVED line — stdout (the driver contract) and the committed history
    row must agree; the raw line would leak a literal placeholder as data.
    A line the inner did finalize (last history row matches) or an error
    line passes through untouched. Test hooks must not pollute the
    committed log (the hang tests run this parent as a subprocess, out of
    monkeypatch reach)."""
    try:
        d = json.loads(line)
    except Exception:
        return line
    if ("error" in d
            or os.environ.get("DPT_BENCH_TEST_HANG")
            or os.environ.get("DPT_BENCH_TEST_WEDGE")
            or _history_has(d)):
        return line
    d["salvaged"] = how
    _resolve_provisional_marker(d, only_arg)
    _record_history(d)
    return json.dumps(d)


def _last_good() -> "dict | None":
    """Most recent committed history row with a real on-chip number — cited
    in the backend-init error JSON so a wedged tunnel (hours-long, twice
    observed: CHIP_STATUS.md) doesn't erase the evidence trail."""
    try:
        rows = [json.loads(l) for l in
                HISTORY_PATH.read_text().splitlines() if l.strip()]
        for r in reversed(rows):
            if r.get("value", 0) and "TPU" in str(r.get("chip", "")):
                return {k: r.get(k) for k in
                        ("timestamp", "metric", "value", "mfu_pct", "chip")}
    except Exception:
        pass
    return None


def _resolve_provisional_marker(d: dict, only_arg: "str | None") -> None:
    """A salvaged provisional line carries a literal "<provisional>" in
    configs_skipped (it is printed before the inner knows what it will get
    to). History rows are provenance: replace the marker with the configs
    that actually never ran — selected labels minus measured ones — so the
    regenerated README never renders a placeholder as data."""
    skipped = d.get("configs_skipped") or []
    if "<provisional>" not in skipped:
        return
    sel = ({s.strip() for s in only_arg.split(",") if s.strip()}
           if only_arg else {l for l, _, _, _ in EXTRA_CONFIGS})
    measured = {c.get("label") for c in d.get("configs", [])
                if c.get("label")}
    missing = {s for s in skipped if s != "<provisional>"} \
        | (sel - {"headline", "fp32"} - measured)
    if (only_arg is None or "fp32" in sel) and \
            not any(c.get("bf16") is False and not c.get("label")
                    for c in d.get("configs", [])):
        # the HEADLINE fp32 arm is the label-less bf16=False config; a
        # labeled fp32 extra (gpt2_124m_fp32) must not mask its absence
        missing.add("fp32")
    d["configs_skipped"] = sorted(missing)


def _history_has(result: dict) -> bool:
    """True iff the last history row is the same measurement (the inner
    recorded it, flushed the JSON, then hung in teardown past the deadline).
    Bookkeeping keys the two paths add differently are ignored."""
    drop = ("timestamp", "salvaged", "salvaged_after_deadline",
            "code_fingerprint")
    try:
        last = json.loads(
            HISTORY_PATH.read_text().splitlines()[-1])
        return {k: v for k, v in last.items() if k not in drop} == \
            {k: v for k, v in result.items() if k not in drop}
    except Exception:
        return False


_FINGERPRINT_CACHE = None


def _code_fingerprint() -> str:
    """Hash of everything that can re-key the persistent compile cache or
    change a config's cost: the package sources, bench.py itself (its
    EXTRA_CONFIGS kwargs define what each label measures), and the JAX
    version. History rows record it; the warm gate only trusts walls from
    rows whose fingerprint matches the running code, so ANY source edit —
    one model file, one kwargs bump — silently reverts to the cold static
    estimates instead of under-reserving a cold compile (the chip-wedging
    watchdog-SIGTERM scenario)."""
    global _FINGERPRINT_CACHE
    if _FINGERPRINT_CACHE is not None:
        return _FINGERPRINT_CACHE
    import hashlib
    h = hashlib.sha256()
    try:
        import jax
        h.update(jax.__version__.encode())
    except Exception:
        pass
    root = Path(__file__).resolve().parent
    files = sorted((root / "distributed_pytorch_training_tpu").rglob("*.py"))
    for f in [Path(__file__).resolve()] + files:
        try:
            h.update(str(f.relative_to(root)).encode())
            h.update(f.read_bytes())
        except Exception:
            h.update(b"<unreadable>")
    _FINGERPRINT_CACHE = h.hexdigest()[:16]
    return _FINGERPRINT_CACHE


def _history_rows(chip_kind: str, fingerprint: "str | None" = None):
    """Parsed history rows for one chip kind; a malformed line (truncated
    append) skips that line only, never the rows after it. With
    ``fingerprint``, only rows recorded by that exact code state are
    returned (the warm gate must never trust walls measured by different
    code — see _code_fingerprint)."""
    rows = []
    try:
        lines = HISTORY_PATH.read_text().splitlines()
    except Exception:
        return rows
    for line in lines:
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except Exception:
            continue
        if row.get("chip") != chip_kind:
            continue
        if fingerprint is not None and \
                row.get("code_fingerprint") != fingerprint:
            continue
        rows.append(row)
    return rows


def _measured_walls(chip_kind: str, fingerprint: "str | None" = None) -> dict:
    """{label: wall_s} of the most recent completed measurement per extra
    config on this chip kind (and code state), from the committed history."""
    walls = {}
    for row in _history_rows(chip_kind, fingerprint):
        for c in row.get("configs", []):
            if c.get("label") and c.get("wall_s"):
                walls[c["label"]] = c["wall_s"]
    return walls


def _headline_wall(chip_kind: str, per_device_batch: int):
    """COLD-compile reference wall for the headline config (resnet18 bf16 at
    this exact batch) on this chip kind, from the committed history: the MAX
    committed wall — cold walls strictly dominate warm ones, and the newest
    row may itself be a warm rerun (a last-row reference would then make
    warmth unprovable forever). Deliberately CROSS-fingerprint, unlike the
    extras' walls: a cold compile's magnitude is a property of chip+model,
    not of the exact code state, and a generation whose first headline ran
    warm (comment-only edit, cache still keyed) would otherwise have only
    warm walls on record — making warmth unprovable for that generation.
    Capped at 400s so one pathological committed run (long-window retries)
    cannot inflate the reference until a genuinely cold run (~226s observed)
    false-positives as warm."""
    wall = None
    for row in _history_rows(chip_kind):
        for c in row.get("configs", []):
            if (c.get("model") == "resnet18" and c.get("bf16")
                    and not c.get("label")
                    and c.get("per_device_batch") == per_device_batch
                    and c.get("wall_s")):
                wall = max(wall or 0.0, c["wall_s"])
    return min(wall, 400.0) if wall else None


def _est_for(label: str, static_est_s: float, walls: dict,
             warm_proven: bool) -> float:
    """Wall-cost gate for one extra config: the static estimate is sized for
    a COLD compile on the tunneled chip (the dominant term), so with a warm
    persistent compile cache it wildly over-reserves and the default-deadline
    driver run skips every extra. ``warm_proven`` must be DIRECT evidence
    from this very run — the headline (which always runs first) finishing in
    under half its committed historical wall time — not a filesystem guess:
    cache files on disk do not promise cache HITS (source or JAX changes
    re-key them), and an under-reserved cold compile overrunning the soft
    deadline is exactly the chip-wedging watchdog SIGTERM the static
    estimates exist to prevent. With warmth proven AND a committed measured
    wall for this label on this chip, gate on 1.5x measured + 60s (capped by
    the static estimate: history recorded cold must never RAISE the
    reservation)."""
    if warm_proven and label in walls:
        return min(static_est_s, walls[label] * 1.5 + 60.0)
    return static_est_s


def _record_history(result: dict) -> None:
    """Append the full result (all configs) to the committed provenance log
    so every README table row is regenerable from JSON in the repo."""
    try:
        HISTORY_PATH.parent.mkdir(parents=True, exist_ok=True)
        entry = dict(result)
        entry["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        entry["code_fingerprint"] = _code_fingerprint()
        with open(HISTORY_PATH, "a") as f:
            f.write(json.dumps(entry) + "\n")
        _log(f"bench: appended result to {HISTORY_PATH}")
    except Exception as e:
        _log(f"bench: history append failed (non-fatal): {e}")


def _bench(args):
    t_start = time.monotonic()
    # Telemetry stream for this bench invocation (before the backend is
    # touched, so the probe diagnostics land in it). Best-effort: a
    # read-only results dir must not cost the measurement.
    telemetry_path = None
    try:
        telemetry_path = str(HISTORY_PATH.parent / "telemetry_bench.jsonl")
        _telemetry.configure(telemetry_path,
                             meta={"entry": "bench.py",
                                   "batch_size": args.batch_size})
    except Exception as e:
        telemetry_path = None
        _log(f"bench: telemetry disabled ({e})")
    # Armed before anything can block on the tunnel (incl. the test hooks):
    # a dead relay turns every later RPC into an unbounded UNAVAILABLE
    # retry loop, so the watch must outlive every phase of the run.
    deathwatch = _start_relay_deathwatch()
    # Soft deadline: leave margin under the parent watchdog so we can skip
    # remaining configs and still print the headline JSON ourselves instead
    # of being SIGTERMed mid-measure with the result lost.
    soft_deadline = t_start + max(60, args.deadline - 90)

    def time_left():
        return soft_deadline - time.monotonic()

    hang = os.environ.get("DPT_BENCH_TEST_HANG")
    if hang:
        # test hook (tests/test_bench.py): simulate the observed failure
        # mode where jax.devices() blocks forever on a wedged tunnel — the
        # watchdog parent must still emit the error-JSON line. The
        # "after-json" variant hangs AFTER flushing a result (a teardown
        # hang): the parent must salvage that line, not report an error.
        if hang == "after-json":
            print(json.dumps({"metric": "test", "value": 42.0,
                              "unit": "samples/sec/chip",
                              "vs_baseline": None}), flush=True)
        time.sleep(10_000)
    # --only parsing happens before the backend is touched: an unknown label
    # must fail loudly without ever claiming the chip.
    only = None
    if args.only:
        only = {s.strip() for s in args.only.split(",") if s.strip()}
        known = {"headline", "fp32"} | {l for l, _, _, _ in EXTRA_CONFIGS}
        unknown = sorted(only - known)
        if unknown:
            print(json.dumps({
                "metric": "bench_only_filter", "value": 0.0,
                "unit": "samples/sec/chip", "vs_baseline": 0.0,
                "error": f"unknown --only labels {unknown}; known: "
                         f"{sorted(known)}"}))
            return 1
        if not only:
            print(json.dumps({
                "metric": "bench_only_filter", "value": 0.0,
                "unit": "samples/sec/chip", "vs_baseline": 0.0,
                "error": f"--only {args.only!r} selects nothing; known: "
                         f"{sorted(known)}"}))
            return 1
        if "fp32" in only:
            only.add("headline")  # vs_baseline is a ratio against headline

    try:
        # The init budget must leave the watchdog room to hear the error-
        # JSON: clamp it under the hard deadline regardless of flag values.
        init_budget = max(30, min(args.init_budget, args.deadline - 60))
        jax, devices, cache_enabled = init_backend_with_retry(
            init_budget_s=init_budget,
            probe_timeout_s=min(args.probe_timeout, init_budget))
    except Exception as e:
        # the bring-up failure's postmortem artifact: the probe-event ring
        # + cause, next to the history file (rc!=0 leaves a flight)
        _telemetry.flush_flight(cause=f"backend init failed: {e}",
                                detail="bench.py chip probe budget", rc=1)
        print(json.dumps({
            "metric": "resnet18_cifar10_train_throughput_bf16"
                      f"_b{args.batch_size}",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": f"backend init failed: {e}",
            # a wedged tunnel is environmental — the committed probe log
            # makes the failure attributable (who held the claim, since when)
            "chip_status_log": "CHIP_STATUS.md",
            "tunnel_status": _tunnel_status(),
            # ...and the last committed on-chip measurement still exists
            # even when this invocation can't reach the chip
            "last_good_committed_run": _last_good(),
        }))
        return 1

    # The tunneled single-chip client is the `axon` PJRT plugin — a real
    # (non-tunneled) TPU host never loads it, so "axon" in jax_platforms
    # plus a successful TPU init CONFIRMS the tunnel. Only then may the
    # watch auto-arm on the default relay ports without an explicit
    # DPT_RELAY_PORTS (the driver's plain `python bench.py` sets no env,
    # and a mid-run relay death there would otherwise hang the measured
    # configs into the watchdog SIGTERM). A plain platform=="tpu" gate
    # would reintroduce the default-port false-kill hazard on real pods.
    tunneled = "axon" in str(
        getattr(jax.config, "jax_platforms", None) or "")
    if deathwatch is None and devices[0].platform == "tpu" and tunneled:
        deathwatch = _start_relay_deathwatch(assume_tunneled=True)

    from distributed_pytorch_training_tpu.experiments.harness import (
        measure_config, measure_serving, measure_serving_continuous,
    )

    n_chips = jax.device_count()

    from distributed_pytorch_training_tpu.experiments.flops import (
        MeasurementError,
    )

    def run(name, **kw):
        _log(f"bench: === {name} {kw} === ({time_left():.0f}s left)")
        t0 = time.perf_counter()
        # exposed-comm split only where collectives exist (>1 chip); the
        # capture is try/except'd inside measure_config — a failed trace
        # never fails a bench row
        kw.setdefault("comm_trace", n_chips > 1)
        try:
            r = measure_config(name, repeats=args.repeats, **kw)
        except MeasurementError as e:
            # noisy tunnel windows: one escalation to much longer windows
            # before giving up on the config
            _log(f"bench: {name}: {e}; retrying with 5s windows")
            r = measure_config(name, repeats=args.repeats,
                               min_window_s=5.0, **kw)
        # wall_s lands in the history row: it is what makes the next run's
        # cost gate empirical instead of worst-case (_est_for)
        r["wall_s"] = round(time.perf_counter() - t0, 1)
        # the per-arm HLO contract verdict (analysis/hlo_rules.py) rides
        # every history row; a failing arm is loud in the log but still a
        # measurement — the contract gate is `analysis check`, not bench
        contract = (r.get("contracts") or {}).get("pass")
        c_str = {True: "ok", False: "VIOLATED", None: "unchecked"}[contract]
        _log(f"bench: {name} done in {r['wall_s']:.1f}s: "
             f"{r['samples_per_sec_chip']:.0f} samples/s/chip, "
             f"mfu={r['mfu_pct']}%, contracts={c_str}")
        sb = r.get("save_blocked_ms")
        if sb and "error" not in sb:
            _log(f"bench: {name} checkpoint stall A/B: sync "
                 f"{sb['sync_blocked_ms']}ms -> async "
                 f"{sb['async_blocked_ms']}ms blocked (snapshot "
                 f"{sb['snapshot_ms']}ms, bg write {sb['write_ms']}ms)")
        if contract is False:
            _log(f"bench: {name} CONTRACT VIOLATIONS: "
                 f"{r['contracts']['violations']}")
        elif contract is None:
            # a broken CHECKER must be distinguishable from a benign skip
            why = (r.get("contracts") or {}).get(
                "error", "no contracts recorded")
            _log(f"bench: {name} contract checker did not run: {why}")
        return r

    def run_serving(label, name, **skw):
        """One serving offered-load row (the `serving` marker arms): routes
        to measure_serving (iteration-granular) or
        measure_serving_continuous (token-granular slot pool) and logs the
        latency/throughput shape a serving row has instead of run()'s
        samples/sec/chip. recompiles_after_warmup != 0 is loud here and a
        hard exit in `serving bench` — bench records it as a measurement."""
        kind = skw.pop("kind", "token")
        _log(f"bench: === {label} serving/{kind} {skw} === "
             f"({time_left():.0f}s left)")
        t0 = time.perf_counter()
        if kind == "iteration":
            r = measure_serving(name, **skw)
        else:
            r = measure_serving_continuous(name, **skw)
        r["wall_s"] = round(time.perf_counter() - t0, 1)
        contract = (r.get("contracts") or {}).get("pass")
        c_str = {True: "ok", False: "VIOLATED", None: "unchecked"}[contract]
        _log(f"bench: {label} done in {r['wall_s']:.1f}s: "
             f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms "
             f"{r.get('tokens_per_sec', 0.0):.1f} tok/s, "
             f"recompiles_after_warmup={r['recompiles_after_warmup']}, "
             f"contracts={c_str}")
        if r.get("ttft_p99_ms") is not None:
            _log(f"bench: {label} ttft p50={r['ttft_p50_ms']}ms "
                 f"p99={r['ttft_p99_ms']}ms")
        if r.get("kv_bytes_ratio") is not None:
            _log(f"bench: {label} paged KV {r['paged_kv_bytes']}B vs dense "
                 f"{r['dense_kv_bytes']}B ({r['kv_bytes_ratio']}x)")
        for rep, stats in (r.get("per_replica") or {}).items():
            _log(f"bench: {label} replica {rep}: served={stats['served']} "
                 f"alive={stats['alive']} p50={stats['p50_ms']}ms "
                 f"p99={stats['p99_ms']}ms")
        if r["recompiles_after_warmup"]:
            _log(f"bench: {label} RECOMPILED after warmup "
                 f"({r['recompiles_after_warmup']}x) — the zero-recompile "
                 "census is broken")
        if contract is False:
            _log(f"bench: {label} CONTRACT VIOLATIONS: "
                 f"{r['contracts']['violations']}")
        return r

    def result_dict(headline, fp32, extras, skipped):
        return {
            "metric":
                f"resnet18_cifar10_train_throughput_bf16_b{args.batch_size}",
            "value": headline["samples_per_sec_chip"],
            "unit": "samples/sec/chip",
            # True AMP curve: bf16 vs HIGHEST-precision fp32, same chip.
            "vs_baseline": (round(headline["samples_per_sec"]
                                  / fp32["samples_per_sec"], 3)
                            if fp32 else None),
            "per_device_batch": args.batch_size,
            "n_chips": n_chips,
            "chip": devices[0].device_kind,
            "mfu_pct": headline["mfu_pct"],
            "chip_peak_tflops_bf16": headline["chip_peak_tflops_bf16"],
            "tflops_per_sec": headline["tflops_per_sec"],
            "fp32_samples_per_sec_chip": (fp32["samples_per_sec_chip"]
                                          if fp32 else None),
            "fp32_true_precision": fp32 is not None,
            "configs": [c for c in [headline, fp32] + extras if c],
            "configs_skipped": skipped,
            "bench_seconds": round(time.monotonic() - t_start, 1),
            # where this invocation's typed event stream (probe events,
            # save_blocked spans, wire counters) landed — `telemetry
            # summary <path>` reads it (ISSUE 8)
            "telemetry_path": telemetry_path,
        }

    # Headline: ResNet-18/CIFAR-10 (the reference's workload) in bf16 FIRST —
    # an fp32-arm failure (bigger memory footprint under HIGHEST precision)
    # must degrade vs_baseline to null, not forfeit the headline number.
    err = None
    headline = fp32 = None
    if only is None or "headline" in only:
        try:
            # ckpt_ab: the headline row carries save_blocked_ms — the
            # sync-vs-async checkpoint stall A/B on the real state (two
            # throwaway saves; cheap at resnet18 size, and only here so
            # the big-model arms don't pay double disk writes)
            headline = run("resnet18", per_device_batch=args.batch_size,
                           steps=args.steps, bf16=True, ckpt_ab=True)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            _log("bench: headline config failed:\n" + traceback.format_exc())
    if headline is not None:
        # Provisional line: a config can overrun the soft-deadline check
        # (compile + the MeasurementError long-window retry are unbounded),
        # and the parent SIGTERMs at the hard deadline. The already-measured
        # headline must be on the pipe before that can happen; the parent
        # salvages the LAST flushed JSON line.
        print(json.dumps(result_dict(headline, None, [], ["<provisional>"])),
              flush=True)
    extras = []
    skipped = []

    # fp32 arm cost estimate: measured 150s on the tunneled v5e (no extra
    # compile of the data path, but HIGHEST-precision matmuls are ~4x the
    # step time); 300s keeps the same never-SIGTERMed margin as the extras.
    # When the arm is wanted but the budget is gone, that is recorded in
    # configs_skipped — an explicitly requested --only fp32 must not vanish
    # silently from an rc=0 result.
    want_fp32 = headline is not None and (only is None or "fp32" in only)
    if want_fp32 and time_left() > 300:
        try:
            fp32 = run("resnet18", per_device_batch=args.batch_size,
                       steps=args.steps, bf16=False)
            print(json.dumps(result_dict(headline, fp32, [],
                                         ["<provisional>"])), flush=True)
        except Exception:
            _log("bench: fp32 baseline arm failed (vs_baseline -> null):\n"
                 + traceback.format_exc())
    elif want_fp32:
        skipped.append("fp32")
        _log("bench: skipped fp32 arm — remaining soft budget "
             f"({time_left():.0f}s) is under its 300s estimate")

    def chunk_result(provisional=False):
        """Result line for a chunked --only run without the headline: report
        the first selected config; every config is in `configs`. Provisional
        flushes carry the "<provisional>" marker so a salvaged line resolves
        to the labels that actually never ran (_resolve_provisional_marker)
        instead of committing `configs_skipped: []` for a truncated chunk."""
        first = extras[0]
        if str(first.get("mode", "")).startswith("serving"):
            # serving rows are latency rows: tokens/sec, no MFU
            metric = f"{first['label']}_serving_tokens_per_sec"
            value, unit = first.get("tokens_per_sec", 0.0), "tokens/sec"
        else:
            prec = "bf16" if first.get("bf16") else "fp32"
            metric = f"{first['label']}_train_throughput_{prec}"
            value = first["samples_per_sec_chip"]
            unit = "samples/sec/chip"
        return {
            "metric": metric,
            "value": value,
            "unit": unit,
            "vs_baseline": None,
            "n_chips": n_chips,
            "chip": devices[0].device_kind,
            "mfu_pct": first.get("mfu_pct"),
            "only": sorted(only),
            "configs": extras,
            "configs_skipped": (skipped + ["<provisional>"] if provisional
                                else skipped),
            "bench_seconds": round(time.monotonic() - t_start, 1),
        }

    # An explicit --only selection overrides --quick: a requested config must
    # run (or fail loudly), never be silently dropped by an unrelated flag.
    if args.quick and only is not None:
        _log("bench: --only given; ignoring --quick for the selected labels")
    if (headline is not None or only) and (not args.quick or only is not None):
        # The rest of the BASELINE matrix, single-chip (BASELINE.json:9-12):
        # ResNet-50 + ViT-B/16 on ImageNet shapes, GPT-2 124M causal LM,
        # BERT-base MLM @ 512. Each entry is (label, model, est_s, kwargs):
        # est_s is a conservative wall-cost estimate on the tunneled v5e
        # (compile dominates; measured 2026-07-31: headline b4096 took 226s,
        # its fp32 arm 150s, and resnet50@224 was still compiling at +370s
        # when the watchdog fired). A config only STARTS when the remaining
        # soft budget covers its estimate: the inner must always finish on
        # its own and release the chip by exiting — a watchdog SIGTERM of a
        # chip-holding process wedged the tunnel for hours, twice
        # (CHIP_STATUS.md). Under the default 840s driver deadline the
        # estimates deliberately leave no room for extras after the
        # headline+fp32 pair; full-matrix provenance comes from chunked
        # `--only` runs committed to bench_history.jsonl.
        # Warmth must be PROVEN by this run, not guessed from disk: the
        # headline ran first, so a headline wall under half its committed
        # historical wall means its compile hit the cache — and the extras'
        # entries live in the same cache generation.
        fp = _code_fingerprint()
        hist_wall = _headline_wall(devices[0].device_kind, args.batch_size)
        warm_proven = bool(
            cache_enabled and headline is not None and hist_wall
            and headline.get("wall_s", hist_wall) < 0.5 * hist_wall)
        walls = _measured_walls(devices[0].device_kind, fingerprint=fp)
        if warm_proven and walls:
            _log(f"bench: cache warmth proven (headline "
                 f"{headline['wall_s']:.0f}s vs historical {hist_wall:.0f}s);"
                 f" empirical wall gates for {sorted(walls)}")
        for label, name, est_s, kw in EXTRA_CONFIGS:
            if only is not None and label not in only:
                continue
            if time_left() < _est_for(label, est_s, walls, warm_proven):
                skipped.append(label)
                continue
            try:
                if "serving" in kw:
                    r = run_serving(label, name, **dict(kw["serving"]))
                else:
                    # bf16 by default; a config may override (fp32 arms)
                    r = run(name, **{"bf16": True, **kw})
                r["label"] = label
                extras.append(r)
                # Flush a provisional line after EVERY completed config so a
                # deadline SIGTERM or teardown hang can't lose already-
                # measured work (the parent salvages the last flushed JSON
                # line) — in chunked runs and full-matrix runs alike.
                if headline is None:
                    print(json.dumps(chunk_result(provisional=True)),
                          flush=True)
                else:
                    print(json.dumps(result_dict(
                        headline, fp32, extras,
                        skipped + ["<provisional>"])), flush=True)
            except Exception:
                _log(f"bench: extra config {label} failed (continuing):\n"
                     + traceback.format_exc())
        by_label = {r.get("label"): r for r in extras}
        s_it = by_label.get("serving_iter_gpt2")
        s_tok = by_label.get("serving_token_gpt2")
        if s_it and s_tok:
            # the continuous-batching claim as a measured sentence: same
            # offered load, same shapes, token-granular vs iteration-
            # granular (the history rows carry the full distributions)
            win = (s_tok.get("tokens_per_sec", 0.0)
                   > s_it.get("tokens_per_sec", 0.0)
                   and s_tok["p99_ms"] < s_it["p99_ms"])
            _log("bench: serving A/B: token-granular "
                 f"{s_tok.get('tokens_per_sec', 0.0):.1f} tok/s "
                 f"p99={s_tok['p99_ms']}ms vs iteration-granular "
                 f"{s_it.get('tokens_per_sec', 0.0):.1f} tok/s "
                 f"p99={s_it['p99_ms']}ms -> "
                 + ("token-granular wins both"
                    if win else "NO WIN — continuous batching regressed"))
        if skipped:
            _log(f"bench: skipped {skipped} — remaining soft budget "
                 f"({time_left():.0f}s of the {args.deadline}s watchdog) is "
                 "under their cost estimates; exiting cleanly instead of "
                 "risking a SIGTERM while holding the chip")

    if headline is None and extras:
        result = chunk_result()
        _record_history(result)
        print(json.dumps(result), flush=True)
        return 0

    if headline is None and only:
        # A chunked run whose every selected config failed or was skipped:
        # name the requested labels, don't blame the never-run headline.
        print(json.dumps({
            "metric": "bench_only_chunk", "value": 0.0,
            "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": f"no selected config produced a measurement "
                     f"(requested {sorted(only)}, skipped {skipped})"
                     + (f"; headline failed: {err}" if err else ""),
        }), flush=True)
        return 1

    if headline is None:
        print(json.dumps({
            "metric": f"resnet18_cifar10_train_throughput_bf16"
                      f"_b{args.batch_size}",
            "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": 0.0,
            "error": err or "unknown",
            "configs_skipped": skipped,
        }), flush=True)
        return 1

    result = result_dict(headline, fp32, extras, skipped)
    _record_history(result)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
