"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric (BASELINE.json:2): training throughput, samples/sec/chip, for
the ResNet-18/CIFAR-10 config (config 1, the reference's own workload,
/root/reference/train_ddp.py) in bf16, measured on whatever devices are
present (one real TPU chip under the driver).

The reference publishes no numbers (`"published": {}`, BASELINE.json:13), so
`vs_baseline` reports the bf16-vs-fp32 speedup on identical hardware — the
"AMP-vs-FP32 speedup curve" the reference's README promises but never fills
in (README.md:31, :35).

Usage: python bench.py [--model resnet18] [--batch-size 128] [--steps 30]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax
import jax.numpy as jnp
import numpy as np


def bench_config(model_name: str, per_device_batch: int, steps: int,
                 bf16: bool, image_hw: int = 32, num_classes: int = 10) -> float:
    """Compiled-step throughput (global samples/s) for one precision."""
    from distributed_pytorch_training_tpu.models import get_model
    from distributed_pytorch_training_tpu.parallel import build_mesh, shard_batch
    from distributed_pytorch_training_tpu.parallel.mesh import batch_shard_count
    from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
    from distributed_pytorch_training_tpu.training.optim import sgd
    from distributed_pytorch_training_tpu.training.tasks import (
        ImageClassificationTask,
    )
    from distributed_pytorch_training_tpu.data import CIFAR10_MEAN, CIFAR10_STD

    mesh = build_mesh()
    global_batch = per_device_batch * batch_shard_count(mesh)
    dtype = jnp.bfloat16 if bf16 else jnp.float32

    model = get_model(model_name, num_classes=num_classes, dtype=dtype)
    task = ImageClassificationTask(mean=CIFAR10_MEAN, std=CIFAR10_STD,
                                   augment=True, compute_dtype=dtype)
    trainer = Trainer(task, mesh, TrainConfig(seed=0, bf16=bf16))
    tx = sgd(0.1, momentum=0.9, weight_decay=5e-4)
    state = trainer.init_state(
        model, np.zeros((1, image_hw, image_hw, 3), np.float32), tx,
        jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    batch = shard_batch({
        "image": rng.randint(0, 256, (global_batch, image_hw, image_hw, 3)).astype(np.uint8),
        "label": rng.randint(0, num_classes, global_batch).astype(np.int32),
        "weight": np.ones(global_batch, np.float32),
    }, mesh)
    key = jax.random.PRNGKey(0)

    # Warmup: compile + 3 steps.
    for _ in range(3):
        state, metrics = trainer._train_step(state, batch, key)
    jax.block_until_ready(metrics["weight"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer._train_step(state, batch, key)
    jax.block_until_ready(metrics["weight"])
    dt = time.perf_counter() - t0
    return global_batch * steps / dt


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18")
    p.add_argument("--batch-size", default=128, type=int)
    p.add_argument("--steps", default=30, type=int)
    args = p.parse_args(argv)

    n_chips = jax.device_count()
    fp32 = bench_config(args.model, args.batch_size, args.steps, bf16=False)
    bf16 = bench_config(args.model, args.batch_size, args.steps, bf16=True)

    result = {
        "metric": f"{args.model}_cifar10_train_throughput_bf16",
        "value": round(bf16 / n_chips, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(bf16 / fp32, 3),  # bf16-vs-fp32 speedup (AMP parity curve)
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
