"""The inference engine: manifest-verified checkpoints -> served tokens.

This is the serving half of the training stack, built from parts that
already exist rather than a parallel implementation:

* **Weights** come through ``training/checkpoint.py::restore_latest`` —
  the SAME manifest-verified restore training resumes from, against a
  template built by ``Trainer.init_state`` (so replicated, zero1, and
  fsdp-flat checkpoint layouts all load; fsdp-flat unflattens through the
  trainer's own template). The engine records which label it serves and
  its manifest ``tree_digest`` — served bytes are provenanced.
* **Shapes** come from the bucket ladder (``data/pack.py``): one compiled
  program per (rows, bucket) pair, assembled once and reused for every
  request — the zero-recompiles-within-a-bucket contract the engine's
  ``compiles`` counter lets tests pin (the compile-count census).
* **Numerics** are the eval forward's. fp32 serving is BITWISE the eval
  forward: prefill logits are literally the same computation (the cache
  fill is a side output), and the KV-cache decode step is pinned
  bitwise-equal to the full-context forward on the CPU mesh
  (models/layers.py ``decode_dot_product_attention`` explains the one
  formulation choice that makes this true). int8 serving reuses the
  gradient-wire codec grid (per-row max-abs scales, ``max(amax,1e-30)/127``,
  round/clip — ``parallel/grad_sync.py``) on the weights, dequantized at
  the matmul inputs inside the compiled forward (XLA fuses the scale
  multiply into the consumer): at-rest weight bytes drop ~4x, and the
  error model is the wire codec's one-shot bound (PARITY.md).

The decode hot loop (``generate``) is host-dispatch only: every per-step
value (next token, positions) chains device-to-device through the compiled
step, the KV cache is DONATED (``donate_argnums``) so each step updates in
place, and the single host fetch happens after the last step. The
``no-host-sync-in-decode`` AST rule and the ``serving_decode`` HLO contract
(analysis/) keep it that way.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..data.pack import bucket_for, pack_token_rows, unpack_token_rows
from ..parallel.mesh import batch_shard_count
from ..parallel.sharding import batch_sharding, replicated, shard_batch
from .batching import Result

SERVE_DTYPES = ("fp32", "bf16", "int8")


@dataclasses.dataclass
class ServeConfig:
    """Engine knobs (CLI-facing; serving/__main__.py mirrors them)."""

    # Prompt-length bucket ladder (sorted ascending). One compiled
    # prefill+decode pair exists per rung; a request pays padding at most
    # to the next rung and NEVER a compile.
    buckets: Tuple[int, ...] = (32, 64, 128)
    # Batch rows per engine cycle — the static row dimension of every
    # compiled program. Must divide by the mesh's batch-shard count.
    rows: int = 8
    # Greedy-decode budget per request; the KV cache is sized
    # bucket + max_new_tokens.
    max_new_tokens: int = 16
    # fp32: bitwise the eval forward. bf16: the model's compute dtype
    # (build the model with dtype=bf16 — the --amp convention). int8:
    # weights quantized at rest through the wire-codec grid, dequantized
    # at the matmul inputs in-kernel.
    serve_dtype: str = "fp32"
    pad_id: int = 0
    # int8: only quantize leaves with >= this many elements (tiny tensors
    # — biases, layernorms — are all error and no memory win).
    quantize_min_elements: int = 4096

    def __post_init__(self):
        if self.serve_dtype not in SERVE_DTYPES:
            raise ValueError(f"serve_dtype {self.serve_dtype!r} is not one "
                             f"of {SERVE_DTYPES}")
        if not self.buckets:
            raise ValueError("at least one bucket is required")
        self.buckets = tuple(sorted(int(b) for b in self.buckets))
        if self.rows < 1:
            raise ValueError(f"rows must be >= 1, got {self.rows}")


@flax.struct.dataclass
class QuantizedLeaf:
    """An int8-at-rest parameter leaf: s8 codes in the original shape plus
    one fp32 scale per trailing-axis row (the wire codec's per-row grid,
    ``grad_sync._quantize_int8_rows``). Dequantizes as ``q * scale`` —
    a multiply XLA fuses into the consuming matmul/gather."""

    q: jnp.ndarray
    scale: jnp.ndarray


def quantize_params(params: Any, min_elements: int = 4096,
                    fused: Optional[bool] = None) -> Any:
    """int8-quantize the weight tree for serving: every leaf with ndim >= 2
    and >= ``min_elements`` elements becomes a `QuantizedLeaf` (per-row
    scales over the trailing axis, leading axes collapsed — embeddings get
    one scale per vocab row, kernels one per input row); everything else
    (biases, layernorm scales, tiny tensors) stays exact fp32. The grid is
    the gradient-wire codec's, by construction: same absmax, same
    ``max(amax, 1e-30) * (1/127)`` scale, same round/clip — so the serve
    error model IS the wire codec's one-shot bound (PARITY.md)."""
    from ..parallel.grad_sync import _quantize_int8_rows

    def one(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim < 2 or leaf.size < min_elements:
            return leaf
        rows = leaf.astype(jnp.float32).reshape(-1, leaf.shape[-1])
        q, scales = _quantize_int8_rows(rows, fused=fused)
        return QuantizedLeaf(
            q=q.reshape(leaf.shape),
            scale=scales.reshape(leaf.shape[:-1]))

    return jax.tree_util.tree_map(one, params)


def dequantize_params(served: Any, like_dtype=jnp.float32) -> Any:
    """Inverse of `quantize_params`, traced inside the compiled forwards:
    codes x per-row scales, cast to the parameter dtype. Exact-fp32 leaves
    pass through untouched."""

    def one(leaf):
        if isinstance(leaf, QuantizedLeaf):
            return (leaf.q.astype(jnp.float32)
                    * leaf.scale[..., None]).astype(like_dtype)
        return leaf

    return jax.tree_util.tree_map(
        one, served, is_leaf=lambda x: isinstance(x, QuantizedLeaf))


def int8_weight_bytes(served: Any) -> Dict[str, int]:
    """At-rest byte accounting of a served tree: {quantized, exact} bytes —
    the serving analogue of grad_sync's wire accounting."""
    quantized = exact = 0
    for leaf in jax.tree_util.tree_leaves(
            served, is_leaf=lambda x: isinstance(x, QuantizedLeaf)):
        if isinstance(leaf, QuantizedLeaf):
            quantized += leaf.q.size + 4 * leaf.scale.size
        else:
            exact += leaf.size * leaf.dtype.itemsize
    return {"quantized_bytes": int(quantized), "exact_bytes": int(exact)}


class InferenceEngine:
    """Compiled batched inference over one (model, mesh, config) triple.

    ``serve_tokens`` is the request-facing entry (the batching layer calls
    it); ``lower_prefill``/``lower_decode`` expose the lowered steps to the
    analysis contract checker; ``compiles`` counts every XLA compile the
    engine ever triggered — the census the zero-recompile contract reads.
    """

    def __init__(self, model, mesh, config: ServeConfig, params,
                 batch_stats: Any = None, rules=None):
        from ..parallel.mesh import MODEL

        self.model = model
        self.mesh = mesh
        self.config = config
        n_shards = batch_shard_count(mesh)
        model_n = dict(mesh.shape).get(MODEL, 1)
        if model_n > 1 and rules is None:
            raise ValueError(
                f"mesh has model={model_n} but the engine was given no "
                "partition rules — serving shards weights over the model "
                "axis via the model's GSPMD rules (tp_fsdp_rules); pass "
                "rules= (harness.build_serving_engine does)")
        if model_n > 1 and config.serve_dtype == "int8":
            raise ValueError(
                "--serve-dtype int8 on a model-axis mesh is not supported "
                "yet: the per-row quantized codes carry their own layout "
                "(serve fp32/bf16 with --mesh model>1, or int8 on a 1-D "
                "mesh)")
        self._validate_rows(n_shards)
        # three serve modes: causal LM (prefill + KV-cache decode), token
        # batch (bert — one bucketed forward, logits/embeddings out), image
        # batch (resnet/vit — fixed-shape forward via serve_images)
        self.is_lm = hasattr(model, "init_cache")
        self.is_token = hasattr(model, "vocab_size")
        top = max(config.buckets) + config.max_new_tokens
        if self.is_lm and top > model.max_position:
            raise ValueError(
                f"largest bucket + max_new_tokens = {top} exceeds the "
                f"model's max_position {model.max_position}")
        self._batch_stats = batch_stats if batch_stats is not None else {}
        rep = replicated(mesh)
        if config.serve_dtype == "int8":
            served = quantize_params(
                params, min_elements=config.quantize_min_elements)
        else:
            served = jax.tree_util.tree_map(jnp.asarray, params)
        if model_n > 1:
            # multi-chip serving of big models (ISSUE 13 satellite): the
            # served weights shard per the model's GSPMD rules — XLA
            # inserts the TP collectives into the compiled forwards;
            # per-device weight residency divides by the model axis
            from ..parallel.sharding import shard_pytree

            self._served = shard_pytree(served, mesh, rules)
        else:
            self._served = jax.device_put(served, rep)
        if jax.tree_util.tree_leaves(self._batch_stats):
            self._batch_stats = jax.device_put(self._batch_stats, rep)
        self._param_dtype = jnp.result_type(
            jax.tree_util.tree_leaves(params)[0])
        # compiled executables, keyed ("prefill"|"decode"|"forward", bucket)
        self._compiled: Dict[Tuple[str, int], Any] = {}
        self.compiles = 0
        # provenance of the served weights (from_checkpoint fills this)
        self.checkpoint_info: Optional[dict] = None

    def _validate_rows(self, n_shards: int) -> None:
        """Dense engine: the row dimension shards over the mesh's batch
        shards, so rows must divide. The slot engine overrides (its state
        is replicated — slot count is a scheduling knob, not a layout)."""
        if self.config.rows % n_shards:
            raise ValueError(
                f"rows={self.config.rows} must divide over the mesh's "
                f"{n_shards} batch shards — every compiled program's row "
                "dimension is sharded over them")

    # -- checkpoint loading -------------------------------------------------

    @classmethod
    def from_checkpoint(cls, ckpt_dir: str, model, mesh,
                        config: ServeConfig, tx, sample_input,
                        train_config=None, rules=None,
                        task=None) -> "InferenceEngine":
        """Restore the newest manifest-verified checkpoint and build an
        engine serving it. ``tx`` and ``train_config`` reconstruct the
        checkpoint's TrainState TEMPLATE (the restore contract: orbax needs
        the full structure — same optimizer family and the same
        zero1/fsdp/wire mode flags the training run used; the CLI exposes
        them). Torn checkpoints are skipped exactly as a training resume
        would skip them; serving a checkpoint nobody could resume from is
        the same bug twice."""
        from ..training import TrainConfig, Trainer
        from ..training.checkpoint import CheckpointManager
        from ..training.tasks import LanguageModelingTask

        train_config = train_config or TrainConfig(seed=0)
        trainer = Trainer(task or LanguageModelingTask(), mesh, train_config,
                          rules=rules)
        template = trainer.init_state(model, sample_input, tx,
                                      jax.random.PRNGKey(0))
        ckpt = CheckpointManager(ckpt_dir)
        try:
            try:
                restored = ckpt.restore_latest(template)
            except (ValueError, TypeError) as e:
                # orbax's structure-mismatch errors dump the whole tree;
                # name the actual knob before the dump scrolls it away
                raise ValueError(
                    "checkpoint restore failed against the serving "
                    "template — the template's TrainState structure must "
                    "match the training run's exactly: same optimizer "
                    "chain (--optimizer/--momentum/--weight-decay; "
                    "train.py's default is sgd) and the same "
                    "--zero1/--fsdp-explicit/--wire-dtype/--bucket-cap-mb "
                    f"flags. Original error: {type(e).__name__}: {e}"
                ) from e
            if restored is None:
                raise FileNotFoundError(
                    f"no restorable checkpoint under {ckpt_dir} "
                    f"(skipped as torn: {ckpt.last_skipped or 'none'})")
            state, _epoch, _step_in_epoch = restored
            label = ckpt.last_restored
            manifest = ckpt.manifest(label) if label is not None else None
            params = (trainer._fsdp_unflatten(state.params)
                      if trainer._fsdp else state.params)
            engine = cls(model, mesh, config, params,
                         batch_stats=state.batch_stats, rules=rules)
            engine.checkpoint_info = {
                "dir": str(ckpt_dir),
                "label": label,
                "step": int(jax.device_get(state.step)),
                "tree_digest": (manifest or {}).get("tree_digest"),
                "verified": manifest is not None,
            }
            return engine
        finally:
            ckpt.close()

    # -- compiled programs --------------------------------------------------

    def _apply_vars(self, params) -> dict:
        variables = {"params": params}
        if jax.tree_util.tree_leaves(self._batch_stats):
            variables["batch_stats"] = self._batch_stats
        return variables

    def _dequant(self, served):
        return dequantize_params(served, like_dtype=self._param_dtype)

    def _make_prefill(self, bucket: int) -> Callable:
        rows, cache_len = self.config.rows, bucket + self.config.max_new_tokens

        def prefill(served, ids, lengths):
            params = self._dequant(served)
            cache0 = self.model.init_cache(rows, cache_len)
            logits, cache = self.model.apply(
                self._apply_vars(params), ids, train=False, cache=cache0)
            # greedy first token from the last REAL prompt position; filler
            # rows (length 0) read row 0 — their outputs are never unpacked
            last_pos = jnp.maximum(lengths - 1, 0)
            last = jnp.take_along_axis(
                logits, last_pos[:, None, None], axis=1)[:, 0]
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return logits, last, cache, tok, lengths.astype(jnp.int32)

        return prefill

    def _make_decode(self, bucket: int) -> Callable:
        def decode(served, cache, tok, positions):
            params = self._dequant(served)
            logits, new_cache = self.model.apply(
                self._apply_vars(params), tok[:, None], train=False,
                cache=cache, cache_positions=positions)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            return new_cache, nxt, positions + 1

        return decode

    def _make_forward(self, bucket: int) -> Callable:
        def forward(served, ids, lengths):
            params = self._dequant(served)
            logits = self.model.apply(
                self._apply_vars(params), ids, train=False)
            last_pos = jnp.maximum(lengths - 1, 0)
            last = jnp.take_along_axis(
                logits, last_pos[:, None, None], axis=1)[:, 0]
            return logits, last

        return forward

    def _aval(self, shape, dtype) -> jax.ShapeDtypeStruct:
        """Input aval with the batch sharding over the leading (row) dim —
        AOT compilation binds shardings, and the call sites always pass
        `shard_batch`-placed arrays."""
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=batch_sharding(self.mesh, len(shape)))

    def _cache_avals(self, bucket: int):
        cache_len = bucket + self.config.max_new_tokens
        head_dim = self.model.hidden_dim // self.model.num_heads
        z = self._aval(
            (self.config.rows, cache_len, self.model.num_heads, head_dim),
            self.model.dtype)
        return tuple((z, z) for _ in range(self.model.depth))

    def _out_batch_shardings(self, tree_like):
        """Pin every output's sharding to batch-over-rows so the prefill
        outputs land exactly in the layout the decode step was compiled
        for (AOT executables reject sharding mismatches at call time)."""
        return jax.tree_util.tree_map(
            lambda x: batch_sharding(self.mesh, len(x.shape)), tree_like)

    def lower_prefill(self, bucket: int):
        """The lowered (uncompiled) prefill step — the contract checker's
        read, and the AOT source `_executable` compiles."""
        rows = self.config.rows
        ids = self._aval((rows, bucket), jnp.int32)
        lengths = self._aval((rows,), jnp.int32)
        vocab = self.model.padded_vocab
        outs = (self._aval((rows, bucket, vocab), jnp.float32),   # logits
                self._aval((rows, vocab), jnp.float32),           # last
                self._cache_avals(bucket),                        # cache
                self._aval((rows,), jnp.int32),                   # tok
                self._aval((rows,), jnp.int32))                   # positions
        return jax.jit(
            self._make_prefill(bucket),
            out_shardings=self._out_batch_shardings(outs),
        ).lower(self._served, ids, lengths)

    def lower_decode(self, bucket: int):
        """The lowered decode step. The cache argument is DONATED: the step
        updates the (rows, bucket + max_new, heads, head_dim) k/v buffers
        in place — without donation every decode step would copy the whole
        cache (the `serving_decode` HLO contract pins the alias table)."""
        if not self.is_lm:
            raise ValueError("decode exists only for causal-LM models")
        rows = self.config.rows
        cache = self._cache_avals(bucket)
        tok = self._aval((rows,), jnp.int32)
        pos = self._aval((rows,), jnp.int32)
        outs = (cache, tok, pos)
        return jax.jit(
            self._make_decode(bucket), donate_argnums=(1,),
            out_shardings=self._out_batch_shardings(outs),
        ).lower(self._served, cache, tok, pos)

    def _executable(self, kind: str, bucket: int):
        key = (kind, bucket)
        if key not in self._compiled:
            if kind == "prefill":
                lowered = self.lower_prefill(bucket)
            elif kind == "decode":
                lowered = self.lower_decode(bucket)
            else:
                rows = self.config.rows
                vocab = self.model.padded_vocab
                outs = (self._aval((rows, bucket, vocab), jnp.float32),
                        self._aval((rows, vocab), jnp.float32))
                lowered = jax.jit(
                    self._make_forward(bucket),
                    out_shardings=self._out_batch_shardings(outs),
                ).lower(self._served,
                        self._aval((rows, bucket), jnp.int32),
                        self._aval((rows,), jnp.int32))
            # the cold-vs-warm instrument: with the persistent compile
            # cache on (DPT_COMPILE_CACHE / enable_persistent_compile_
            # cache), a restarted/autoscaled engine's spans collapse from
            # full-compile to cache-load time — the restart-downtime win,
            # measurable per program in the stream
            # attr named `program`, not `kind`: the recorder's emit() owns
            # the `kind` parameter (event kind), attrs must not shadow it
            with telemetry.span("compile", program=kind, bucket=bucket):
                self._compiled[key] = lowered.compile()
            self.compiles += 1
        return self._compiled[key]

    def warmup(self) -> int:
        """Compile every bucket's programs up front (the bench does this
        before the timed window); returns the engine's compile count.
        Image models compile lazily in `serve_images` (their one shape is
        the image's, not a bucket's)."""
        if self.is_token:
            for b in self.config.buckets:
                self._executable("prefill" if self.is_lm else "forward", b)
                if self.is_lm:
                    self._executable("decode", b)
        return self.compiles

    def kv_cache_bytes(self, bucket: Optional[int] = None) -> int:
        """At-rest bytes of this engine's dense KV cache at ``bucket``
        (default: the top rung — the engine's HBM ceiling). The baseline
        the paged engine's >= 3x int8 cut is measured against
        (models/layers.dense_kv_bytes; bench serving records both)."""
        from ..models.layers import dense_kv_bytes

        if not self.is_lm:
            return 0
        b = max(self.config.buckets) if bucket is None else int(bucket)
        return dense_kv_bytes(
            self.config.rows, b + self.config.max_new_tokens,
            self.model.num_heads, self.model.hidden_dim // self.model.num_heads,
            self.model.depth,
            itemsize=jnp.dtype(self.model.dtype).itemsize)

    # -- serving ------------------------------------------------------------

    def serve_tokens(self, seqs: Sequence[np.ndarray],
                     max_new_tokens: Optional[int] = None,
                     return_prompt_logits: bool = False) -> List[Result]:
        """Serve one ragged group of token prompts: bucket, pack, prefill,
        greedy-decode, unpack. All prompts must fit ONE bucket (the
        batching layer groups by bucket before calling)."""
        if not seqs:
            return []
        if not self.is_token:
            raise ValueError(
                "serve_tokens needs a token model (gpt2/bert); image "
                "models serve through serve_images")
        cfg = self.config
        bucket = max(bucket_for(len(s), cfg.buckets) for s in seqs)
        ids, lengths, _w = pack_token_rows(seqs, bucket, cfg.rows,
                                           pad_id=cfg.pad_id)
        batch_ids = shard_batch(ids, self.mesh)
        batch_len = shard_batch(lengths, self.mesh)

        if not self.is_lm:
            t0 = time.perf_counter()
            fwd = self._executable("forward", bucket)
            logits, last = fwd(self._served, batch_ids, batch_len)
            # the (rows, bucket, vocab) per-position logits cross to the
            # host only when asked for — the default embedding serve
            # fetches just the (rows, vocab) last-position rows
            fetched = jax.device_get((last, logits) if return_prompt_logits
                                     else (last,))
            last_h = fetched[0]
            prefill_s = time.perf_counter() - t0
            telemetry.span_event("prefill", prefill_s, bucket=bucket,
                                 rows=len(seqs))
            per_req = (unpack_token_rows(fetched[1], lengths, len(seqs))
                       if return_prompt_logits else [None] * len(seqs))
            return [Result(tokens=np.zeros((0,), np.int32),
                           last_logits=last_h[i],
                           prompt_logits=per_req[i],
                           bucket=bucket, prefill_s=prefill_s)
                    for i in range(len(seqs))]

        new_tokens = (cfg.max_new_tokens if max_new_tokens is None
                      else min(int(max_new_tokens), cfg.max_new_tokens))
        t0 = time.perf_counter()
        pre = self._executable("prefill", bucket)
        logits, last, cache, tok, positions = pre(self._served, batch_ids,
                                                  batch_len)
        prefill_s = time.perf_counter() - t0
        telemetry.span_event("prefill", prefill_s, bucket=bucket,
                             rows=len(seqs))
        t0 = time.perf_counter()
        toks, cache = self.generate(bucket, cache, tok, positions,
                                    new_tokens)
        # ONE host fetch for the whole batch, after the last decode step
        fetch = [toks, last]
        if return_prompt_logits:
            fetch.append(logits)
        fetched = jax.device_get(fetch)
        toks_h, last_h = fetched[0], fetched[1]
        decode_s = time.perf_counter() - t0
        telemetry.span_event("decode", decode_s, bucket=bucket,
                             steps=max(new_tokens - 1, 0), rows=len(seqs))
        if return_prompt_logits:
            per_req = unpack_token_rows(fetched[2], lengths, len(seqs))
        else:
            per_req = [None] * len(seqs)
        return [Result(tokens=toks_h[i, :new_tokens],
                       last_logits=np.asarray(last_h[i]),
                       prompt_logits=per_req[i],
                       bucket=bucket, prefill_s=prefill_s,
                       decode_s=decode_s)
                for i in range(len(seqs))]

    def generate(self, bucket: int, cache, tok, positions,
                 new_tokens: int):
        """The decode hot loop: ``new_tokens`` compiled steps, cache donated
        and updated in place, every chained value (token, positions) staying
        on device — NO host fetch inside the loop (the
        ``no-host-sync-in-decode`` lint pins this function). Returns the
        (rows, new_tokens) generated-token matrix (stacked on device) and
        the final cache."""
        dec = self._executable("decode", bucket)
        out = []
        for k in range(new_tokens):
            out.append(tok)
            if k + 1 < new_tokens:  # K tokens need K-1 steps: the first
                cache, tok, positions = dec(  # token comes from prefill
                    self._served, cache, tok, positions)
        stacked = jnp.stack(out, axis=1) if out else \
            jnp.zeros((self.config.rows, 0), jnp.int32)
        return stacked, cache

    def serve_images(self, images: np.ndarray, mean: Sequence[float],
                     std: Sequence[float]) -> np.ndarray:
        """Batched image classification: normalize exactly like the eval
        task (data/augment.normalize_images — fp32 serve logits are the
        eval forward's bitwise) and forward. Returns (n, classes) logits
        for the real rows."""
        from ..data.augment import normalize_images

        cfg = self.config
        n = images.shape[0]
        if n > cfg.rows:
            raise ValueError(f"{n} images exceed rows={cfg.rows}")
        padded = np.zeros((cfg.rows,) + images.shape[1:], images.dtype)
        padded[:n] = images
        # mean/std are closed over the compiled program — they must key
        # the cache too, or a later call with different normalization
        # would silently reuse the first call's constants
        key = ("image", images.shape[1:], tuple(mean), tuple(std))
        if key not in self._compiled:
            def forward(served, imgs):
                params = self._dequant(served)
                x = normalize_images(imgs, mean, std,
                                     dtype=getattr(self.model, "dtype",
                                                   jnp.float32))
                return self.model.apply(self._apply_vars(params), x,
                                        train=False)
            self._compiled[key] = jax.jit(forward).lower(
                self._served, shard_batch(padded, self.mesh)).compile()
            self.compiles += 1
        t0 = time.perf_counter()
        logits = self._compiled[key](self._served,
                                     shard_batch(padded, self.mesh))
        logits = jax.device_get(logits)
        telemetry.span_event("prefill", time.perf_counter() - t0,
                             rows=n, image=True)
        return logits[:n]
