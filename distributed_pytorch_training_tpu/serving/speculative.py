"""Draft-model speculative decoding over the paged cache (ISSUE 19).

Every emitted token in the continuous engine costs one full target
forward. Speculative decoding buys several tokens per target forward
without changing a single emitted byte:

* A small DRAFT model proposes K greedy tokens per live slot per round,
  decoding over its OWN smaller paged pool (same page machinery, fp32).
* The TARGET verifies all K+1 window positions in ONE batched forward —
  the per-row-positions decode mode of models/gpt2.py generalized to an
  S-token window, whose row j is BITWISE the s=1 decode step at that
  position (the window parity pin in models/layers.py).
* Acceptance is exact token match: window output j is the token the
  plain path would have sampled at that position (same logits bitwise,
  same ``fold_in(request_key, position)`` key), and a proposal is
  accepted only when it EQUALS that token. Every emitted token is
  target-sampled, so the stream is pinned BITWISE vs the non-speculative
  SlotEngine — the draft's numerics steer only the accept RATIO, never
  the output (PARITY.md "Exactness model: speculative decode").
* Rejection is structural rollback, never re-prefill: the round commits
  the window's target k/v rows page-locally and advances the frontier by
  the accepted count only; stale rows past the frontier are rewritten
  in-view before any later window can see them (same masking argument as
  bucket padding), and the draft simply restarts its next propose run
  from the target's frontier.

fp32 pools only: an int8 pool would hand the verify window FRESH fp32
k/v for in-window rows where the plain path reads the dequantized page
bytes it committed one step earlier — residency in the window would
change the stream. The engine refuses int8 outright (the same exactness
economics as the prefix-skip gate in serving/continuous.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..data.pack import bucket_for
from ..models.layers import gather_paged_kv, scatter_paged_prefill, \
    scatter_paged_window
from .batching import Request, RequestQueue
from .continuous import ContinuousScheduler, SlotEngine, sample_tokens
from .paged import PagedServeConfig, PageLease, PagePool


class SpeculativeEngine(SlotEngine):
    """`SlotEngine` plus a draft model and two extra compiled programs:

    * ``draft_propose`` — K sequential draft decode steps over the draft
      pool (one gather, K in-view applies, one window scatter back),
      returning (rows, K) greedy proposals. Reads the TARGET control's
      positions/tok READ-ONLY — the draft keeps no control of its own,
      so rejection rollback is free: the next round re-reads the
      target's frontier.
    * ``spec_verify`` — the target's K+1-window forward + exact-match
      acceptance + window commit, replacing `decode_step` in the
      speculative scheduler's round. Donates pool + control exactly like
      the plain decode step (the ``serving_spec`` contract pins it) and
      additionally returns the per-slot emitted count — the ONE value
      the host must see each round.

    Draft prefill compiles per bucket like the target's; the whole
    program set compiles at `warmup` and the census stays flat.
    """

    def __init__(self, model, mesh, config: PagedServeConfig, params,
                 draft_model, draft_params, spec_k: int = 4,
                 batch_stats: Any = None, rules=None):
        if config.kv_dtype != "fp32":
            raise ValueError(
                "speculative decoding needs an fp32 page pool: the verify "
                "window reads in-window rows as fresh fp32 where the "
                "plain int8 path reads dequantized page bytes — int8 "
                "speculation would change the emitted stream (PARITY.md)")
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        super().__init__(model, mesh, config, params,
                         batch_stats=batch_stats, rules=rules)
        self.spec_k = int(spec_k)
        self.draft_model = draft_model
        # the draft pool must cover prompt + want + K positions per slot:
        # the last propose run of a request writes draft k/v up to
        # (n + want - 2) + K - 1. Sizing via the same config math keeps
        # the fail-safe floor semantics (paged.py `total_pages`).
        self.draft_config = dataclasses.replace(
            config, max_new_tokens=config.max_new_tokens + spec_k,
            kv_dtype="fp32", n_pages=0)
        if self.draft_padded_len > draft_model.max_position:
            raise ValueError(
                f"draft pages_per_slot * page_size = "
                f"{self.draft_padded_len} exceeds the draft model's "
                f"max_position {draft_model.max_position}")
        if getattr(draft_model, "vocab_size", None) != getattr(
                model, "vocab_size", None):
            raise ValueError(
                f"draft vocab {getattr(draft_model, 'vocab_size', None)} "
                f"!= target vocab {getattr(model, 'vocab_size', None)}: "
                "proposals are target-vocab token ids compared by exact "
                "match — the vocabularies must be the same table")
        self._draft_served = jax.device_put(
            jax.tree_util.tree_map(jnp.asarray, draft_params), self._rep)
        self.reset_draft_state()

    @property
    def draft_padded_len(self) -> int:
        cfg = self.draft_config
        return cfg.pages_per_slot * cfg.page_size

    def reset_state(self) -> None:
        super().reset_state()
        if hasattr(self, "draft_model"):   # base __init__ calls us early
            self.reset_draft_state()

    def reset_draft_state(self) -> None:
        """Zeroed draft pool + all-scratch draft table (compiled programs
        survive, same as `reset_state`)."""
        cfg = self.draft_config
        dpool = self.draft_model.init_paged_pool(
            cfg.total_pages, cfg.page_size, quantized=False)
        self._draft_pool = jax.device_put(dpool, self._rep)
        self._draft_table = np.zeros(
            (cfg.rows, cfg.pages_per_slot), np.int32)
        self._draft_table_dev = jax.device_put(self._draft_table,
                                               self._row_sharding(2))
        self._proposals = jax.device_put(
            np.zeros((cfg.rows, self.spec_k), np.int32),
            self._row_sharding(2))

    def draft_set_page_row(self, slot: int, row: np.ndarray) -> None:
        """`set_page_row` for the draft table (host numpy authoritative,
        device copy refreshed here, never in the round)."""
        self._draft_table[slot] = row
        self._draft_table_dev = jax.device_put(self._draft_table,
                                               self._row_sharding(2))

    # -- compiled programs ---------------------------------------------------

    def _draft_vars(self, dparams) -> dict:
        return {"params": dparams}

    def _draft_pool_avals(self):
        return jax.tree_util.tree_map(
            lambda x: self._rep_aval(x.shape, x.dtype), self._draft_pool)

    def _make_draft_prefill(self, bucket: int) -> Callable:
        def dprefill(dserved, dpool, dtable, ids, length, slot):
            cache0 = self.draft_model.init_cache(1, bucket)
            _logits, cache = self.draft_model.apply(
                self._draft_vars(dserved), ids, train=False, cache=cache0)
            row = dtable[slot]
            k_seqs = jnp.stack([c[0][0] for c in cache])
            v_seqs = jnp.stack([c[1][0] for c in cache])
            return scatter_paged_prefill(dpool, row, k_seqs, v_seqs,
                                         length)

        return dprefill

    def _make_draft_propose(self) -> Callable:
        k_spec = self.spec_k
        dpad = self.draft_padded_len

        def propose(dserved, dpool, dtable, positions, tok, budget):
            # K greedy draft steps chained through the dense in-view
            # cache: step j feeds the previous proposal at positions + j
            # and writes its k/v row in view; ONE window scatter commits
            # all K rows back to the draft pool afterwards. The target's
            # positions/tok are read-only inputs — draft state never
            # feeds back into target state except through `proposals`.
            active = budget > 0
            k_all, v_all = gather_paged_kv(dpool, dtable,
                                           dtype=self.draft_model.dtype)
            cache = tuple((k_all[l], v_all[l])
                          for l in range(self.draft_model.depth))
            cur = tok
            props = []
            # K+1 applies for K proposals: the last one only writes its
            # k/v row — a fully-accepted round advances the frontier by
            # K+1, and the next propose run attends position p+K, so the
            # draft cache must cover it (skipping this write starves the
            # draft after its first perfect round and craters the accept
            # ratio)
            for j in range(k_spec + 1):
                logits, cache = self.draft_model.apply(
                    self._draft_vars(dserved), cur[:, None], train=False,
                    cache=cache, cache_positions=positions + j)
                if j < k_spec:
                    cur = jnp.argmax(logits[:, 0],
                                     axis=-1).astype(jnp.int32)
                    props.append(cur)
            proposals = jnp.stack(props, axis=1)          # (rows, K)
            win_pos = positions[:, None] + jnp.arange(k_spec + 1)[None, :]
            idxc = jnp.clip(win_pos, 0, dpad - 1)[:, :, None, None]
            k_rows = jnp.stack([jnp.take_along_axis(c[0], idxc, axis=1)
                                for c in cache])   # (L, rows, K, H, D)
            v_rows = jnp.stack([jnp.take_along_axis(c[1], idxc, axis=1)
                                for c in cache])
            act = active[:, None] & (win_pos < dpad)
            new_dpool = scatter_paged_window(dpool, dtable, win_pos,
                                             k_rows, v_rows, act)
            return new_dpool, proposals

        return propose

    def _make_spec_verify(self) -> Callable:
        cfg: PagedServeConfig = self.config
        rows, s = cfg.rows, self.spec_k + 1
        pad = self.padded_len

        def verify(served, pool, control, page_table, proposals):
            params = self._dequant(served)
            active = control["budget"] > 0
            positions = control["positions"]
            tok = control["tok"]
            # the verify window: the committed-next token plus the K
            # draft proposals, one batched S-row forward over the pool
            window = jnp.concatenate([tok[:, None], proposals], axis=1)
            k_all, v_all = gather_paged_kv(pool, page_table,
                                           dtype=self.model.dtype)
            cache = tuple((k_all[l], v_all[l])
                          for l in range(self.model.depth))
            logits, new_cache = self.model.apply(
                self._apply_vars(params), window, train=False,
                cache=cache, cache_positions=positions)  # (rows, S, vocab)
            # sample every window output with ITS position's key — window
            # row j's token is bitwise the plain step's at that position
            # (same logits by the window parity pin, same fold_in key,
            # and sample_tokens is row-independent)
            win_pos = positions[:, None] + jnp.arange(s)[None, :]
            step_keys = jax.vmap(jax.random.fold_in)(
                jnp.repeat(control["keys"], s, axis=0),
                (win_pos + 1).reshape(-1))
            outs = sample_tokens(
                logits.reshape(rows * s, -1), step_keys,
                jnp.repeat(control["temps"], s),
                jnp.repeat(control["top_ps"], s)).reshape(rows, s)
            # exact-match acceptance: keep the longest prefix of
            # proposals that equals the target-sampled stream, then emit
            # one more (the target's own token at the first mismatch) —
            # never past the remaining budget
            match = (outs[:, :-1] == proposals).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            n_emit = jnp.where(
                active, jnp.minimum(n_acc + 1, control["budget"]), 0)
            # commit ALL S window rows page-locally: rows past the new
            # frontier hold a rejected continuation, but every later
            # reader rewrites them in-view before its mask can expose
            # them (the structural-rollback argument above)
            idxc = jnp.clip(win_pos, 0, pad - 1)[:, :, None, None]
            k_rows = jnp.stack([jnp.take_along_axis(c[0], idxc, axis=1)
                                for c in new_cache])
            v_rows = jnp.stack([jnp.take_along_axis(c[1], idxc, axis=1)
                                for c in new_cache])
            act = active[:, None] & (win_pos < pad)
            new_pool = scatter_paged_window(pool, page_table, win_pos,
                                            k_rows, v_rows, act)
            # emit outs[:n_emit] into out_buf at this slot's cursor
            out_idx = jnp.where(
                jnp.arange(s)[None, :] < n_emit[:, None],
                control["emitted"][:, None] + jnp.arange(s)[None, :],
                cfg.max_new_tokens)
            out_buf = control["out_buf"].at[
                jnp.arange(rows)[:, None], out_idx].set(outs, mode="drop")
            last = jnp.take_along_axis(
                outs, jnp.clip(n_emit - 1, 0, s - 1)[:, None],
                axis=1)[:, 0]
            # skip-admitted slots capture their last-prompt logits off
            # window row 0 — same last_pos protocol as the plain step
            cap = positions == control["last_pos"]
            new_control = dict(control)
            new_control["tok"] = jnp.where(active, last, tok)
            new_control["positions"] = positions + n_emit
            new_control["budget"] = control["budget"] - n_emit
            new_control["emitted"] = control["emitted"] + n_emit
            new_control["out_buf"] = out_buf
            new_control["last_buf"] = jnp.where(
                cap[:, None], logits[:, 0], control["last_buf"])
            new_control["last_pos"] = jnp.where(
                cap, -1, control["last_pos"])
            return new_pool, new_control, n_emit

        return verify

    def lower_draft_prefill(self, bucket: int):
        """The lowered B=1 draft admission fill — draft pool DONATED."""
        cfg = self.draft_config
        dpool_avals = self._draft_pool_avals()
        scalar_i = self._rep_aval((), jnp.int32)
        return jax.jit(
            self._make_draft_prefill(bucket), donate_argnums=(1,),
            out_shardings=self._out_shardings(dpool_avals),
        ).lower(self._draft_served, dpool_avals,
                self._row_aval((cfg.rows, cfg.pages_per_slot), jnp.int32),
                self._rep_aval((1, bucket), jnp.int32), scalar_i, scalar_i)

    def lower_draft_propose(self):
        """The lowered K-step propose round — draft pool DONATED; target
        positions/tok/budget are read-only inputs."""
        cfg = self.draft_config
        rows = cfg.rows
        dpool_avals = self._draft_pool_avals()
        outs = (dpool_avals,
                self._row_aval((rows, self.spec_k), jnp.int32))
        return jax.jit(
            self._make_draft_propose(), donate_argnums=(1,),
            out_shardings=self._out_shardings(outs),
        ).lower(self._draft_served, dpool_avals,
                self._row_aval((rows, cfg.pages_per_slot), jnp.int32),
                self._row_aval((rows,), jnp.int32),
                self._row_aval((rows,), jnp.int32),
                self._row_aval((rows,), jnp.int32))

    def lower_spec_verify(self):
        """The lowered K+1-window verify step — pool + control DONATED
        exactly like the plain decode step's (the `serving_spec` contract
        reads this); the extra ``n_emit`` output is the round's one
        host-visible value."""
        cfg: PagedServeConfig = self.config
        pool_avals = self._pool_avals()
        ctrl_avals = self._control_avals()
        outs = (pool_avals, ctrl_avals,
                self._row_aval((cfg.rows,), jnp.int32))
        return jax.jit(
            self._make_spec_verify(), donate_argnums=(1, 2),
            out_shardings=self._out_shardings(outs),
        ).lower(self._served, pool_avals, ctrl_avals,
                self._row_aval((cfg.rows, cfg.pages_per_slot), jnp.int32),
                self._row_aval((cfg.rows, self.spec_k), jnp.int32))

    def _executable(self, kind: str, bucket: int):
        if kind not in ("draft_prefill", "draft_propose", "spec_verify"):
            return super()._executable(kind, bucket)
        key = (kind, bucket)
        if key not in self._compiled:
            lowered = {
                "draft_prefill": lambda: self.lower_draft_prefill(bucket),
                "draft_propose": self.lower_draft_propose,
                "spec_verify": self.lower_spec_verify,
            }[kind]()
            with telemetry.span("compile", program=kind, bucket=bucket):
                self._compiled[key] = lowered.compile()
            self.compiles += 1
        return self._compiled[key]

    def warmup(self) -> int:
        super().warmup()
        self._executable("draft_propose", 0)
        self._executable("spec_verify", 0)
        for b in self.config.buckets:
            self._executable("draft_prefill", b)
        return self.compiles

    # -- runtime entries -----------------------------------------------------

    def draft_admit(self, slot: int, tokens: np.ndarray) -> int:
        """Fill the slot's draft pages from the prompt (no control, no
        sampling — the draft only ever needs k/v). Unfenced like the
        target admission; the scheduler's round fence bounds it."""
        cfg = self.draft_config
        bucket = bucket_for(len(tokens), cfg.buckets)
        ids = np.full((1, bucket), cfg.pad_id, np.int32)
        ids[0, :len(tokens)] = tokens
        dev = lambda x: jax.device_put(x, self._rep)  # noqa: E731
        exe = self._executable("draft_prefill", bucket)
        self._draft_pool = exe(
            self._draft_served, self._draft_pool, self._draft_table_dev,
            dev(ids), dev(np.int32(len(tokens))), dev(np.int32(slot)))
        return bucket

    def draft_propose(self) -> None:
        """One K-token propose round for every live slot (device-chained;
        the proposals buffer feeds `verify_step` without a host trip)."""
        exe = self._executable("draft_propose", 0)
        self._draft_pool, self._proposals = exe(
            self._draft_served, self._draft_pool, self._draft_table_dev,
            self._control["positions"], self._control["tok"],
            self._control["budget"])

    def verify_step(self):
        """One verify round over the whole slot pool; returns the (rows,)
        per-slot emitted-count DEVICE array — the scheduler fetches it
        once per round (acceptance is inherently a host decision: the
        budget mirrors must advance by the true accepted counts)."""
        exe = self._executable("spec_verify", 0)
        self._pool, self._control, n_emit = exe(
            self._served, self._pool, self._control, self._table_dev,
            self._proposals)
        return n_emit

    def draft_bytes(self) -> int:
        """At-rest bytes of the draft pool (fp32) — the bench's HBM
        accounting includes the speculation tax explicitly."""
        from ..models.layers import paged_kv_bytes

        return paged_kv_bytes(self._draft_pool)


class SpeculativeScheduler(ContinuousScheduler):
    """`ContinuousScheduler` whose advance is one propose + verify round.

    The three base-class hooks manage the draft lease lifecycle: a
    request is admitted only when BOTH pools can hold it (`_draft_admit`
    — a failed draft lease rolls the target lease back and the request
    stays pending), the draft prefill dispatches right after the target
    admission lands (`_post_admit`), and completion releases the draft
    pages with the target's (`_post_complete`). Everything else — skip /
    resume admission, TTFT stamping, drain/kill — is inherited unchanged.
    """

    def __init__(self, engine: SpeculativeEngine, queue: RequestQueue):
        if not isinstance(engine, SpeculativeEngine):
            raise ValueError("SpeculativeScheduler needs a "
                             "SpeculativeEngine (draft model + verify "
                             "step); plain SlotEngines run under "
                             "ContinuousScheduler")
        super().__init__(engine, queue)
        dcfg = engine.draft_config
        # the draft allocator: no prefix sharing (draft pages are never
        # content-addressed — the draft always prefills its own copy, so
        # a draft admission can never change target residency/behavior)
        self.draft_pool = PagePool(dcfg.total_pages, dcfg.page_size,
                                   dcfg.pages_per_slot,
                                   prefix_sharing=False)
        self._draft_leases: Dict[int, PageLease] = {}   # guarded-by: _lock
        self._draft_pending: Dict[int, PageLease] = {}  # guarded-by: _lock
        # acceptance census: proposals offered vs accepted (the gauge the
        # bench's accept-ratio column reads)
        self.spec_rounds = 0                            # guarded-by: _lock
        self.spec_proposed = 0                          # guarded-by: _lock
        self.spec_accepted = 0                          # guarded-by: _lock

    @property
    def accept_ratio(self) -> float:
        """Accepted draft tokens / proposed draft tokens, cumulative."""
        with self._lock:
            return (self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else 0.0)

    # -- draft lease lifecycle (the base-class hooks) ------------------------

    def _draft_admit(self, req: Request, lease: PageLease,
                     want: int) -> bool:   # lock-held: _lock
        eng: SpeculativeEngine = self.engine
        dlease = self.draft_pool.alloc(
            req.tokens, len(req.tokens) + want + eng.spec_k)
        if dlease is None:
            return False
        self._draft_pending[req.id] = dlease
        return True

    def _post_admit(self, slot: int, req: Request) -> None:  # lock-held: _lock
        eng: SpeculativeEngine = self.engine
        dlease = self._draft_pending.pop(req.id)
        self._draft_leases[slot] = dlease
        eng.draft_set_page_row(slot, dlease.pages)
        t0 = time.perf_counter()
        bucket = eng.draft_admit(slot, req.tokens)
        telemetry.span_event("draft_decode", time.perf_counter() - t0,
                             prefill=True, bucket=bucket, slot=slot,
                             request=req.id)

    def _post_complete(self, slot: int) -> None:   # lock-held: _lock
        eng: SpeculativeEngine = self.engine
        dlease = self._draft_leases.pop(slot, None)
        if dlease is not None:
            self.draft_pool.release(dlease)
            eng.draft_set_page_row(
                slot, np.zeros(eng.draft_config.pages_per_slot, np.int32))

    # -- the speculative round -----------------------------------------------

    def _advance(self) -> None:   # lock-held: _lock
        """One propose + verify round: up to K+1 tokens per slot per
        fence. The n_emit fetch is the round's one host sync — the
        accepted counts ARE host state (budget mirrors, completion), and
        the caller fences right after anyway; the per-token
        no-host-sync contract (`_step_decode_loop`) is untouched because
        this path never runs it."""
        eng: SpeculativeEngine = self.engine
        live = len(self.running)
        t0 = time.perf_counter()
        eng.draft_propose()
        t1 = time.perf_counter()
        telemetry.span_event("draft_decode", t1 - t0, k=eng.spec_k,
                             slots=live)
        n_emit = np.asarray(jax.device_get(eng.verify_step()))
        t2 = time.perf_counter()
        telemetry.span_event("spec_verify", t2 - t1, slots=live)
        for slot, st in self.running.items():
            got = int(n_emit[slot])
            st.left = max(st.left - got, 0)
            # emitted - 1 of each round's tokens came from accepted
            # proposals (the +1 is the target's own token); the clamp to
            # the remaining budget is still "accepted" for the ratio —
            # the draft was right, the request just ended
            self.spec_accepted += max(got - 1, 0)
        self.spec_proposed += eng.spec_k * live
        self.spec_rounds += 1
        if self.spec_proposed:
            # inline, not the accept_ratio property: that takes _lock
            # for external readers and this method already holds it
            telemetry.gauge("spec_accept_ratio",
                            self.spec_accepted / self.spec_proposed)


def serve_speculative(engine: SpeculativeEngine, queue: RequestQueue,
                      stop, log=None) -> int:
    """Worker-loop twin of `serve_continuous` for the speculative
    scheduler (the CLI runs one per replica thread when --draft is
    armed)."""
    return SpeculativeScheduler(engine, queue).run(stop, log=log)
