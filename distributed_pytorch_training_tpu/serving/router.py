"""Multi-replica request router: offered load -> N serving replicas.

One continuous-batching replica saturates at its slot pool; the fleet
answer is N REPLICAS of the same engine behind one stdlib router:

* **Replica handles** hide where the engine lives. `InProcessReplica`
  wraps a `SlotEngine` + `ContinuousScheduler` on a worker thread (tests,
  single-process fleets); `HttpReplica` fronts a ``serving serve``
  process over its ``POST /generate`` endpoint, with liveness read from
  the replica's OWN ``/healthz`` step-fence and load from its
  ``/metrics`` queue-depth gauge (telemetry/metrics_http.py) — the
  router consumes the observability surface the fleet already exports,
  it does not invent a private protocol.
* **Dispatch** picks the healthy replica with the smallest queue depth
  (ties: round-robin order), under a ``router_dispatch`` telemetry span
  — queue-depth skew across replicas is readable straight off the
  span's attrs.
* **Failure = resubmit**: a `RouterRequest` that dies with its replica
  (the injected replica death) is resubmitted to the surviving replicas
  — every request completes while at least one replica lives, and the
  resubmission count rides the result. Sampling determinism makes the
  retry invisible: the same request seed emits the same tokens on ANY
  replica (serving/continuous.py).

`resilience.fleet.ServingFleet` supervises the replica PROCESSES
(relaunch-on-death, SIGTERM drain, one federated /metrics page); this
module only routes.
"""

from __future__ import annotations

import http.client
import json
import re
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..utils.locktrace import named_lock
from .batching import Request, RequestQueue, Result

_GAUGE_RE = re.compile(
    r'dpt_gauge\{name="serving_queue_depth"[^}]*\}\s+([0-9.eE+-]+)')


class ReplicaDead(RuntimeError):
    """A replica failed a request (process death, scheduler kill, refused
    connection) — the router's cue to resubmit elsewhere."""


class InProcessReplica:
    """One continuous-batching engine + scheduler on a worker thread.

    The unit the router tests compose: `kill` is the chaos hook (the
    scheduler fails everything in flight with `ReplicaDead`, the router
    resubmits), `stop` is the drain path."""

    def __init__(self, name: str, engine, start: bool = True,
                 scheduler_cls=None):
        from .continuous import ContinuousScheduler

        if scheduler_cls is None:
            # a SpeculativeEngine under the plain scheduler would decode
            # token-at-a-time and never touch the draft — auto-pair the
            # engine with the scheduler that drives its verify loop
            from .speculative import SpeculativeEngine, SpeculativeScheduler
            scheduler_cls = (SpeculativeScheduler
                             if isinstance(engine, SpeculativeEngine)
                             else ContinuousScheduler)
        self.name = name
        self.engine = engine
        self.queue = RequestQueue(engine.config.buckets)
        self.scheduler = scheduler_cls(engine, self.queue)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self.scheduler.run, args=(self._stop,),
            name=f"replica-{name}", daemon=True)
        if start:
            self._thread.start()

    def submit(self, tokens: np.ndarray, **kw) -> Request:
        if not self.healthy():
            raise ReplicaDead(f"replica {self.name} is down")
        try:
            return self.queue.submit(tokens, **kw)
        except RuntimeError as e:  # closed (draining/dead) queue
            raise ReplicaDead(f"replica {self.name}: {e}") from e

    def healthy(self) -> bool:
        return self._thread.is_alive() and not self.scheduler.killed

    def queue_depth(self) -> int:
        # racy snapshot of another thread's collections, by design: the
        # router wants a cheap load estimate, not a fenced truth
        return (len(self.queue) + len(self.scheduler.pending)
                + len(self.scheduler.running))

    def kill(self) -> List[Request]:
        """Inject a replica death: fail everything in flight, stop the
        worker. Returns the failed requests (the router resubmits its
        own; direct submitters see `ReplicaDead`)."""
        failed = self.scheduler.kill(ReplicaDead(
            f"replica {self.name} died"))
        self._stop.set()
        self._thread.join(timeout=30.0)
        return failed

    def stop(self) -> None:
        """Drain and stop: accepted work completes, then the worker
        exits (the SIGTERM contract, in-process form)."""
        self._stop.set()
        self._thread.join(timeout=600.0)


class HttpReplica:
    """A ``serving serve`` process, fronted over stdlib HTTP.

    ``port`` is the /generate endpoint; ``metrics_port`` (when given) is
    the SAME replica's /healthz + /metrics surface — liveness is the
    step-fence verdict, load is the ``serving_queue_depth`` gauge. With
    no metrics port, health degrades to 'the last request worked'."""

    def __init__(self, name: str, port: int,
                 metrics_port: Optional[int] = None,
                 host: str = "127.0.0.1", timeout_s: float = 600.0):
        self.name = name
        self.host = host
        self.port = int(port)
        self.metrics_port = metrics_port
        self.timeout_s = float(timeout_s)
        # deliberately unguarded: a monotonic-ish health HINT written by
        # whichever request finished last — a stale read only delays the
        # router's next probe, it cannot corrupt anything
        self._last_ok = True

    def _url(self, path: str, port: int) -> str:
        return f"http://{self.host}:{port}{path}"

    def submit(self, tokens: np.ndarray, **kw) -> "_HttpPending":
        body = {"tokens": np.asarray(tokens, np.int32).tolist(), **{
            k: v for k, v in kw.items() if v is not None}}
        return _HttpPending(self, body)

    def healthy(self) -> bool:
        if self.metrics_port:
            try:
                with urllib.request.urlopen(
                        self._url("/healthz", self.metrics_port),
                        timeout=2.0) as resp:
                    return resp.status == 200
            except (OSError, urllib.error.URLError):
                return False
        return self._last_ok

    def queue_depth(self) -> int:
        if not self.metrics_port:
            return 0
        from ..telemetry.metrics_http import scrape_metrics

        page = scrape_metrics(self.metrics_port) or ""
        m = _GAUGE_RE.search(page)
        return int(float(m.group(1))) if m else 0


class _HttpPending:
    """A lazily-POSTed HTTP request: the POST happens (and blocks) inside
    ``result()``, on the caller's thread — same waitable surface as
    `Request`, and a connection failure surfaces as `ReplicaDead` so the
    router's retry loop treats processes and threads alike."""

    def __init__(self, replica: HttpReplica, body: dict):
        self.replica = replica
        self.body = body

    def result(self, timeout: Optional[float] = None) -> Result:
        data = json.dumps(self.body).encode()
        req = urllib.request.Request(
            self.replica._url("/generate", self.replica.port), data=data,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.replica.timeout_s) as resp:
                # read INCREMENTALLY: a replica dying mid-response must
                # surface now, as a death, not at the request timeout.
                # A chunk-boundary reset raises (IncompleteRead /
                # ConnectionResetError — both handled below); a clean
                # close short of Content-Length is the same half-response
                # and is promoted to IncompleteRead here, because
                # json.loads on a truncated body would misreport a dead
                # replica as a protocol bug
                want = resp.headers.get("Content-Length")
                chunks = []
                while True:
                    chunk = resp.read(65536)
                    if not chunk:
                        break
                    chunks.append(chunk)
                raw = b"".join(chunks)
                if want is not None and len(raw) < int(want):
                    raise http.client.IncompleteRead(
                        raw, int(want) - len(raw))
                out = json.loads(raw.decode())
        except (TimeoutError, socket.timeout) as e:
            # a slow read is NOT a death: the replica is healthy but
            # busy, and resubmitting would stack a duplicate in-flight
            # copy on it — surface the timeout to the caller instead
            raise TimeoutError(
                f"replica {self.replica.name}: no response within "
                f"{timeout or self.replica.timeout_s}s") from e
        except http.client.HTTPException as e:
            # half-response (IncompleteRead) or a torn status line: the
            # process died mid-POST — resubmit elsewhere immediately
            # (the route-time-pinned seed makes the retry emit the
            # identical stream)
            self.replica._last_ok = False
            raise ReplicaDead(
                f"replica {self.replica.name}: died mid-response "
                f"({type(e).__name__}: {e})") from e
        except (OSError, urllib.error.URLError) as e:
            reason = getattr(e, "reason", None)
            if isinstance(reason, (TimeoutError, socket.timeout)):
                raise TimeoutError(
                    f"replica {self.replica.name}: no response within "
                    f"{timeout or self.replica.timeout_s}s") from e
            # connection refused/reset: the process is actually gone
            self.replica._last_ok = False
            raise ReplicaDead(
                f"replica {self.replica.name}: {e}") from e
        self.replica._last_ok = True
        return Result(
            tokens=np.asarray(out.get("tokens", []), np.int32),
            last_logits=np.asarray(out.get("last_logits", []), np.float32),
            bucket=int(out.get("bucket", 0)))


class RouterRequest:
    """One routed request: dispatched to a replica at submit time,
    RESUBMITTED to survivors if that replica dies before completing.
    ``replica_deaths`` counts the retries the caller never saw."""

    _seeds = iter(range(1, 1 << 62))   # guarded-by: _seeds_lock
    _seeds_lock = named_lock("RouterRequest._seeds_lock")

    def __init__(self, router: "Router", tokens: np.ndarray, kw: dict):
        self.router = router
        self.tokens = np.asarray(tokens, np.int32)
        self.kw = dict(kw)
        if self.kw.get("seed") is None:
            # pin the seed at ROUTE time, not engine time: a resubmitted
            # request must sample the identical stream on its new replica
            with RouterRequest._seeds_lock:
                self.kw["seed"] = next(RouterRequest._seeds)
        self.replica_deaths = 0
        self.replica_name: Optional[str] = None
        # completion stamp (perf_counter): the WORKER's set_result time
        # when the replica exposes one, else when result() returned here.
        # Latency instruments must read this, not their own clock after
        # result() — a caller collecting results in submission order
        # observes early completions late and inflates every percentile.
        self.t_done: Optional[float] = None
        self._inner = None
        self._dispatch(exclude=())

    def _dispatch(self, exclude: Sequence[str]) -> None:
        t0 = time.perf_counter()
        replica = self.router._pick(exclude=exclude)
        self._inner = replica.submit(self.tokens, **self.kw)
        self.replica_name = replica.name
        telemetry.span_event(
            "router_dispatch", time.perf_counter() - t0,
            replica=replica.name, depth=replica.queue_depth(),
            retry=self.replica_deaths)

    def result(self, timeout: Optional[float] = None) -> Result:
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        while True:
            left = None
            if deadline is not None:
                left = deadline - time.perf_counter()
                if left <= 0:
                    raise TimeoutError(
                        f"routed request timed out after {timeout}s "
                        f"({self.replica_deaths} replica deaths)")
            try:
                res = self._inner.result(timeout=left)
                self.t_done = getattr(self._inner, "t_done", None) \
                    or time.perf_counter()
                return res
            except ReplicaDead as e:
                # the replica died with our request in flight: resubmit
                # to the survivors (same seed -> same tokens, so the
                # retry is invisible in the output stream) — but only
                # while the caller's deadline still has room; a spent
                # deadline must raise, not spin resubmitting forever.
                # A plain slow read raises TimeoutError (not
                # ReplicaDead) and propagates: slow is not dead.
                if deadline is not None and \
                        time.perf_counter() >= deadline:
                    raise TimeoutError(
                        f"routed request timed out after {timeout}s "
                        f"({self.replica_deaths} replica deaths)") from e
                self.replica_deaths += 1
                dead = self.replica_name
                self._dispatch(exclude=(dead,) if dead else ())


class Router:
    """Spread offered load over replica handles: least-depth healthy
    replica wins, requests orphaned by a death are resubmitted. Pure
    host-side stdlib — the router never touches a device."""

    def __init__(self, replicas: Sequence):
        if not replicas:
            raise ValueError("a router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        self.replicas: Dict[str, object] = {r.name: r for r in replicas}
        self._rr = 0   # guarded-by: _lock
        self._lock = named_lock("Router._lock")

    def _pick(self, exclude: Sequence[str] = ()):
        # snapshot under the lock, PROBE outside it: healthy() and
        # queue_depth() are HTTP round trips for HttpReplica (2s timeout
        # each), and holding the router lock across them would let one
        # unreachable replica serialize every dispatch on every thread
        with self._lock:
            self._rr += 1
            rr = self._rr
            replicas = list(self.replicas.values())
        live = [r for r in replicas
                if r.name not in exclude and r.healthy()]
        if not live:
            # second chance for the excluded (a lone restarted
            # replica beats failing the request outright)
            live = [r for r in replicas if r.healthy()]
        if not live:
            raise ReplicaDead("no healthy replicas")
        depths = [(r.queue_depth(), i) for i, r in enumerate(live)]
        best = min(d for d, _ in depths)
        candidates = [i for d, i in depths if d == best]
        return live[candidates[rr % len(candidates)]]

    def submit(self, tokens: np.ndarray, **kw) -> RouterRequest:
        return RouterRequest(self, tokens, kw)

    def healthy_count(self) -> int:
        return sum(1 for r in self.replicas.values() if r.healthy())

    def stop(self) -> None:
        for r in self.replicas.values():
            stop = getattr(r, "stop", None)
            if stop is not None:
                stop()
