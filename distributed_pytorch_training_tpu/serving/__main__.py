"""``python -m distributed_pytorch_training_tpu.serving`` — serve a
manifest-verified checkpoint through the batched inference engine.

Also installed as the ``serving`` console script (pyproject.toml).

Commands:
  smoke [--ckpt-dir D] [--prompt 12,7,99 | --prompt-len N]
      One-shot: build the engine (restoring the newest verified checkpoint
      when --ckpt-dir is given; random-init weights otherwise — a smoke of
      the serving PATH, loudly labeled, never of a served model), serve a
      handful of synthetic prompts, print the generated tokens and the
      checkpoint provenance (label + manifest tree_digest).
  bench [--requests N] [--offered-load RPS] [--json]
      Latency/throughput at fixed offered load: a deterministic load
      generator submits mixed-length prompts on a 1/RPS cadence while the
      engine worker drains the queue (continuous batching); reports
      p50/p99 latency, achieved request/token throughput, the compile
      census (zero recompiles after warmup is the contract), and the
      serving HLO-contract verdict — the serving row of the bench table
      (experiments/harness.py::measure_serving).
      --continuous switches to the TOKEN-granular arm (slot engine +
      paged/int8 KV, serving/continuous.py) — same load schedule, so the
      two rows are the iteration-vs-token A/B; --replicas N spreads it
      over N in-process replicas behind the stdlib router and
      --kill-replica injects one replica death mid-load (every request
      must still complete, recompiles must stay 0).
      --draft MODEL arms speculative decoding (draft proposes --draft-k
      tokens, target verifies the K+1 window in one forward; the row
      gains accept_ratio and the stream stays bitwise the plain arm's);
      --shared-frac F gives F of the requests one shared prompt — after
      the primer each admits with ZERO prefill (prefill_skips + the
      warm/cold TTFT split are the receipts).
  serve [--port P] [--kv-dtype int8] [--page-size N]
      ONE long-lived continuous-batching replica: POST /generate
      ({"tokens": [...], "max_new_tokens"?, "temperature"?, "top_p"?,
      "seed"?, "want_logits"?}) blocks until the tokens are out; /healthz
      + /metrics ride --metrics-port (the router reads both). SIGTERM
      drains: admitted requests complete, then exit 0.
  fleet [--replicas N] [--port BASE] [--federation-port P]
      N `serve` replicas as supervised child processes (replica r on port
      BASE+r, metrics on --metrics-port+r): a replica that dies is
      relaunched within budget, SIGTERM drains the whole fleet, and
      --federation-port serves the ONE merged /metrics dashboard
      (resilience/fleet.py::ServingFleet).

Health/drain: the resilience Deathwatch watches the relay ports exactly as
train.py's does (opt-in via DPT_RELAY_PORTS); SIGTERM closes the queue,
DRAINS it (accepted requests complete, new ones are refused), flushes a
telemetry flight, and exits 0. Any abnormal exit flushes a flight too.

Checkpoint templates: orbax restores against the training run's full
TrainState structure, so a checkpoint written under --zero1 /
--fsdp-explicit / an int8 wire needs the same flags here (exactly the
resume-hint contract train.py documents).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from pathlib import Path
from typing import List, Optional

import numpy as np


def _parse_buckets(text: str) -> tuple:
    try:
        out = tuple(int(b) for b in text.split(",") if b.strip())
    except ValueError:
        out = ()
    if not out:
        raise SystemExit(f"serving: --buckets expects e.g. '16,32,64', "
                         f"got {text!r}")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="serving", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=["smoke", "bench", "serve", "fleet"])
    p.add_argument("--model", default="gpt2_124m")
    p.add_argument("--ckpt-dir", default=None,
                   help="serve the newest manifest-verified checkpoint "
                        "from this directory (omit: random-init smoke)")
    p.add_argument("--serve-dtype", default="fp32",
                   choices=["fp32", "bf16", "int8"])
    p.add_argument("--mesh", default=None,
                   help="mesh spec, e.g. 'data=4,model=2' (default: pure "
                        "DP over all devices) — model>1 shards the served "
                        "weights over the model axis via the model's "
                        "GSPMD partition rules (multi-chip serving of "
                        "models too big for one chip); validate_mesh "
                        "rejects axes the served model cannot use")
    p.add_argument("--buckets", default="16,32",
                   help="prompt-length bucket ladder, e.g. '32,64,128'")
    p.add_argument("--rows", type=int, default=8,
                   help="batch rows per engine cycle")
    p.add_argument("--max-new-tokens", type=int, default=8)
    p.add_argument("--model-overrides", default="",
                   help="architecture overrides, e.g. "
                        "'hidden_dim=64,depth=2,num_heads=2'")
    # checkpoint TEMPLATE flags (must mirror the training run's — orbax
    # validates the TrainState structure, and the optimizer chain's
    # structure depends on these: see harness.build_serving_engine)
    p.add_argument("--zero1", action="store_true")
    p.add_argument("--fsdp-explicit", action="store_true")
    p.add_argument("--wire-dtype", default="fp32")
    p.add_argument("--bucket-cap-mb", type=float, default=0.0)
    p.add_argument("--optimizer", default="auto",
                   choices=["auto", "sgd", "adamw"],
                   help="the training run's optimizer (auto: adamw for "
                        "LMs, sgd for vision — train.py's own default is "
                        "sgd everywhere)")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=5e-4)
    # smoke
    p.add_argument("--prompt", default=None,
                   help="smoke: comma-separated token ids")
    p.add_argument("--prompt-len", type=int, default=12,
                   help="smoke: synthetic prompt length when no --prompt")
    # continuous / paged serving (serve, fleet, bench --continuous)
    p.add_argument("--continuous", action="store_true",
                   help="bench: token-granular slot-engine arm (paged KV) "
                        "instead of the iteration-granular engine")
    p.add_argument("--replicas", type=int, default=1,
                   help="bench --continuous: in-process replicas behind "
                        "the router; fleet: serve children to supervise")
    p.add_argument("--kv-dtype", default="fp32", choices=["fp32", "int8"],
                   help="paged KV pool dtype (int8: per-row quantized "
                        "pages through the grad-sync int8 grid)")
    p.add_argument("--page-size", type=int, default=8,
                   help="positions per KV page (divide the top bucket + "
                        "max-new for a padding-free pool)")
    p.add_argument("--kill-replica", action="store_true",
                   help="bench --continuous --replicas>1: kill replica 0 "
                        "mid-load; the router must resubmit its requests")
    # speculative decoding + prefix-resident admission (bench --continuous)
    p.add_argument("--draft", default=None, metavar="MODEL",
                   help="bench --continuous: arm speculative decoding "
                        "with this (random-init, smaller) draft LM — "
                        "fp32 KV only; the emitted streams stay bitwise "
                        "the plain row's (acceptance is exact match)")
    p.add_argument("--draft-k", type=int, default=4,
                   help="draft tokens proposed per slot per verify round")
    p.add_argument("--shared-frac", type=float, default=0.0,
                   help="bench --continuous: fraction of requests that "
                        "share ONE page-aligned prompt — after the "
                        "primer, each admits with zero prefill dispatch "
                        "(prefill_skips + warm/cold TTFT in the row)")
    p.add_argument("--no-prefix-skip", action="store_true",
                   help="disable the prefix-resident admission fast path "
                        "(shared pages still dedupe; admission prefills)")
    p.add_argument("--port", type=int, default=8100,
                   help="serve: /generate port (0 = ephemeral, logged); "
                        "fleet: base port — replica r listens on base+r")
    p.add_argument("--federation-port", type=int, default=None,
                   help="fleet: one merged /metrics page over the "
                        "replicas' ports (needs --metrics-port)")
    # bench
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--offered-load", type=float, default=16.0,
                   help="bench: offered request rate (req/s)")
    p.add_argument("--mixed-want", action="store_true",
                   help="bench: per-request decode lengths (1..max_new, "
                        "seed-pinned) — the serving-traffic A/B workload; "
                        "the iteration arm still decodes the full max_new "
                        "per batch (it cannot honor per-request wants) "
                        "and only the wanted tokens are credited")
    p.add_argument("--output-dir", default="./serving_out",
                   help="telemetry stream + flight directory")
    p.add_argument("--no-telemetry", action="store_true")
    p.add_argument("--metrics-port", default=None, type=int,
                   help="serve live /metrics + /healthz on this port "
                        "(+rank offset); default DPT_METRICS_PORT env, "
                        "else off (zero threads)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    buckets = _parse_buckets(args.buckets)

    # Standalone CPU runs get the 8-device virtual mesh (the analysis CLI's
    # recipe — serving shares it so `serving smoke` exercises real
    # cross-device batch sharding with no TPU).
    from ..analysis.__main__ import _ensure_test_mesh

    _ensure_test_mesh()

    import jax

    from .. import telemetry
    from ..resilience.heartbeat import Deathwatch
    from ..utils.logging import log_main

    tele_rank = telemetry.rank_identity(jax.process_index())
    if not args.no_telemetry and telemetry.should_stream(tele_rank):
        Path(args.output_dir).mkdir(parents=True, exist_ok=True)
        telemetry.configure(
            str(Path(args.output_dir)
                / telemetry.stream_filename(tele_rank)),
            rank=tele_rank, gen=telemetry.generation_identity(),
            meta={"entry": "serving", "model": args.model,
                  "serve_dtype": args.serve_dtype,
                  "buckets": list(buckets)})
    # live /metrics + /healthz (telemetry/metrics_http.py): the serving
    # replica's scrape surface — prefill/decode histograms feed the same
    # phase metric the training loop's dispatch does, and the healthz
    # fence counts decode progress. Off (default) starts zero threads.
    metrics_port = telemetry.resolve_metrics_port(args.metrics_port,
                                                  tele_rank)
    if metrics_port and telemetry.is_configured():
        # None on a bind failure (stderr-noted): the live surface never
        # takes the serving process down. backend stamps dpt_build_info
        # (the federated-scrape identity satellite, ISSUE 15).
        import jax

        if telemetry.start_metrics_server(
                metrics_port, telemetry.get(),
                backend=jax.default_backend()) is not None:
            log_main(f"serving: /metrics + /healthz on :{metrics_port}")
    Deathwatch.arm(log=log_main)

    try:
        return _run(args, buckets)
    except BaseException as e:
        # every abnormal serving exit leaves a postmortem flight (the
        # train.py contract); clean SystemExit(0) is not abnormal
        if not (isinstance(e, SystemExit) and e.code in (0, None)):
            telemetry.flush_flight(
                cause=f"{type(e).__name__}: {e}",
                detail="serving abnormal exit",
                rc=e.code if isinstance(e, SystemExit) else 1)
        raise
    finally:
        # guarded on the module having loaded: the metrics-off path never
        # imports metrics_http at all (its zero-cost-when-off contract)
        if "distributed_pytorch_training_tpu.telemetry.metrics_http" \
                in sys.modules:
            telemetry.stop_metrics_server()
        telemetry.reset()


def _run(args, buckets) -> int:
    import jax

    from .. import telemetry
    from ..experiments.harness import (
        build_serving_engine, is_lm_model, lm_vocab, measure_serving,
    )
    from ..training import TrainConfig
    from ..utils.config import parse_model_overrides
    from ..utils.logging import log_main
    from .batching import RequestQueue, drain, serve_forever

    overrides = (parse_model_overrides(args.model_overrides)
                 if args.model_overrides else None)
    train_config = TrainConfig(
        seed=0, zero1=args.zero1, fsdp_explicit=args.fsdp_explicit,
        wire_dtype=args.wire_dtype, bucket_cap_mb=args.bucket_cap_mb)
    # Warm-restart compilation cache, keyed by (topology, config): a
    # restarted or autoscaled serving replica re-AOT-compiles its whole
    # bucket ladder — with the persistent cache on, those compiles load
    # from disk instead (the engine's per-program `compile` telemetry
    # spans are the cold-vs-warm instrument). DPT_COMPILE_CACHE tri-state;
    # "auto" refuses XLA:CPU (unsafe reloads — runtime.dist docstring).
    from ..runtime import compile_cache_dir, enable_persistent_compile_cache

    enable_persistent_compile_cache(compile_cache_dir(
        Path(args.output_dir) / ".jax_cache",
        topology=f"{jax.default_backend()}-{len(jax.devices())}dev"
                 + (f"-{args.mesh.replace('=', '').replace(',', '-')}"
                    if args.mesh else ""),
        config_tag=f"{args.model}-{args.serve_dtype}-rows{args.rows}"))

    if args.command == "serve":
        return _serve(args, buckets, overrides, train_config)
    if args.command == "fleet":
        return _fleet(args, buckets)

    if args.command == "bench" and args.continuous:
        from ..experiments.harness import measure_serving_continuous

        row = measure_serving_continuous(
            model_name=args.model, n_requests=args.requests,
            offered_rps=args.offered_load, buckets=buckets, rows=args.rows,
            max_new_tokens=args.max_new_tokens, kv_dtype=args.kv_dtype,
            page_size=args.page_size, mixed_want=args.mixed_want,
            replicas=args.replicas,
            kill_replica=args.kill_replica, model_overrides=overrides,
            ckpt_dir=args.ckpt_dir, seed=args.seed,
            optimizer=args.optimizer, momentum=args.momentum,
            weight_decay=args.weight_decay, train_config=train_config,
            mesh_spec=args.mesh, draft_model=args.draft,
            draft_k=args.draft_k, shared_frac=args.shared_frac,
            prefix_skip=not args.no_prefix_skip)
        if args.as_json:
            print(json.dumps(row, sort_keys=True, default=str))
        else:
            spec = (f", draft={row['draft']} k={row['draft_k']} "
                    f"accept {row['accept_ratio']} "
                    f"({row['accepted_per_verify']} tok/verify)"
                    if row.get("draft") else "")
            skip = (f", {row['prefill_skips']} prefill skips / "
                    f"{row['tail_resumes']} tail resumes"
                    + (f" (ttft warm {row['ttft_warm_p50_ms']}ms vs "
                       f"cold {row['ttft_cold_p50_ms']}ms)"
                       if "ttft_warm_p50_ms" in row else "")
                    if row.get("prefill_skips") or row.get("tail_resumes")
                    else "")
            log_main(
                f"serving bench [token-granular x{row['replicas']}]: "
                f"{row['model']} kv={row['kv_dtype']} "
                f"p50 {row['p50_ms']}ms p99 {row['p99_ms']}ms "
                f"ttft p50 {row['ttft_p50_ms']}ms at "
                f"{row['achieved_rps']}/{row['offered_rps']} req/s "
                f"({row['tokens_per_sec']} tok/s), KV "
                f"{row['paged_kv_bytes']}B vs dense "
                f"{row['dense_kv_bytes']}B ({row['kv_bytes_ratio']}x), "
                f"{row['compiles']} compiles "
                f"({row['recompiles_after_warmup']} after warmup, "
                f"{row['replica_deaths']} replica deaths)"
                + spec + skip)
            if row.get("contracts", {}).get("pass") is False:
                log_main(f"serving bench: CONTRACT VIOLATIONS: "
                         f"{row['contracts']['violations']}")
        return 0 if row.get("recompiles_after_warmup") == 0 else 1

    if args.command == "bench":
        row = measure_serving(
            model_name=args.model, n_requests=args.requests,
            offered_rps=args.offered_load, buckets=buckets, rows=args.rows,
            max_new_tokens=args.max_new_tokens,
            serve_dtype=args.serve_dtype, mixed_want=args.mixed_want,
            model_overrides=overrides,
            ckpt_dir=args.ckpt_dir, seed=args.seed,
            optimizer=args.optimizer, momentum=args.momentum,
            weight_decay=args.weight_decay, train_config=train_config,
            mesh_spec=args.mesh)
        if args.as_json:
            print(json.dumps(row, sort_keys=True, default=str))
        else:
            toks = (f" ({row['tokens_per_sec']} tok/s)"
                    if "tokens_per_sec" in row else "")
            log_main(
                f"serving bench: {row['model']} [{row['serve_dtype']}] "
                f"p50 {row['p50_ms']}ms p99 {row['p99_ms']}ms at "
                f"{row['achieved_rps']}/{row['offered_rps']} req/s{toks}, "
                f"{row['compiles']} compiles "
                f"({row['recompiles_after_warmup']} after warmup)")
            if row.get("contracts", {}).get("pass") is False:
                log_main(f"serving bench: CONTRACT VIOLATIONS: "
                         f"{row['contracts']['violations']}")
        return 0 if row.get("recompiles_after_warmup") == 0 else 1

    # -- smoke ---------------------------------------------------------------
    engine, mesh = build_serving_engine(
        jax.devices(), args.model, buckets=buckets, rows=args.rows,
        max_new_tokens=args.max_new_tokens, serve_dtype=args.serve_dtype,
        model_overrides=overrides, ckpt_dir=args.ckpt_dir,
        train_config=train_config, seed=args.seed,
        optimizer=args.optimizer, momentum=args.momentum,
        weight_decay=args.weight_decay, mesh_spec=args.mesh)
    if engine.checkpoint_info:
        info = engine.checkpoint_info
        log_main(f"serving: checkpoint label={info['label']} "
                 f"step={info['step']} verified={info['verified']} "
                 f"tree_digest={info['tree_digest']}")
    else:
        log_main("serving: NOTE: random-init weights (no --ckpt-dir) — "
                 "this smokes the serving path, not a trained model")

    if not engine.is_token:
        rng = np.random.RandomState(args.seed)
        logits = engine.serve_images(
            rng.randint(0, 256, (2, 32, 32, 3)).astype(np.uint8),
            mean=(0.4914, 0.4822, 0.4465), std=(0.247, 0.243, 0.262))
        log_main(f"serving smoke: {logits.shape[0]} images -> logits "
                 f"{logits.shape}, top-1 {logits.argmax(-1).tolist()}")
        return 0

    if args.prompt:
        prompts = [np.asarray([int(t) for t in args.prompt.split(",")],
                              np.int32)]
    else:
        rng = np.random.RandomState(args.seed)
        vocab = lm_vocab(args.model) if is_lm_model(args.model) else 256
        prompts = [rng.randint(0, vocab, n).astype(np.int32)
                   for n in (args.prompt_len, max(args.prompt_len // 2, 1),
                             min(args.prompt_len * 2, max(buckets)))]

    # the production wiring in miniature: queue + worker thread + SIGTERM
    # drain — smoke exercises the same path a real frontend would use
    queue = RequestQueue(buckets)
    stop = threading.Event()

    def on_sigterm(signum, frame):
        log_main("serving: SIGTERM — draining the queue, then exiting")
        stop.set()
        telemetry.flush_flight(cause="sigterm drain",
                               detail="serving graceful shutdown", rc=0)

    prev = signal.signal(signal.SIGTERM, on_sigterm)
    try:
        worker = threading.Thread(target=serve_forever,
                                  args=(engine, queue, stop),
                                  kwargs={"log": log_main}, daemon=True)
        worker.start()
        reqs = [queue.submit(p) for p in prompts]
        for req, prm in zip(reqs, prompts):
            res = req.result(timeout=600.0)
            log_main(
                f"serving smoke: prompt[{len(prm)} tok] bucket={res.bucket} "
                f"-> {res.tokens.tolist() if res.tokens.size else '[]'} "
                f"(prefill {res.prefill_s * 1e3:.1f}ms, decode "
                f"{res.decode_s * 1e3:.1f}ms)")
        stop.set()
        worker.join(timeout=60.0)
        # drain is idempotent here (queue already empty) — it exists so a
        # SIGTERM mid-smoke still completes accepted work before exit
        drain(engine, queue, log=log_main)
    finally:
        signal.signal(signal.SIGTERM, prev)
    log_main(f"serving smoke: ok ({engine.compiles} compiles)")
    return 0


def _serve(args, buckets, overrides, train_config) -> int:
    """ONE long-lived continuous-batching replica behind stdlib HTTP:
    POST /generate blocks the handler thread on the request's result
    (ThreadingHTTPServer gives each request its own thread; the slot
    scheduler worker is the single engine caller). SIGTERM drains."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import jax

    from .. import telemetry
    from ..experiments.harness import build_slot_engine
    from ..utils.logging import log_main
    from .batching import RequestQueue
    from .continuous import ContinuousScheduler

    engine, _ = build_slot_engine(
        jax.devices(), args.model, buckets=buckets, rows=args.rows,
        max_new_tokens=args.max_new_tokens, kv_dtype=args.kv_dtype,
        page_size=args.page_size, model_overrides=overrides,
        ckpt_dir=args.ckpt_dir, train_config=train_config, seed=args.seed,
        optimizer=args.optimizer, momentum=args.momentum,
        weight_decay=args.weight_decay, mesh_spec=args.mesh)
    engine.warmup()
    log_main(f"serving: slot engine ready — {engine.compiles} programs, "
             f"kv={args.kv_dtype} pages of {args.page_size} "
             f"({engine.paged_bytes()}B paged vs "
             f"{engine.dense_baseline_bytes()}B dense)")
    queue = RequestQueue(buckets)
    sched = ContinuousScheduler(engine, queue)
    stop = threading.Event()
    worker = threading.Thread(target=sched.run, args=(stop,),
                              kwargs={"log": log_main}, daemon=True)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):  # request logging rides telemetry
            pass

        def _reply(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                # the metrics port's /healthz is the richer step-fence
                # verdict; this one answers 'is the replica accepting'
                self._reply(200 if not stop.is_set() else 503,
                            {"draining": stop.is_set(),
                             "served": sched.served})
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n).decode() or "{}")
                tokens = np.asarray(body["tokens"], np.int32)
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            try:
                req = queue.submit(
                    tokens, max_new_tokens=body.get("max_new_tokens"),
                    temperature=float(body.get("temperature", 0.0)),
                    top_p=float(body.get("top_p", 1.0)),
                    seed=body.get("seed"))
                res = req.result(timeout=600.0)
            except Exception as e:  # noqa: BLE001 - one request, one reply
                self._reply(503, {"error": f"{type(e).__name__}: {e}"})
                return
            out = {"tokens": res.tokens.tolist(), "bucket": res.bucket,
                   "queue_wait_ms": round(res.queue_wait_s * 1e3, 3),
                   "decode_ms": round(res.decode_s * 1e3, 3)}
            if body.get("want_logits"):
                out["last_logits"] = [float(v) for v in res.last_logits]
            self._reply(200, out)

    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    port = httpd.server_address[1]

    def on_sigterm(signum, frame):
        log_main("serving: SIGTERM — draining the slot pool, then exiting")
        stop.set()

    prev = signal.signal(signal.SIGTERM, on_sigterm)
    worker.start()
    srv = threading.Thread(target=httpd.serve_forever, daemon=True)
    srv.start()
    log_main(f"serving: POST /generate on :{port} — SIGTERM drains")
    try:
        while not stop.wait(0.2):
            pass
    except KeyboardInterrupt:
        stop.set()
    finally:
        signal.signal(signal.SIGTERM, prev)
        queue.close()
        worker.join(timeout=600.0)
        httpd.shutdown()
    telemetry.flush_flight(cause="sigterm drain",
                           detail="serving replica graceful shutdown",
                           rc=0)
    log_main(f"serving: replica drained ({sched.served} served, "
             f"{engine.compiles} compiles)")
    return 0


def _fleet(args, buckets) -> int:
    """N `serve` replicas as supervised children (ServingFleet): ports
    base+r, metrics base+r (the child env's rank stamp applies the offset
    — the argv passes the BASE, resolve_metrics_port adds the rank),
    relaunch-on-death, SIGTERM drains the whole fleet."""
    from ..resilience.fleet import ServingFleet
    from ..telemetry.recorder import ALL_RANKS_ENV
    from ..utils.logging import log_main

    base = int(args.port)
    mbase = args.metrics_port

    def argv_for(rank: int, generation: int):
        argv = [sys.executable, "-m",
                "distributed_pytorch_training_tpu.serving", "serve",
                "--model", args.model, "--buckets",
                ",".join(str(b) for b in buckets),
                "--rows", str(args.rows),
                "--max-new-tokens", str(args.max_new_tokens),
                "--kv-dtype", args.kv_dtype,
                "--page-size", str(args.page_size),
                "--port", str(base + rank),
                "--output-dir",
                str(Path(args.output_dir) / f"replica{rank}"),
                "--seed", str(args.seed)]
        if args.model_overrides:
            argv += ["--model-overrides", args.model_overrides]
        if args.ckpt_dir:
            argv += ["--ckpt-dir", args.ckpt_dir]
        if args.mesh:
            argv += ["--mesh", args.mesh]
        if mbase:
            argv += ["--metrics-port", str(int(mbase))]
        if args.no_telemetry:
            argv += ["--no-telemetry"]
        return argv

    fleet = ServingFleet(
        argv_for, replicas=args.replicas,
        metrics_ports=([int(mbase) + r for r in range(args.replicas)]
                       if mbase else None),
        federation_port=args.federation_port,
        log_dir=Path(args.output_dir) / "fleet_logs",
        # every replica streams + serves /metrics, not just rank 0 —
        # the federation page must carry all of them
        env_extra={ALL_RANKS_ENV: "1"},
        log=log_main)
    stop = threading.Event()

    def on_sigterm(signum, frame):
        log_main("serving fleet: SIGTERM — draining every replica")
        stop.set()

    prev = signal.signal(signal.SIGTERM, on_sigterm)
    try:
        fleet.start()
        log_main(f"serving fleet: {args.replicas} replicas on ports "
                 f"{[base + r for r in range(args.replicas)]}")
        fleet.run(stop)
    finally:
        signal.signal(signal.SIGTERM, prev)
    report = fleet.report()
    if args.as_json:
        print(json.dumps(report, sort_keys=True, default=str))
    else:
        for rep in report["per_replica"]:
            log_main(f"serving fleet: replica {rep['rank']} — "
                     f"{rep['relaunches']} relaunches, "
                     f"rc history {rep['rc_history']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
