"""Paged KV cache: the host-side page allocator + serving config.

The HBM ceiling of the dense engine is its cache SHAPE: (rows,
bucket + max_new, heads, head_dim) per block, live for every slot whether
it serves a request or not, fp32 always. The paged cache breaks the shape
into fixed-size pages (models/layers.py `PagedKV`) and makes residency a
host-side ALLOCATION decision:

* **PagePool** is the allocator: a free list over physical pages 1..N-1
  (page 0 is the scratch page every unmapped table entry points at), with
  per-page refcounts so one physical page can back many slots.
* **Prefix sharing**: pages wholly covered by a prompt are keyed by the
  cumulative prefix hash (``data.pack.prompt_page_hashes``) — a request
  repeating an earlier prompt's prefix maps the SAME physical pages
  instead of recomputing/rewriting them. Safe by construction: identical
  weights + identical token prefix give bitwise-identical k/v, and the
  compiled prefill rewrites a shared page only with its own bytes, while
  decode writes always land past the last fully-covered prompt page.
* **Eviction**: a released prefix page keeps its hash and parks in an LRU
  retention list (refcount 0, still reusable); when the free list runs
  dry, the oldest retained page is evicted — its hash is forgotten and
  the page returns to general circulation. Allocation fails (request
  stays queued) only when free + evictable together cannot cover a
  request.
* **Byte accounting**: ``paged_kv_bytes`` vs ``dense_kv_bytes``
  (models/layers.py) is the bench's HBM story — int8 pages store 1 byte
  per element + one fp32 scale per (page, position, head) row, a >= 3x
  cut against the dense fp32 cache at the same config.

Quantization rides the SAME per-row int8 grid as the gradient wire
(``grad_sync._quantize_int8_rows``), so the exactness story is the wire
codec's: deterministic, bounded, and replica-identical — every replica
quantizes the same values to the same codes (PARITY.md).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..data.pack import prompt_page_hashes
from ..utils.locktrace import named_lock
from .engine import ServeConfig

KV_DTYPES = ("fp32", "int8")


@dataclasses.dataclass
class PagedServeConfig(ServeConfig):
    """`ServeConfig` plus the paged-cache knobs (serving/continuous.py).

    ``rows`` is the SLOT count of the continuous engine — the static row
    dimension of the one compiled decode step requests join and leave at
    token granularity. ``n_pages=0`` sizes the pool so every slot can hold
    a full (max bucket + max_new_tokens) context with no sharing — the
    fail-safe floor; smaller pools lean on prefix sharing + eviction,
    larger ones retain more shared prefixes.
    """

    page_size: int = 16
    n_pages: int = 0
    kv_dtype: str = "fp32"
    prefix_sharing: bool = True
    # Prefix-resident admission (ISSUE 19): when a prompt's leading pages
    # are already resident (prefix sharing mapped them), admission skips
    # the prefill dispatch — fully resident prompts go straight into
    # decode at the resumed position, partially resident ones prefill
    # only the fresh tail. fp32 pools only: the skip path's token #0
    # reads the dequantized pages where the cold prefill reads fresh
    # fp32, so an int8 skip could emit a different stream on a resident
    # vs cold replica and break the router's same-seed-retry invariant
    # (serving/continuous.py applies the gate).
    prefix_skip: bool = True
    # PR 6 fused-quantize tri-state for the int8 page codec: None = auto
    # (DPT_FUSED_QUANTIZE env, else TPU-only), True/False = forced. The
    # fused kernel is bit-identical to the XLA-composed reference
    # (ops/quantize.py), so this flips kernels, never page bytes.
    fused_quantize: Optional[bool] = None

    def __post_init__(self):
        super().__post_init__()
        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(f"kv_dtype {self.kv_dtype!r} is not one of "
                             f"{KV_DTYPES}")
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, "
                             f"got {self.page_size}")

    @property
    def cache_len(self) -> int:
        return max(self.buckets) + self.max_new_tokens

    @property
    def pages_per_slot(self) -> int:
        return -(-self.cache_len // self.page_size)

    @property
    def total_pages(self) -> int:
        """Physical pool size: the configured ``n_pages`` or the fail-safe
        floor (every slot fully resident, plus scratch page 0)."""
        floor = self.rows * self.pages_per_slot + 1
        return max(int(self.n_pages), floor) if self.n_pages else floor


@dataclasses.dataclass
class PageLease:
    """One slot's page holding: which table entries are real allocations
    (vs scratch), and which of them are shared prefix pages."""

    pages: np.ndarray          # (pages_per_slot,) int32, scratch-padded
    n_pages: int               # real entries: pages[:n_pages]
    shared: List[int] = dataclasses.field(default_factory=list)


class PagePool:
    """Thread-safe page allocator with refcounts, prefix sharing, and LRU
    eviction of retained prefix pages. Page ids are HOST integers — the
    device only ever sees the (rows, pages_per_slot) int32 table the
    scheduler assembles from leases."""

    def __init__(self, n_pages: int, page_size: int,
                 pages_per_slot: int, prefix_sharing: bool = True):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (scratch + 1), "
                             f"got {n_pages}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.prefix_sharing = bool(prefix_sharing)
        self._lock = named_lock("PagePool._lock")
        self._free: List[int] = list(range(1, self.n_pages))   # guarded-by: _lock
        self._ref: Dict[int, int] = {}                         # guarded-by: _lock
        self._by_hash: Dict[str, int] = {}                     # guarded-by: _lock
        self._hash_of: Dict[int, str] = {}                     # guarded-by: _lock
        # refcount-0 prefix pages, oldest first — the eviction queue
        self._retained: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()                          # guarded-by: _lock
        self.evictions = 0                                     # guarded-by: _lock
        self.prefix_hits = 0                                   # guarded-by: _lock

    # -- internals (lock held) ----------------------------------------------

    def _take_page(self) -> Optional[int]:   # lock-held: _lock
        if self._free:
            return self._free.pop()
        if self._retained:  # evict the LRU retained prefix page
            page, _ = self._retained.popitem(last=False)
            h = self._hash_of.pop(page, None)
            if h is not None:
                self._by_hash.pop(h, None)
            self.evictions += 1
            return page
        return None

    def _release_page(self, page: int) -> None:   # lock-held: _lock
        self._ref[page] -= 1
        if self._ref[page] > 0:
            return
        del self._ref[page]
        if page in self._hash_of:   # keep the prefix warm, evictable
            self._retained[page] = None
            self._retained.move_to_end(page)
        else:
            self._free.append(page)

    # -- the allocator API --------------------------------------------------

    def alloc(self, tokens: Sequence[int],
              n_positions: int) -> Optional[PageLease]:
        """Lease pages covering positions [0, n_positions) for a request
        whose prompt is ``tokens``: shared prefix pages first (refcount
        bump, no write needed beyond the idempotent rewrite), fresh pages
        for the rest. None when the pool cannot cover the request — the
        caller keeps it queued (admission control, not an error)."""
        need = -(-int(n_positions) // self.page_size)
        if need > self.pages_per_slot:
            raise ValueError(
                f"{n_positions} positions need {need} pages, over the "
                f"table's {self.pages_per_slot} per slot")
        hashes = (prompt_page_hashes(tokens, self.page_size)
                  if self.prefix_sharing else [])
        with self._lock:
            pages: List[int] = []
            shared: List[int] = []
            for h in hashes[:need]:
                page = self._by_hash.get(h)
                if page is None:
                    break   # prefix diverges from here on: fresh pages
                # claim AT MATCH TIME: refcount bump + unpark, so a dry
                # free list can never evict a just-matched refcount-0
                # retained page and re-lease it as a fresh page (the
                # same physical page at two logical offsets would let
                # the prefill scatter corrupt the shared prefix)
                self._ref[page] = self._ref.get(page, 0) + 1
                self._retained.pop(page, None)  # leased: not evictable
                pages.append(page)
                shared.append(page)
            fresh_start = len(pages)
            ok = True
            for i in range(fresh_start, need):
                page = self._take_page()
                if page is None:
                    ok = False
                    break
                pages.append(page)
            if not ok:      # roll back: nothing leased on failure
                for page in pages[fresh_start:]:
                    self._free.append(page)
                for page in shared:
                    self._release_page(page)  # re-parks retained prefixes
                return None
            for page in pages[fresh_start:]:
                self._ref[page] = self._ref.get(page, 0) + 1
            self.prefix_hits += len(shared)
            # register the fresh fully-covered prompt pages for future
            # sharing (the tail/decode pages carry no hash by design)
            for i in range(fresh_start, min(len(hashes), need)):
                h, page = hashes[i], pages[i]
                if h not in self._by_hash:
                    self._by_hash[h] = page
                    self._hash_of[page] = h
            row = np.zeros(self.pages_per_slot, np.int32)
            row[:need] = pages
            return PageLease(pages=row, n_pages=need, shared=shared)

    def release(self, lease: PageLease) -> None:
        """Return a lease's pages: refcounts drop; prefix pages park in
        the LRU retention queue, anonymous pages go straight to free."""
        with self._lock:
            for page in lease.pages[:lease.n_pages]:
                self._release_page(int(page))

    def rollback(self, lease: PageLease) -> None:
        """Undo an alloc whose admission ABORTED before any prefill
        dispatched (e.g. the draft pool refused its half). The lease's
        FRESH pages were hash-registered for sharing at alloc time but
        never written — a later identical prompt matching them would
        skip-admit onto garbage, so their hashes must be forgotten here.
        Pages this alloc matched as shared were written by an earlier
        admission and just release normally."""
        shared = set(map(int, lease.shared))
        with self._lock:
            for page in map(int, lease.pages[:lease.n_pages]):
                if page not in shared:
                    h = self._hash_of.pop(page, None)
                    if h is not None:
                        self._by_hash.pop(h, None)
                self._release_page(page)

    # -- observability -------------------------------------------------------

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free) + len(self._retained)

    def stats(self) -> dict:
        with self._lock:
            return {
                "n_pages": self.n_pages,
                "free": len(self._free),
                "retained": len(self._retained),
                "leased": len(self._ref),
                "shared_hashes": len(self._by_hash),
                "prefix_hits": self.prefix_hits,
                "evictions": self.evictions,
            }
