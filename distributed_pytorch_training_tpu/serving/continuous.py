"""Token-granular continuous batching over a paged KV cache.

PR 9's engine batches at ITERATION granularity: a group of requests
enters prefill together, decodes together, and exits together — a short
request waits for the longest batch-mate, and a new arrival waits for the
whole cycle. This module rebuilds the decode loop around SLOTS:

* `SlotEngine` owns ONE compiled decode step over a fixed pool of
  ``rows`` slots plus one compiled prefill per bucket rung. A request is
  admitted into a free slot by its bucket's prefill (slot index, prompt
  length, sampling knobs are all TRACED scalars — admission never
  recompiles), and from then on the shared decode step advances EVERY
  live slot one token per call. Requests join and leave the running
  batch at token granularity; the per-row position/budget masks are the
  substrate (`budget > 0` is liveness, inactive rows' cache writes are
  dropped).
* The KV cache is the PAGED pool (models/layers.py): the decode step
  gathers each slot's pages into the same dense view the bitwise-pinned
  decode attention consumes, and scatters the one fresh row back. Page
  residency is a host decision (serving/paged.py `PagePool`): prefix
  sharing, eviction, int8 pages — none of it touches the compiled step.
* Sampling is threaded PER REQUEST like training threads per-step RNG
  keys: each slot carries its request's (key, temperature, top_p), and
  the token at absolute position ``q`` is sampled with
  ``fold_in(request_key, q)`` — a function of the request alone, so the
  emitted stream is identical regardless of slot assignment, join order,
  or batch company (the determinism satellite pins this).
  ``temperature=0`` short-circuits to argmax — bitwise the PR 9 greedy
  path.
* `ContinuousScheduler` is the host loop: admit from the queue
  (``RequestQueue.take`` — FIFO, bucket-blind), run the decode step,
  mirror per-slot budgets in Python ints, and complete requests the
  moment THEIR budget hits zero (host fetches happen here, outside the
  AST-pinned ``_step_decode_loop``). ``slot_wait`` spans and the
  slot-occupancy / page-pool gauges are emitted here.

Layout: the page POOL is replicated over the mesh (pages are
slot-agnostic — prefix sharing crosses slots), while the per-slot
control arrays, page table, and every (rows, ...) intermediate of the
decode step SHARD over the batch axis whenever rows divide the shard
count — each device decodes its own slots and only the freshly written
k/v rows all-gather back into the pool (tokens, (L, rows, H, D) — tiny).
Everything is DONATED through both compiled programs, so each step
updates in place — the ``serving_paged`` HLO contract (analysis/) pins
the alias table the same way ``serving_decode`` pins the dense cache's.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..data.pack import bucket_for
from ..models.layers import (
    dense_kv_bytes,
    gather_paged_kv,
    paged_kv_bytes,
    scatter_paged_prefill,
    scatter_paged_rows,
    scatter_paged_window,
)
from ..parallel.mesh import batch_shard_count
from ..parallel.sharding import batch_sharding, replicated
from ..utils.locktrace import named_lock
from .batching import Request, RequestQueue, Result
from .engine import InferenceEngine
from .paged import PagedServeConfig, PageLease, PagePool


def sample_tokens(logits: jnp.ndarray, keys: jnp.ndarray,
                  temperatures: jnp.ndarray,
                  top_ps: jnp.ndarray) -> jnp.ndarray:
    """Per-row temperature/top-p sampling, (rows, vocab) logits -> (rows,)
    int32 tokens. Every op is row-independent and each row consumes its
    OWN key (``keys`` (rows, 2) uint32), so a row's token is a function of
    (its logits, its key, its knobs) alone — batch-mates, slot index, and
    pool size are invisible (the determinism contract). ``temperature <= 0``
    selects plain argmax — bitwise the dense engine's greedy path; the
    sampled branch's value is computed but discarded by the where."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / temps
    order = jnp.argsort(-scaled, axis=-1)           # descending
    sorted_l = jnp.take_along_axis(scaled, order, axis=-1)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # nucleus: keep the smallest prefix with mass >= top_p; the first
    # column always survives (cum - prob == 0 < top_p)
    keep = (cum - probs) < top_ps[:, None]
    masked = jnp.where(keep, sorted_l, jnp.finfo(jnp.float32).min)
    choice = jax.vmap(lambda k, row: jax.random.categorical(k, row))(
        keys, masked)
    sampled = jnp.take_along_axis(
        order, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
    return jnp.where(temperatures <= 0.0, greedy, sampled)


class SlotEngine(InferenceEngine):
    """The compiled half of continuous batching: one paged decode step
    over the whole slot pool, one B=1 paged prefill per bucket, state
    donated and chained device-to-device. ``compiles`` (inherited) is
    still the census the zero-recompile contract reads: after `warmup`,
    admissions, decode steps, and completions never compile."""

    def __init__(self, model, mesh, config: PagedServeConfig, params,
                 batch_stats: Any = None, rules=None):
        if not isinstance(config, PagedServeConfig):
            raise ValueError(
                "SlotEngine needs a PagedServeConfig (page_size/kv_dtype "
                "knobs) — plain ServeConfig drives the dense engine")
        super().__init__(model, mesh, config, params,
                         batch_stats=batch_stats, rules=rules)
        if not self.is_lm:
            raise ValueError("continuous batching decodes causal LMs only")
        if self.padded_len > model.max_position:
            raise ValueError(
                f"pages_per_slot * page_size = {self.padded_len} exceeds "
                f"the model's max_position {model.max_position} — the "
                "gathered dense view must fit the position table")
        self._rep = replicated(mesh)
        # Slot rows shard over the mesh's batch shards whenever they
        # divide — each device then decodes rows/n_shards slots instead of
        # redundantly decoding ALL of them (replicated state means every
        # device repeats the whole forward; on the 8-way CPU mesh that was
        # an 8x per-step compute tax). The page POOL stays replicated —
        # pages are slot-agnostic (prefix sharing crosses slots), so the
        # decode step reads it locally and the written rows all-gather
        # back (tiny: one (L, rows, H, D) per k/v per token).
        n_shards = batch_shard_count(mesh)
        self._row_sharded = n_shards > 1 and config.rows % n_shards == 0
        self.reset_state()

    def _validate_rows(self, n_shards: int) -> None:
        """Slot rows shard over the batch shards when divisible and fall
        back to replicated otherwise — the slot count is a scheduling
        knob, never a hard layout constraint; any rows >= 1 works."""

    def _row_sharding(self, ndim: int):
        """Sharding for a (rows, ...) slot-state array: leading dim over
        the batch shards when rows divide, replicated otherwise."""
        if self._row_sharded:
            return batch_sharding(self.mesh, ndim)
        return self._rep

    # -- state --------------------------------------------------------------

    @property
    def padded_len(self) -> int:
        """Width of the gathered dense view (pages_per_slot * page_size,
        >= bucket + max_new). The extra tail positions hold scratch/stale
        FINITE values the decode mask zeroes exactly — same argument as
        dense bucket padding."""
        cfg: PagedServeConfig = self.config
        return cfg.pages_per_slot * cfg.page_size

    def _init_control(self) -> Dict[str, jnp.ndarray]:
        cfg: PagedServeConfig = self.config
        rows, vocab = cfg.rows, self.model.padded_vocab
        return {
            # token occupying `positions` (written by the NEXT decode step)
            "tok": jnp.zeros((rows,), jnp.int32),
            "positions": jnp.zeros((rows,), jnp.int32),
            # tokens still to emit; budget > 0 IS slot liveness
            "budget": jnp.zeros((rows,), jnp.int32),
            "emitted": jnp.zeros((rows,), jnp.int32),
            # per-request sampling state, threaded like per-step RNG keys
            "keys": jnp.zeros((rows, 2), jnp.uint32),
            "temps": jnp.zeros((rows,), jnp.float32),
            "top_ps": jnp.ones((rows,), jnp.float32),
            # per-slot output accumulators, fetched ONCE at completion
            "out_buf": jnp.zeros((rows, cfg.max_new_tokens), jnp.int32),
            "last_buf": jnp.zeros((rows, vocab), jnp.float32),
            # prefix-skip support: the position whose decode logits should
            # be captured into last_buf (-1 = already captured — the
            # prefill path writes last_buf itself; a skip-admitted slot
            # never ran a prefill, so its first decode step captures the
            # last-prompt logits here, bitwise the prefill's by the
            # decode-vs-full parity pin)
            "last_pos": jnp.full((rows,), -1, jnp.int32),
        }

    def reset_state(self) -> None:
        """(Re)build the device state: zeroed paged pool (page 0 scratch —
        all-finite by construction), idle control rows, all-scratch page
        table. Compiled executables survive a reset (the census does not
        restart)."""
        cfg: PagedServeConfig = self.config
        pool = self.model.init_paged_pool(
            cfg.total_pages, cfg.page_size,
            quantized=cfg.kv_dtype == "int8")
        self._pool = jax.device_put(pool, self._rep)
        self._control = {
            k: jax.device_put(v, self._row_sharding(v.ndim))
            for k, v in self._init_control().items()}
        self._page_table = np.zeros(
            (cfg.rows, cfg.pages_per_slot), np.int32)
        self._table_dev = jax.device_put(self._page_table,
                                         self._row_sharding(2))

    def set_page_row(self, slot: int, row: np.ndarray) -> None:
        """Point one slot's table row at its leased pages (all-zeros =
        scratch = released). Host numpy is the source of truth; the device
        copy refreshes here — NEVER inside the decode loop."""
        self._page_table[slot] = row
        self._table_dev = jax.device_put(self._page_table,
                                         self._row_sharding(2))

    # -- compiled programs ---------------------------------------------------

    def _rep_aval(self, shape, dtype) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(shape, dtype, sharding=self._rep)

    def _row_aval(self, shape, dtype) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=self._row_sharding(len(shape)))

    def _pool_avals(self):
        return jax.tree_util.tree_map(
            lambda x: self._rep_aval(x.shape, x.dtype), self._pool)

    def _control_avals(self):
        return {k: self._row_aval(v.shape, v.dtype)
                for k, v in self._control.items()}

    def _make_paged_prefill(self, bucket: int) -> Callable:
        cfg: PagedServeConfig = self.config

        def prefill(served, pool, control, page_table, ids, length, slot,
                    want, key, temp, top_p):
            params = self._dequant(served)
            cache0 = self.model.init_cache(1, bucket)
            logits, cache = self.model.apply(
                self._apply_vars(params), ids, train=False, cache=cache0)
            # eval-forward-bitwise logits; token #0 comes from the last
            # REAL prompt position and occupies absolute position `length`
            last = jnp.take(logits[0], jnp.maximum(length - 1, 0), axis=0)
            k0 = jax.random.fold_in(key, length)
            t0 = sample_tokens(last[None, :], k0[None, :], temp[None],
                               top_p[None])[0]
            page_row = page_table[slot]
            # stack the per-block prompt k/v to (L, S, H, D): the pool is
            # layer-stacked, so the whole prompt lands in ONE scatter
            k_seqs = jnp.stack([c[0][0] for c in cache])
            v_seqs = jnp.stack([c[1][0] for c in cache])
            new_pool = scatter_paged_prefill(pool, page_row, k_seqs,
                                             v_seqs, length,
                                             fused=cfg.fused_quantize)
            out_row = jnp.zeros((cfg.max_new_tokens,), jnp.int32)
            out_row = out_row.at[0].set(t0)
            control = dict(control)
            control["tok"] = control["tok"].at[slot].set(t0)
            control["positions"] = control["positions"].at[slot].set(length)
            control["budget"] = control["budget"].at[slot].set(want - 1)
            control["emitted"] = control["emitted"].at[slot].set(1)
            control["keys"] = control["keys"].at[slot].set(key)
            control["temps"] = control["temps"].at[slot].set(temp)
            control["top_ps"] = control["top_ps"].at[slot].set(top_p)
            control["out_buf"] = control["out_buf"].at[slot].set(out_row)
            control["last_buf"] = control["last_buf"].at[slot].set(last)
            control["last_pos"] = control["last_pos"].at[slot].set(-1)
            return new_pool, control

        return prefill

    def _make_paged_decode(self) -> Callable:
        rows = self.config.rows
        fused = self.config.fused_quantize

        def decode(served, pool, control, page_table):
            params = self._dequant(served)
            active = control["budget"] > 0
            positions = control["positions"]
            tok = control["tok"]
            # read half: every slot's pages -> the dense view the
            # bitwise-pinned decode attention consumes unchanged. The pool
            # is layer-stacked, so this is ONE gather; the per-layer
            # slices below are fused into their attention consumers.
            k_all, v_all = gather_paged_kv(pool, page_table,
                                           dtype=self.model.dtype)
            cache = tuple((k_all[l], v_all[l])
                          for l in range(self.model.depth))
            logits, new_cache = self.model.apply(
                self._apply_vars(params), tok[:, None], train=False,
                cache=cache, cache_positions=positions)
            # write half: ONE fresh (H, D) row per live slot per layer,
            # restacked to (L, rows, H, D) -> ONE scatter back to the pool
            idx = positions[:, None, None, None]
            k_rows = jnp.stack([
                jnp.take_along_axis(k_new, idx, axis=1)[:, 0]
                for k_new, _ in new_cache])
            v_rows = jnp.stack([
                jnp.take_along_axis(v_new, idx, axis=1)[:, 0]
                for _, v_new in new_cache])
            new_pool = scatter_paged_rows(pool, page_table, positions,
                                          k_rows, v_rows, active,
                                          fused=fused)
            # the token at position p+1, from THIS request's key stream
            step_keys = jax.vmap(jax.random.fold_in)(
                control["keys"], positions + 1)
            nxt = sample_tokens(logits[:, 0], step_keys, control["temps"],
                                control["top_ps"])
            act = active.astype(jnp.int32)
            safe_row = jnp.where(active, jnp.arange(rows), rows)
            out_buf = control["out_buf"].at[
                safe_row, control["emitted"]].set(nxt, mode="drop")
            # a skip-admitted slot's first step captures the last-prompt
            # logits the prefill would have stored (bitwise, by the
            # decode-vs-full parity pin); -1 for everyone else
            cap = positions == control["last_pos"]
            new_control = dict(control)
            new_control["tok"] = jnp.where(active, nxt, tok)
            new_control["positions"] = positions + act
            new_control["budget"] = control["budget"] - act
            new_control["emitted"] = control["emitted"] + act
            new_control["out_buf"] = out_buf
            new_control["last_buf"] = jnp.where(
                cap[:, None], logits[:, 0], control["last_buf"])
            new_control["last_pos"] = jnp.where(
                cap, -1, control["last_pos"])
            return new_pool, new_control

        return decode

    def _rep_out(self, tree):
        return jax.tree_util.tree_map(lambda _: self._rep, tree)

    def _out_shardings(self, tree):
        """Each output keeps its aval's own sharding (pool replicated,
        control row-sharded) — donation requires in/out layouts to
        match."""
        return jax.tree_util.tree_map(lambda x: x.sharding, tree)

    def lower_paged_prefill(self, bucket: int):
        """The lowered B=1 admission step — slot/length/knobs traced, pool
        + control DONATED (exposed for the serving_paged contract)."""
        cfg: PagedServeConfig = self.config
        pool_avals = self._pool_avals()
        ctrl_avals = self._control_avals()
        scalar_i = self._rep_aval((), jnp.int32)
        scalar_f = self._rep_aval((), jnp.float32)
        outs = (pool_avals, ctrl_avals)
        return jax.jit(
            self._make_paged_prefill(bucket), donate_argnums=(1, 2),
            out_shardings=self._out_shardings(outs),
        ).lower(self._served, pool_avals, ctrl_avals,
                self._row_aval((cfg.rows, cfg.pages_per_slot), jnp.int32),
                self._rep_aval((1, bucket), jnp.int32),
                scalar_i, scalar_i, scalar_i,
                self._rep_aval((2,), jnp.uint32), scalar_f, scalar_f)

    def lower_paged_decode(self):
        """The lowered shared decode step: advances every live slot one
        token. Pool + control are DONATED — in-place page updates are what
        the page-table-donation HLO rule pins."""
        cfg: PagedServeConfig = self.config
        pool_avals = self._pool_avals()
        ctrl_avals = self._control_avals()
        outs = (pool_avals, ctrl_avals)
        return jax.jit(
            self._make_paged_decode(), donate_argnums=(1, 2),
            out_shardings=self._out_shardings(outs),
        ).lower(self._served, pool_avals, ctrl_avals,
                self._row_aval((cfg.rows, cfg.pages_per_slot), jnp.int32))

    # -- prefix-resident admission (ISSUE 19) --------------------------------

    @property
    def prefix_skip_enabled(self) -> bool:
        """Whether admission may skip/shorten prefill for resident
        prefixes. fp32 pools only: an int8 skip would read dequantized
        pages where the cold prefill reads fresh fp32 — residency would
        change the emitted stream and break the router's same-seed-retry
        determinism (PARITY.md documents the exclusion)."""
        cfg: PagedServeConfig = self.config
        return (cfg.prefix_sharing and cfg.prefix_skip
                and cfg.kv_dtype == "fp32")

    def _make_paged_skip(self) -> Callable:
        cfg: PagedServeConfig = self.config

        def skip(control, slot, last_tok, length, want, key, temp, top_p):
            # Fully resident prompt: no forward at all. The slot enters
            # the shared decode step at position length-1 holding the last
            # prompt token; that step rewrites the resident row with its
            # own bytes (idempotent — the prefix-sharing safety argument),
            # samples token #0 with fold_in(key, length) exactly like the
            # prefill path, and captures the last-prompt logits via
            # last_pos. budget = want (nothing emitted yet), vs the
            # prefill path's want - 1.
            control = dict(control)
            control["tok"] = control["tok"].at[slot].set(last_tok)
            control["positions"] = control["positions"].at[slot].set(
                length - 1)
            control["budget"] = control["budget"].at[slot].set(want)
            control["emitted"] = control["emitted"].at[slot].set(0)
            control["keys"] = control["keys"].at[slot].set(key)
            control["temps"] = control["temps"].at[slot].set(temp)
            control["top_ps"] = control["top_ps"].at[slot].set(top_p)
            control["out_buf"] = control["out_buf"].at[slot].set(
                jnp.zeros((cfg.max_new_tokens,), jnp.int32))
            control["last_pos"] = control["last_pos"].at[slot].set(
                length - 1)
            return control

        return skip

    def _make_paged_resume(self, bucket: int) -> Callable:
        """Tail-only prefill for a PARTIALLY resident prompt: feed just
        the uncovered suffix through the verify-window decode mode at
        offset ``start`` — each tail row attends the resident pages plus
        the in-window causal prefix, so its logits (and written k/v) are
        bitwise the full prefill's rows (the window parity pin)."""
        cfg: PagedServeConfig = self.config
        fused = cfg.fused_quantize

        def resume(served, pool, control, page_table, ids, start, length,
                   slot, want, key, temp, top_p):
            params = self._dequant(served)
            row_tbl = jax.lax.dynamic_slice_in_dim(page_table, slot, 1, 0)
            k_all, v_all = gather_paged_kv(pool, row_tbl,
                                           dtype=self.model.dtype)
            cache = tuple((k_all[l], v_all[l])
                          for l in range(self.model.depth))
            logits, new_cache = self.model.apply(
                self._apply_vars(params), ids, train=False, cache=cache,
                cache_positions=start[None])
            tail = length - start
            last = jnp.take(logits[0], jnp.maximum(tail - 1, 0), axis=0)
            k0 = jax.random.fold_in(key, length)
            t0 = sample_tokens(last[None, :], k0[None, :], temp[None],
                               top_p[None])[0]
            # commit the tail k/v rows at positions [start, length)
            win_pos = (start + jnp.arange(bucket))[None, :]     # (1, S)
            idxc = jnp.clip(win_pos[0], 0, self.padded_len - 1)
            k_wins = jnp.stack([jnp.take_along_axis(
                c[0], idxc[None, :, None, None], axis=1) for c in new_cache
            ])                                        # (L, 1, S, H, D)
            v_wins = jnp.stack([jnp.take_along_axis(
                c[1], idxc[None, :, None, None], axis=1) for c in new_cache
            ])
            act = (win_pos < length) & (win_pos < self.padded_len)
            new_pool = scatter_paged_window(pool, row_tbl, win_pos, k_wins,
                                            v_wins, act, fused=fused)
            out_row = jnp.zeros((cfg.max_new_tokens,), jnp.int32)
            out_row = out_row.at[0].set(t0)
            control = dict(control)
            control["tok"] = control["tok"].at[slot].set(t0)
            control["positions"] = control["positions"].at[slot].set(length)
            control["budget"] = control["budget"].at[slot].set(want - 1)
            control["emitted"] = control["emitted"].at[slot].set(1)
            control["keys"] = control["keys"].at[slot].set(key)
            control["temps"] = control["temps"].at[slot].set(temp)
            control["top_ps"] = control["top_ps"].at[slot].set(top_p)
            control["out_buf"] = control["out_buf"].at[slot].set(out_row)
            control["last_buf"] = control["last_buf"].at[slot].set(last)
            control["last_pos"] = control["last_pos"].at[slot].set(-1)
            return new_pool, control

        return resume

    def lower_paged_skip(self):
        """The lowered control-only skip admission — every knob traced,
        control DONATED (no pool, no forward: the zero-dispatch path)."""
        ctrl_avals = self._control_avals()
        scalar_i = self._rep_aval((), jnp.int32)
        scalar_f = self._rep_aval((), jnp.float32)
        return jax.jit(
            self._make_paged_skip(), donate_argnums=(0,),
            out_shardings=self._out_shardings(ctrl_avals),
        ).lower(ctrl_avals, scalar_i, scalar_i, scalar_i, scalar_i,
                self._rep_aval((2,), jnp.uint32), scalar_f, scalar_f)

    def lower_paged_resume(self, bucket: int):
        """The lowered tail-only prefill (partial residency) — pool +
        control DONATED like the full prefill's."""
        cfg: PagedServeConfig = self.config
        pool_avals = self._pool_avals()
        ctrl_avals = self._control_avals()
        scalar_i = self._rep_aval((), jnp.int32)
        scalar_f = self._rep_aval((), jnp.float32)
        outs = (pool_avals, ctrl_avals)
        return jax.jit(
            self._make_paged_resume(bucket), donate_argnums=(1, 2),
            out_shardings=self._out_shardings(outs),
        ).lower(self._served, pool_avals, ctrl_avals,
                self._row_aval((cfg.rows, cfg.pages_per_slot), jnp.int32),
                self._rep_aval((1, bucket), jnp.int32),
                scalar_i, scalar_i, scalar_i, scalar_i,
                self._rep_aval((2,), jnp.uint32), scalar_f, scalar_f)

    def _executable(self, kind: str, bucket: int):
        if kind not in ("paged_prefill", "paged_decode", "paged_skip",
                        "paged_resume"):
            return super()._executable(kind, bucket)
        key = (kind, bucket)
        if key not in self._compiled:
            lowered = {
                "paged_prefill": lambda: self.lower_paged_prefill(bucket),
                "paged_decode": self.lower_paged_decode,
                "paged_skip": self.lower_paged_skip,
                "paged_resume": lambda: self.lower_paged_resume(bucket),
            }[kind]()
            with telemetry.span("compile", program=kind, bucket=bucket):
                self._compiled[key] = lowered.compile()
            self.compiles += 1
        return self._compiled[key]

    def warmup(self) -> int:
        """Compile the decode step + every bucket's prefill (and, when
        prefix skip is live, the skip + per-bucket tail-resume programs)
        up front; the census is flat from here (the zero-recompile
        acceptance)."""
        self._executable("paged_decode", 0)
        for b in self.config.buckets:
            self._executable("paged_prefill", b)
        if self.prefix_skip_enabled:
            self._executable("paged_skip", 0)
            for b in self.config.buckets:
                self._executable("paged_resume", b)
        return self.compiles

    # -- the three runtime entries (scheduler-facing) ------------------------

    def admit(self, slot: int, tokens: np.ndarray, want: int,
              temperature: float, top_p: float, seed: int) -> int:
        """Dispatch the slot's admission prefill (token #0 is emitted
        inside) and return the bucket served. Does NOT fence: the prefill
        rides the donated pool/control chain and the scheduler's per-step
        fence bounds it — fencing every admission would serialize the
        whole admission wave behind host-device round trips (measured
        ~25% of capacity at saturation)."""
        cfg: PagedServeConfig = self.config
        bucket = bucket_for(len(tokens), cfg.buckets)
        ids = np.full((1, bucket), cfg.pad_id, np.int32)
        ids[0, :len(tokens)] = tokens
        key = np.asarray(jax.random.PRNGKey(int(seed)), np.uint32)
        dev = lambda x: jax.device_put(x, self._rep)  # noqa: E731
        pre = self._executable("paged_prefill", bucket)
        self._pool, self._control = pre(
            self._served, self._pool, self._control, self._table_dev,
            dev(ids), dev(np.int32(len(tokens))), dev(np.int32(slot)),
            dev(np.int32(want)), dev(key),
            dev(np.float32(temperature)), dev(np.float32(top_p)))
        return bucket

    def admit_skip(self, slot: int, last_tok: int, length: int, want: int,
                   temperature: float, top_p: float, seed: int) -> None:
        """Admit a FULLY prefix-resident request with no forward at all:
        one control-only program arms the slot to enter the shared decode
        step at the resumed position (see `_make_paged_skip` — token #0
        and the last-prompt logits come out of that step, bitwise the
        prefill path's)."""
        key = np.asarray(jax.random.PRNGKey(int(seed)), np.uint32)
        dev = lambda x: jax.device_put(x, self._rep)  # noqa: E731
        exe = self._executable("paged_skip", 0)
        self._control = exe(
            self._control, dev(np.int32(slot)), dev(np.int32(last_tok)),
            dev(np.int32(length)), dev(np.int32(want)), dev(key),
            dev(np.float32(temperature)), dev(np.float32(top_p)))

    def admit_resume(self, slot: int, tokens: np.ndarray, start: int,
                     want: int, temperature: float, top_p: float,
                     seed: int) -> int:
        """Admit a PARTIALLY resident request: prefill only the uncovered
        tail ``tokens[start:]`` through the tail bucket's resume program
        (verify-window forward at offset ``start`` over the resident
        pages). Returns the tail bucket served."""
        cfg: PagedServeConfig = self.config
        tail = tokens[start:]
        bucket = bucket_for(len(tail), cfg.buckets)
        ids = np.full((1, bucket), cfg.pad_id, np.int32)
        ids[0, :len(tail)] = tail
        key = np.asarray(jax.random.PRNGKey(int(seed)), np.uint32)
        dev = lambda x: jax.device_put(x, self._rep)  # noqa: E731
        exe = self._executable("paged_resume", bucket)
        self._pool, self._control = exe(
            self._served, self._pool, self._control, self._table_dev,
            dev(ids), dev(np.int32(start)), dev(np.int32(len(tokens))),
            dev(np.int32(slot)), dev(np.int32(want)), dev(key),
            dev(np.float32(temperature)), dev(np.float32(top_p)))
        return bucket

    def decode_step(self) -> None:
        """One compiled decode step over the whole slot pool — every
        chained value stays on device (no fetch; the scheduler's
        ``_step_decode_loop`` is the AST-pinned caller)."""
        dec = self._executable("paged_decode", 0)
        self._pool, self._control = dec(
            self._served, self._pool, self._control, self._table_dev)

    def fetch_slot(self, slot: int) -> Tuple[np.ndarray, np.ndarray]:
        """ONE host fetch of a finished slot's outputs (tokens row +
        last-prompt logits) — completion-time only, never in the loop."""
        return jax.device_get((self._control["out_buf"][slot],
                               self._control["last_buf"][slot]))

    # -- byte accounting -----------------------------------------------------

    def paged_bytes(self) -> int:
        """At-rest bytes of the live paged pool (codes + scales when
        int8); compare `kv_cache_bytes` (inherited) for the dense fp32
        baseline the >= 3x cut is measured against."""
        return paged_kv_bytes(self._pool)

    def dense_baseline_bytes(self) -> int:
        """What the PR 9 dense engine would hold at this config, fp32."""
        cfg: PagedServeConfig = self.config
        return dense_kv_bytes(
            cfg.rows, max(cfg.buckets) + cfg.max_new_tokens,
            self.model.num_heads,
            self.model.hidden_dim // self.model.num_heads,
            self.model.depth)


@dataclasses.dataclass
class _SlotState:
    """Host mirror of one live slot: enough to detect completion without
    touching the device (the device's budget arithmetic is replayed in
    Python ints, one decrement per decode step)."""

    req: Request
    lease: PageLease
    bucket: int
    want: int
    left: int  # tokens still to emit (device budget mirror)


class ContinuousScheduler:
    """The host loop: queue -> slots -> compiled steps -> results.

    Single-threaded over the engine (the device programs chain donated
    state, so there is exactly one legal caller at a time); thread-safety
    toward producers lives in `RequestQueue`. `run` is the worker-loop
    analogue of batching.serve_forever — stop means DRAIN (admitted and
    queued work completes; new work is refused), `kill` is the chaos hook
    (fail everything in flight, the router resubmits elsewhere)."""

    def __init__(self, engine: SlotEngine, queue: RequestQueue):
        cfg: PagedServeConfig = engine.config
        self.engine = engine
        self.queue = queue
        self.pool = PagePool(cfg.total_pages, cfg.page_size,
                             cfg.pages_per_slot,
                             prefix_sharing=cfg.prefix_sharing)
        self.free_slots: List[int] = list(range(cfg.rows))  # guarded-by: _lock
        self.running: Dict[int, _SlotState] = {}            # guarded-by: _lock
        self.pending: List[Request] = []                    # guarded-by: _lock
        self._t_popped: Dict[int, float] = {}               # guarded-by: _lock
        self.served = 0                                     # guarded-by: _lock
        self.killed = False                                 # guarded-by: _lock
        # serializes step() against kill(): kill runs on the CALLER's
        # thread (InProcessReplica.kill) while the worker is mid-step,
        # and without the lock it races the running/pending iteration
        # (dict changed size) and can double-resolve a request that is
        # completing at the instant of death
        self._lock = named_lock("ContinuousScheduler._lock")
        # max decode steps per fence when nothing is waiting to join
        # (see step()); 1 restores strict fence-per-token behavior
        self.burst_steps = 4
        # prefix-resident admission census (ISSUE 19): how many
        # admissions skipped prefill entirely vs prefilled only a tail
        self.prefill_skips = 0                              # guarded-by: _lock
        self.tail_resumes = 0                               # guarded-by: _lock

    # -- admission -----------------------------------------------------------

    def _gauges(self) -> None:   # lock-held: _lock
        cfg: PagedServeConfig = self.engine.config
        telemetry.gauge("serving_slot_occupancy",
                        len(self.running) / max(cfg.rows, 1))
        telemetry.gauge("serving_page_pool_free", self.pool.free_pages())
        # the router's load signal: everything accepted but unfinished
        # (HttpReplica.queue_depth scrapes this off /metrics)
        telemetry.gauge("serving_queue_depth",
                        len(self.queue) + len(self.pending)
                        + len(self.running))

    def _try_admit(self, req: Request) -> bool:   # lock-held: _lock
        """One admission attempt: needs a free slot AND a page lease.
        False means 'not now' (the request stays pending) — admission
        pressure is absorbed here, never by a recompile.

        With prefix skip live (fp32 pools, `prefix_skip_enabled`), the
        lease's shared-page count decides the prefill's fate: covered >=
        len(prompt) - 1 positions resident -> NO prefill dispatch at all
        (the slot enters decode at the resumed position; the at-most-one
        uncovered position is the one the first decode step writes
        anyway); partially covered -> a tail-only prefill over just the
        fresh pages. Cold prompts take the classic full prefill."""
        if not self.free_slots:
            return False
        cfg: PagedServeConfig = self.engine.config
        want = cfg.max_new_tokens if req.max_new_tokens is None else \
            min(int(req.max_new_tokens), cfg.max_new_tokens)
        want = max(want, 1)
        lease = self.pool.alloc(req.tokens, len(req.tokens) + want)
        if lease is None:
            return False
        if not self._draft_admit(req, lease, want):
            # rollback, NOT release: the lease's fresh pages were
            # hash-registered at alloc time but never prefilled — a
            # plain release would park them as "resident" and a retry
            # of the same prompt would skip-admit onto garbage KV
            self.pool.rollback(lease)
            return False
        slot = self.free_slots.pop()
        self.engine.set_page_row(slot, lease.pages)
        n = len(req.tokens)
        covered = len(lease.shared) * cfg.page_size
        t0 = time.perf_counter()
        skip_ok = getattr(self.engine, "prefix_skip_enabled", False)
        if skip_ok and covered >= n - 1 and covered > 0:
            self.engine.admit_skip(slot, int(req.tokens[-1]), n, want,
                                   req.temperature, req.top_p, req.seed)
            bucket = bucket_for(n, cfg.buckets)
            left = want   # nothing emitted yet: decode emits all `want`
            self.prefill_skips += 1
            telemetry.span_event("prefill_skip", time.perf_counter() - t0,
                                 slot=slot, request=req.id,
                                 resident=covered)
        elif skip_ok and covered > 0:
            bucket = self.engine.admit_resume(
                slot, req.tokens, covered, want, req.temperature,
                req.top_p, req.seed)
            left = want - 1
            self.tail_resumes += 1
            telemetry.span_event("prefill", time.perf_counter() - t0,
                                 bucket=bucket, slot=slot, request=req.id,
                                 resumed=covered)
        else:
            bucket = self.engine.admit(slot, req.tokens, want,
                                       req.temperature, req.top_p,
                                       req.seed)
            left = want - 1
            telemetry.span_event("prefill", time.perf_counter() - t0,
                                 bucket=bucket, slot=slot, request=req.id)
        now = time.perf_counter()
        # t_first_token stays None until the NEXT step fence — admission
        # only dispatched device work; step() stamps it once the fence
        # proves token #0 landed. The spans above are the dispatch cost.
        telemetry.span_event(
            "slot_wait", now - self._t_popped.pop(req.id, now),
            request=req.id, slot=slot)
        self.running[slot] = _SlotState(req=req, lease=lease, bucket=bucket,
                                        want=want, left=left)
        self._post_admit(slot, req)
        self._gauges()
        return True

    def _draft_admit(self, req: Request, lease: PageLease,
                     want: int) -> bool:   # lock-held: _lock
        """Speculative hook: lease + prefill the DRAFT pool for this
        request before the target admission commits (False aborts the
        attempt — the target lease is rolled back). The plain scheduler
        has no draft."""
        return True

    def _post_admit(self, slot: int, req: Request) -> None:  # lock-held: _lock
        """Speculative hook: called once the target admission landed in
        ``running`` (the draft engine points its page row here)."""

    def _post_complete(self, slot: int) -> None:   # lock-held: _lock
        """Speculative hook: a slot finished — release its draft lease."""

    def _admit_pending(self) -> None:   # lock-held: _lock
        still: List[Request] = []
        for req in self.pending:
            if not self._try_admit(req):
                still.append(req)
        self.pending = still

    def _pull(self, timeout: float = 0.005) -> None:   # lock-held: _lock
        # keep at most ~2 pool-fulls on deck; never block while slots are
        # actively decoding (the queue wait is for the idle loop only)
        cap = 2 * self.engine.config.rows - len(self.pending)
        if cap <= 0:
            return
        got = self.queue.take(cap,
                              timeout=0.0 if self.running else timeout)
        now = time.perf_counter()
        for req in got:
            self._t_popped[req.id] = now
        self.pending.extend(got)

    # -- the decode hot loop -------------------------------------------------

    def _step_decode_loop(self, n_steps: int) -> None:   # lock-held: _lock
        """``n_steps`` compiled decode steps, mirrors replayed in Python —
        NO host fetch in here (the ``no-host-sync-in-decode`` lint pins
        this function by name). Completion fetches happen afterwards, in
        `_complete`."""
        for _ in range(n_steps):
            self.engine.decode_step()
            for st in self.running.values():
                if st.left > 0:
                    st.left -= 1

    def _advance(self) -> None:   # lock-held: _lock
        """Advance every live slot: the plain scheduler runs 1..burst
        compiled decode steps (one token each); the speculative scheduler
        (serving/speculative.py) overrides this with one draft-propose +
        verify round (up to K+1 tokens per fence). Either way the caller
        fences afterwards and completes finished slots."""
        steps = 1
        if not self.pending and not len(self.queue):
            steps = max(1, min(min(st.left for st in
                                   self.running.values()),
                               self.burst_steps))
        self._step_decode_loop(steps)

    def _complete_finished(self) -> None:   # lock-held: _lock
        t0 = time.perf_counter()
        done = [slot for slot, st in self.running.items() if st.left == 0]
        for slot in done:
            st = self.running.pop(slot)
            toks, last = self.engine.fetch_slot(slot)
            now = time.perf_counter()
            first = st.req.t_first_token or t0
            res = Result(tokens=np.asarray(toks[:st.want], np.int32),
                         last_logits=np.asarray(last),
                         bucket=st.bucket,
                         queue_wait_s=max(0.0, first - st.req.t_submit),
                         decode_s=max(0.0, now - first))
            self.pool.release(st.lease)
            self.engine.set_page_row(
                slot, np.zeros(self.engine.config.pages_per_slot, np.int32))
            self._post_complete(slot)
            self.free_slots.append(slot)
            st.req.set_result(res)
            self.served += 1
        if done:
            self._gauges()

    # -- lifecycle -----------------------------------------------------------

    def step(self) -> bool:
        """One scheduling iteration: pull, admit, decode one token for
        every live slot, complete. Returns whether any work remains in
        flight or pending.

        The fence bounds dispatch depth: Python dispatches faster than
        the device decodes, and without it the queued-step backlog grows
        without bound — every completion fetch then waits behind the
        WHOLE backlog (the donated pool chain serializes), and
        per-request latency balloons with uptime. It must fence on the
        step's own OUTPUT: any earlier buffer was already donated into
        this dispatch and cannot be blocked on. It is a device fence, not
        a host transfer — the per-token no-host-sync contract
        (`_step_decode_loop`) is untouched.

        When NOTHING is waiting to join (queue and pending both empty),
        the loop bursts up to `burst_steps` decode steps before fencing —
        no slot can finish earlier than its remaining budget, so the
        burst never delays a completion, and a request arriving mid-burst
        waits at most `burst_steps` tokens for admission (the
        token-granularity bound, traded explicitly for fewer host-device
        round trips on long decodes).

        The whole iteration runs under the scheduler lock: `kill` (the
        caller-thread chaos hook) waits for the step boundary, so it can
        never mutate running/pending mid-iteration or error a request
        this step is concurrently completing."""
        with self._lock:
            if self.killed:
                return False
            self._pull()
            self._admit_pending()
            if self.running:
                self._advance()
                jax.block_until_ready(self.engine._control["tok"])
                # the fence proves every dispatched prefill's token #0
                # landed: the honest (if slightly late) TTFT stamp
                now = time.perf_counter()
                for st in self.running.values():
                    if st.req.t_first_token is None:
                        st.req.t_first_token = now
                self._complete_finished()
            return bool(self.running or self.pending)

    def run(self, stop: threading.Event, log=None) -> int:
        """Serve until ``stop`` is set AND everything accepted has
        completed (stop = drain, the SIGTERM contract). Returns requests
        served."""
        # unlocked reads of killed/running/pending/served below are the
        # worker's OWN loop control + post-mortem logging: killed is a
        # monotonic flag step() re-checks under the lock before touching
        # anything, and after kill() the collections are already cleared
        while not self.killed:  # analysis: disable=guarded-by
            if stop.is_set():
                self.queue.close()
            busy = self.step()
            if stop.is_set() and not busy and not len(self.queue):
                break
        if self.killed and log is not None:  # analysis: disable=guarded-by
            log("serving: scheduler killed with "
                f"{len(self.running) + len(self.pending)} in flight")  # analysis: disable=guarded-by
        return self.served  # analysis: disable=guarded-by

    def drain(self, log=None) -> int:
        """Finish everything queued + in flight, then return — wrapped in
        the ``drain`` span like the iteration-granular path."""
        stop = threading.Event()
        stop.set()
        # span attrs are a racy diagnostic snapshot, deliberately taken
        # without stalling the worker's step for it
        with telemetry.span("drain",
                            pending=len(self.queue) + len(self.pending),  # analysis: disable=guarded-by
                            running=len(self.running)):  # analysis: disable=guarded-by
            return self.run(stop, log=log)

    def kill(self, err: Optional[BaseException] = None) -> List[Request]:
        """Chaos hook: fail every in-flight, pending, AND still-queued
        request (the injected replica death). Returns the failed requests
        — the router resubmits them to surviving replicas.

        Runs under the scheduler lock, so the death lands at a step
        boundary: requests the in-flight step already completed are out
        of `running` (resolved exactly once, as results), everything
        else fails here exactly once."""
        with self._lock:
            self.killed = True
            err = err or RuntimeError("replica died")
            failed: List[Request] = []
            for st in self.running.values():
                st.req.set_error(err)
                failed.append(st.req)
            for req in self.pending:
                req.set_error(err)
                failed.append(req)
            # accepted-but-unpulled requests die with the replica too:
            # left parked in the closed queue they would hang their
            # waiters forever (no worker remains to pull them)
            self.queue.close()
            for req in self.queue.take(len(self.queue) + 1, timeout=0.0):
                req.set_error(err)
                failed.append(req)
            self.running.clear()
            self.pending.clear()
            return failed


def serve_continuous(engine: SlotEngine, queue: RequestQueue,
                     stop: threading.Event, log=None) -> int:
    """Drop-in worker-loop twin of ``batching.serve_forever`` for the
    continuous engine (the CLI runs one per replica thread)."""
    return ContinuousScheduler(engine, queue).run(stop, log=log)
