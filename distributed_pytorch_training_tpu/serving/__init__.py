"""serving/ — manifest-verified batched inference on the training stack.

The path from a training checkpoint to a served token, built from the
pieces the training side already ships: ``training/checkpoint.py``'s
manifest-verified restore for the weights, ``data/pack.py``'s bucket
ladder for the shapes, ``models/gpt2.py``'s cache-aware forward for
prefill + KV-cache decode, the grad-sync int8 codec grid for
weight-at-rest quantization, ``resilience/`` for liveness + drain, and
``telemetry/`` for the latency story (queue_wait / prefill / decode /
drain spans).

Entry points: the ``serving`` console script (``smoke`` / ``bench``), or
`InferenceEngine` + `RequestQueue` directly.
"""

from .batching import Request, RequestQueue, Result, drain, serve_forever
from .engine import (
    InferenceEngine, QuantizedLeaf, ServeConfig, dequantize_params,
    int8_weight_bytes, quantize_params,
)

__all__ = [
    "InferenceEngine", "QuantizedLeaf", "Request", "RequestQueue", "Result",
    "ServeConfig", "dequantize_params", "drain", "int8_weight_bytes",
    "quantize_params", "serve_forever",
]
