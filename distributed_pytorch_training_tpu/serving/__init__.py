"""serving/ — manifest-verified batched inference on the training stack.

The path from a training checkpoint to a served token, built from the
pieces the training side already ships: ``training/checkpoint.py``'s
manifest-verified restore for the weights, ``data/pack.py``'s bucket
ladder for the shapes, ``models/gpt2.py``'s cache-aware forward for
prefill + KV-cache decode, the grad-sync int8 codec grid for
weight-at-rest quantization, ``resilience/`` for liveness + drain, and
``telemetry/`` for the latency story (queue_wait / prefill / decode /
drain spans).

Two batching disciplines share the stack. The iteration-granular path
(`InferenceEngine` + `serve_forever`) forms a batch, decodes it to
completion, forms the next. The token-granular path (`SlotEngine` +
`ContinuousScheduler`, ISSUE 17) keeps ONE compiled decode program
running over a fixed slot pool backed by a paged — optionally int8 —
KV cache (`PagedServeConfig` / `PagePool`), admitting and retiring
requests between tokens with zero recompiles. `Router` spreads requests
over N replicas of either and resubmits on replica death with the
request's sampling seed pinned, so a retried request samples the
identical stream.

Entry points: the ``serving`` console script (``smoke`` / ``bench`` /
``serve`` / ``fleet``), or the classes directly.
"""

from .batching import Request, RequestQueue, Result, drain, serve_forever
from .continuous import (
    ContinuousScheduler, SlotEngine, sample_tokens, serve_continuous,
)
from .engine import (
    InferenceEngine, QuantizedLeaf, ServeConfig, dequantize_params,
    int8_weight_bytes, quantize_params,
)
from ..models.layers import dense_kv_bytes, paged_kv_bytes
from .paged import PagedServeConfig, PagePool
from .router import (
    HttpReplica, InProcessReplica, ReplicaDead, Router, RouterRequest,
)

__all__ = [
    "ContinuousScheduler", "HttpReplica", "InProcessReplica",
    "InferenceEngine", "PagePool", "PagedServeConfig", "QuantizedLeaf",
    "ReplicaDead", "Request", "RequestQueue", "Result", "Router",
    "RouterRequest", "ServeConfig", "SlotEngine", "dense_kv_bytes",
    "dequantize_params", "drain", "int8_weight_bytes", "paged_kv_bytes",
    "quantize_params", "sample_tokens", "serve_continuous",
    "serve_forever",
]
