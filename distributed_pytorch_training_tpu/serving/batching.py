"""Request queue + continuous batch assembly for the inference engine.

The serving hot path is shaped by one constraint: XLA compiles per input
SHAPE, so the engine may only ever see a small static set of shapes (one
per bucket in the ladder). Everything ragged about real traffic — arrival
times, prompt lengths, burst sizes — is absorbed HERE, on the host:

* ``RequestQueue`` is the thread-safe front door. Producers (RPC handlers,
  the bench's load generator) ``submit`` token prompts and block on the
  returned ``Request`` until the engine fills its result.
* ``next_batch`` drains the queue into ONE bucket-compatible group:
  the oldest request picks the bucket (``data.pack.bucket_for`` — smallest
  rung that fits), and every queued request that fits the same rung rides
  along, up to the engine's row budget. This is continuous batching at
  iteration granularity: a request never waits for a "full" batch — it
  joins the very next engine cycle — and a long prompt never blocks a
  burst of short ones behind a shape it doesn't share.
* ``serve_forever`` is the engine worker loop the CLI runs on a thread:
  pop a group, ``engine.serve_tokens`` it, fill results, repeat; on stop,
  DRAIN — finish everything already queued (the SIGTERM contract: accepted
  work completes, new work is refused), under a ``drain`` telemetry span.

Per-request ``queue_wait`` (submit -> popped) is emitted as a telemetry
span so the latency story decomposes: queue_wait is the load/provisioning
share, prefill/decode the compute share (``telemetry summary`` buckets all
four).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..data.pack import bucket_for
from ..utils.locktrace import named_condition, named_lock


@dataclasses.dataclass
class Result:
    """What the engine hands back for one request."""

    tokens: np.ndarray        # (n_generated,) int32 greedy continuation
    last_logits: np.ndarray   # (vocab,) fp32 logits at the last prompt token
    prompt_logits: Optional[np.ndarray] = None  # (len, vocab) when requested
    bucket: int = 0
    queue_wait_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0


class Request:
    """One submitted prompt; waitable. ``result()`` blocks until the engine
    (or a drain-time rejection) resolves it."""

    _ids = iter(range(1, 1 << 62))   # guarded-by: _ids_lock
    _ids_lock = named_lock("Request._ids_lock")

    def __init__(self, tokens: np.ndarray,
                 return_prompt_logits: bool = False,
                 max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 seed: Optional[int] = None):
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 1 or tokens.size == 0:
            raise ValueError(
                f"a request is a non-empty 1-D token array, got shape "
                f"{tokens.shape}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        with Request._ids_lock:
            self.id = next(Request._ids)
        self.tokens = tokens
        self.return_prompt_logits = return_prompt_logits
        # per-request sampling knobs (the continuous engine threads these
        # per slot, like training's per-step RNG keys; temperature 0.0 is
        # the pinned greedy path, bitwise). seed defaults to the request
        # id so two unseeded requests never share a stream.
        self.max_new_tokens = max_new_tokens
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.seed = int(self.id if seed is None else seed)
        self.t_submit = time.perf_counter()
        self.t_done: Optional[float] = None  # set at resolution (bench read)
        self.t_first_token: Optional[float] = None  # TTFT (prefill emits #0)
        # _result/_error are Event-synchronized, not locked: exactly one
        # resolver writes them, then _done.set() publishes (the Event's
        # internal lock is the happens-before edge result() reads through)
        self._done = threading.Event()
        self._result: Optional[Result] = None
        self._error: Optional[BaseException] = None

    def set_result(self, result: Result) -> None:
        self._result = result
        self.t_done = time.perf_counter()
        self._done.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self.t_done = time.perf_counter()
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> Result:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} still pending")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


class RequestQueue:
    """Thread-safe FIFO of pending requests with bucket-aware draining."""

    def __init__(self, buckets: Sequence[int]):
        if not buckets:
            raise ValueError("the bucket ladder must have at least one rung")
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self._q: Deque[Request] = collections.deque()   # guarded-by: _cv
        self._cv = named_condition("RequestQueue._cv")
        self._closed = False                            # guarded-by: _cv

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)

    def submit(self, tokens: np.ndarray,
               return_prompt_logits: bool = False, **kw) -> Request:
        """Enqueue one prompt (``**kw``: the per-request sampling knobs —
        max_new_tokens/temperature/top_p/seed — `Request` validates them).
        Raises on a closed (draining) queue — the SIGTERM contract:
        accepted work completes, new work is refused — and on prompts no
        bucket fits (bucket_for's loud rejection beats a truncated
        serve)."""
        req = Request(tokens, return_prompt_logits=return_prompt_logits,
                      **kw)
        bucket_for(len(req.tokens), self.buckets)  # validate: raises if huge
        with self._cv:
            if self._closed:
                raise RuntimeError(
                    "request queue is closed (draining for shutdown)")
            self._q.append(req)
            self._cv.notify()
        return req

    def close(self) -> None:
        """Refuse new submissions; queued requests stay servable (drain)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def next_batch(self, max_rows: int,
                   timeout: Optional[float] = 0.05) -> List[Request]:
        """Pop the next bucket-compatible group (<= max_rows requests).

        The OLDEST pending request picks the bucket; younger requests join
        iff they fit the same rung (in queue order — no starvation: the
        head of the queue is always served first). Returns [] on timeout
        or when the queue is closed and empty (the drain-finished signal).
        """
        with self._cv:
            if not self._q:
                if self._closed:
                    return []
                self._cv.wait(timeout)
            if not self._q:
                return []
            head = self._q.popleft()
            bucket = bucket_for(len(head.tokens), self.buckets)
            group = [head]
            keep: List[Request] = []
            while self._q and len(group) < max_rows:
                req = self._q.popleft()
                if bucket_for(len(req.tokens), self.buckets) == bucket:
                    group.append(req)
                else:
                    keep.append(req)
            # non-matching requests keep their queue order at the FRONT
            self._q.extendleft(reversed(keep))
        now = time.perf_counter()
        for req in group:
            telemetry.span_event("queue_wait", now - req.t_submit,
                                 request=req.id, bucket=bucket)
        return group

    def take(self, max_n: int,
             timeout: Optional[float] = 0.05) -> List[Request]:
        """Pop up to ``max_n`` requests in FIFO order, bucket-blind — the
        token-granular admission path (serving/continuous.py): the slot
        engine prefills each request on its OWN bucket's program, so there
        is no shared-shape constraint and no reason to hold a short prompt
        back behind a long one. Returns [] on timeout or when closed and
        empty (the drain-finished signal); queue_wait here is only the
        queue share — slot admission waits get their own ``slot_wait``
        span."""
        with self._cv:
            if not self._q:
                if self._closed:
                    return []
                self._cv.wait(timeout)
            group = [self._q.popleft()
                     for _ in range(min(max_n, len(self._q)))]
        now = time.perf_counter()
        for req in group:
            telemetry.span_event("queue_wait", now - req.t_submit,
                                 request=req.id)
        return group


def serve_forever(engine, queue: RequestQueue,
                  stop: threading.Event, log=None) -> int:
    """The engine worker loop: drain the queue through the engine until
    ``stop`` is set AND the queue is empty (stop means drain, not abandon).
    Returns the number of requests served. A failed batch fails exactly its
    own requests (their ``result()`` re-raises); the loop itself survives —
    one malformed request must not take the server down.
    """
    served = 0
    while True:
        if stop.is_set():
            queue.close()
        group = queue.next_batch(engine.config.rows)
        if not group:
            if stop.is_set() and not len(queue):
                return served
            continue
        try:
            results = engine.serve_tokens(
                [r.tokens for r in group],
                return_prompt_logits=any(r.return_prompt_logits
                                         for r in group))
            now = time.perf_counter()
            for req, res in zip(group, results):
                res.queue_wait_s = max(0.0, now - req.t_submit
                                       - res.prefill_s - res.decode_s)
                req.set_result(res)
            served += len(group)
        except Exception as e:  # noqa: BLE001 - fail the batch, not the loop
            if log is not None:
                log(f"serving: batch of {len(group)} failed: "
                    f"{type(e).__name__}: {e}")
            for req in group:
                req.set_error(e)


def drain(engine, queue: RequestQueue, log=None) -> int:
    """Serve everything still queued, then return (the SIGTERM path).
    Wrapped in the ``drain`` telemetry span so shutdown latency is on the
    record next to queue_wait/prefill/decode."""
    stop = threading.Event()
    stop.set()
    with telemetry.span("drain", pending=len(queue)):
        return serve_forever(engine, queue, stop, log=log)
