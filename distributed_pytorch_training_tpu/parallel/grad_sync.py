"""Gradient synchronization as an explicit, configurable, profiled stage —
the TPU-native rebuild of DDP's C++ reducer (bucketed, backward-overlapped,
optionally compressed all-reduce; /root/reference/train_ddp.py:305-310 wraps
it, README.md:35 promises to profile it).

The repo's default data-parallel path leaves gradient sync to XLA: the batch
is sharded over the mesh, the loss mean contracts over the global batch, and
the compiler inserts one all-reduce per gradient leaf wherever its scheduler
likes. That is correct but opaque — O(leaves) small collectives, no knob for
wire precision, nothing to profile against. This module makes the reducer
explicit, with the three levers DDP exposes (and two it doesn't):

* **Bucketing** (`BucketPlan`): gradients are flattened into ONE fp32 vector
  (leaf order = `jax.tree_util.tree_leaves` order, the documented
  reassociation order) and cut into contiguous size-capped buckets — the
  `bucket_cap_mb` analog. The compiled step then carries
  ``ceil(total_grad_bytes / cap)`` large collectives instead of one per
  leaf. Unlike DDP, bucket boundaries may split a leaf: the plan chunks the
  concatenated vector, so the bucket count meets the ceil bound exactly
  (DDP's greedy per-tensor packing can only promise 2x it).
* **Wire compression** (`reduce_flat`, `compressed_psum_scatter`): the
  collective operand dtype is a choice, not a given. ``bf16`` halves wire
  bytes (sum accumulates in bf16 on TPU — bounded error, no state);
  ``int8`` uses per-bucket max-abs scales plus **error feedback**
  (Karimireddy et al.; the DynamiQ lever, PAPERS.md): the quantization
  residual is carried to the next reduction so the bias telescopes instead
  of accumulating. Master accumulation is always fp32 — compression
  touches only the wire. Honest accounting for the int8 BUCKETED form
  (gather-based, see below): per-replica ring traffic is ~(n-1)·S bytes
  vs ~8·S for an uncompressed fp32 all-reduce, so the byte saving is real
  only for small DP degrees (break-even near n=9); the zero1 int8 scatter
  (s8 all-to-all, ~1 B/element regardless of n) does not have this
  scaling. The n-independent fix for the bucketed path — multi-hop
  reduce-scatter with REQUANTIZATION of the partial sums before the
  gather hop (DynamiQ's scheme) — costs a second collective per bucket
  and is the ROADMAP follow-up.
* **Overlap** is the caller's third lever: `training/loop.py` reduces
  microbatch *i*'s buckets INSIDE the grad-accum scan body, so the
  collective for step *i* has no data dependency on step *i+1*'s compute
  and XLA's latency-hiding scheduler can run them concurrently — exposed
  comm time becomes hidden time (measured by
  `experiments.trace_analysis.comm_overlap_split`).

Everything here is shard_map-body code: collectives take bound mesh axis
names, never a Mesh. The int8 wire uses all-gather / all-to-all (each
replica's quantized contribution travels with its own scale and is summed
AFTER dequantization) because a SUM all-reduce of int8 operands would
overflow at 2 replicas — the gather form is what keeps s8 on the wire.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

WIRE_DTYPES = ("fp32", "bf16", "int8")

# Quantization grid half-width: int8 values in [-127, 127] (symmetric; -128
# unused so the grid is zero-centered and dequantization is a pure scale).
_QMAX = 127.0


# ---------------------------------------------------------------------------
# Bucket plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static layout of the flattened gradient vector.

    ``bounds`` are cumulative element offsets cutting the concatenated fp32
    gradient vector into buckets: bucket k is ``flat[bounds[k]:bounds[k+1]]``.
    Built from parameter SHAPES only, so it is identical at trace time and
    across processes (no data-dependent layout).
    """

    total_size: int           # elements in the concatenated gradient vector
    bounds: Tuple[int, ...]   # len == n_buckets + 1; bounds[0] == 0

    @property
    def n_buckets(self) -> int:
        return len(self.bounds) - 1

    @property
    def total_bytes(self) -> int:
        """fp32 master bytes of one full gradient (the bucket-cap currency)."""
        return self.total_size * 4

    def bucket_sizes(self) -> Tuple[int, ...]:
        return tuple(b - a for a, b in zip(self.bounds, self.bounds[1:]))


def build_bucket_plan(params: Any, bucket_cap_mb: float) -> BucketPlan:
    """Cut the flattened gradient of ``params`` into size-capped buckets.

    ``bucket_cap_mb`` caps each bucket at that many MB of fp32 elements
    (DDP's ``bucket_cap_mb``, default 25 there). ``<= 0`` means one bucket —
    a single fused collective, the fully-flat extreme. The bucket count is
    exactly ``ceil(total_fp32_bytes / cap_bytes)``: boundaries cut the
    concatenated vector, not the leaf list, so no greedy-packing slack.
    """
    total = int(sum(np.prod(np.shape(leaf)) or 1
                    for leaf in jax.tree_util.tree_leaves(params)))
    if total == 0:
        return BucketPlan(total_size=0, bounds=(0,) * 2)
    cap_elems = int(bucket_cap_mb * (1024 ** 2) // 4)
    if bucket_cap_mb <= 0 or cap_elems >= total:
        return BucketPlan(total_size=total, bounds=(0, total))
    cap_elems = max(1, cap_elems)
    bounds = tuple(range(0, total, cap_elems)) + (total,)
    plan = BucketPlan(total_size=total, bounds=bounds)
    assert plan.n_buckets == math.ceil(total / cap_elems)
    return plan


def flatten_tree(tree: Any) -> jnp.ndarray:
    """Concatenate every leaf (ravelled, cast fp32) in tree-leaves order —
    the master flat gradient the buckets slice. This fixed order IS the
    documented reassociation order of the bucketed reducer."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate(
        [jnp.ravel(leaf).astype(jnp.float32) for leaf in leaves])


def unflatten_tree(flat: jnp.ndarray, like: Any) -> Any:
    """Rebuild a pytree shaped like ``like`` from the flat vector, casting
    each leaf back to its template's dtype (fp32 master -> param dtype)."""
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out = []
    offset = 0
    for leaf in leaves:
        size = int(np.prod(np.shape(leaf)) or 1)
        out.append(
            lax.slice_in_dim(flat, offset, offset + size)
            .reshape(np.shape(leaf)).astype(jnp.result_type(leaf)))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Compressed collectives (shard_map-body code: axis names must be bound)
# ---------------------------------------------------------------------------


def _quantize_int8(v: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(int8 codes, fp32 scale): symmetric per-bucket max-abs scaling."""
    scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-30) / _QMAX
    q = jnp.clip(jnp.round(v / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def _int8_gather_sum(q: jnp.ndarray, scale: jnp.ndarray,
                     axis_names: Sequence[str], n_shards: int) -> jnp.ndarray:
    """SUM-of-dequantized across replicas via an s8 all-gather.

    Each replica contributes (codes, scale); codes ride the wire as s8
    (the compression), scales as one fp32 scalar per replica (noise). The
    sum happens AFTER dequantization, locally and in the same axis order on
    every replica — so the result is exactly replicated, and no int8
    overflow is possible. Wire scaling caveat: an all-gather moves every
    replica's codes to every replica (~(n-1)·S bytes each), so the saving
    over a fp32 all-reduce (~8·S) erodes as n grows — see the module
    docstring.
    """
    gathered = lax.all_gather(q, axis_names, axis=0, tiled=True)
    scales = lax.all_gather(scale[None], axis_names, axis=0, tiled=True)
    per_replica = gathered.reshape(n_shards, -1).astype(jnp.float32)
    return jnp.sum(per_replica * scales[:, None], axis=0)


def _compressed_psum(v: jnp.ndarray, axis_names: Sequence[str],
                     n_shards: int, wire_dtype: str,
                     residual: Optional[jnp.ndarray]
                     ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """One bucket's SUM all-reduce at the chosen wire dtype.

    Returns ``(fp32 global sum, new residual)``; the residual is None unless
    ``wire_dtype == 'int8'`` (error feedback: what this replica's
    quantization dropped, to be re-injected at its next reduction).
    """
    names = tuple(axis_names)
    if wire_dtype == "fp32":
        return lax.psum(v, names), residual
    if wire_dtype == "bf16":
        # wire + accumulation in bf16 (that is the point: half the bytes);
        # the caller keeps the fp32 master copy
        return lax.psum(v.astype(jnp.bfloat16), names).astype(jnp.float32), \
            residual
    if wire_dtype != "int8":
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(choose from {WIRE_DTYPES})")
    if residual is None:
        raise ValueError("int8 wire needs an error-feedback residual "
                         "(Trainer.init_state builds it)")
    carried = v + residual
    q, scale = _quantize_int8(carried)
    new_residual = carried - q.astype(jnp.float32) * scale
    return _int8_gather_sum(q, scale, names, n_shards), new_residual


def reduce_flat(flat: jnp.ndarray, plan: BucketPlan,
                axis_names: Sequence[str], n_shards: int, wire_dtype: str,
                residual: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Reduce the flat local gradient vector bucket-by-bucket.

    ``flat``: this replica's (total_size,) fp32 contribution (weight-scaled
    gradient sums). Returns the globally-summed fp32 vector and the updated
    error-feedback residual (same shape, int8 wire only). One collective per
    bucket — the O(buckets) contract `grad_sync_census` verifies in HLO.
    """
    outs: List[jnp.ndarray] = []
    res_outs: List[jnp.ndarray] = []
    for a, b in zip(plan.bounds, plan.bounds[1:]):
        v = lax.slice_in_dim(flat, a, b)
        r = (lax.slice_in_dim(residual, a, b)
             if residual is not None else None)
        summed, new_r = _compressed_psum(v, axis_names, n_shards,
                                         wire_dtype, r)
        outs.append(summed)
        if new_r is not None:
            res_outs.append(new_r)
    synced = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    new_residual = (jnp.concatenate(res_outs) if len(res_outs) > 1
                    else res_outs[0]) if res_outs else None
    return synced, new_residual


def compressed_psum_scatter(v: jnp.ndarray, axis_names: Sequence[str],
                            n_shards: int, wire_dtype: str,
                            residual: Optional[jnp.ndarray] = None
                            ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Reduce-scatter one flat-padded leaf at the chosen wire dtype — the
    compressed half-all-reduce of the ZeRO-1 update (training/loop.py).

    ``v``: (padded,) local fp32, padded size divisible by ``n_shards``.
    Returns this replica's (padded/n,) fp32 chunk of the cross-replica sum
    plus the updated error-feedback residual (int8 only, full padded size —
    EF must remember what was dropped from EVERY chunk, not just the kept
    one). int8 rides an s8 all-to-all: replica j receives every peer's
    chunk j (2 wire bytes per 8 fp32 bytes, scatter-half included), then
    dequantizes with the peers' gathered scales and sums in fp32.
    """
    names = tuple(axis_names)
    if wire_dtype == "fp32":
        return lax.psum_scatter(v, names, scatter_dimension=0, tiled=True), \
            residual
    if wire_dtype == "bf16":
        return lax.psum_scatter(v.astype(jnp.bfloat16), names,
                                scatter_dimension=0,
                                tiled=True).astype(jnp.float32), residual
    if wire_dtype != "int8":
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(choose from {WIRE_DTYPES})")
    if residual is None:
        raise ValueError("int8 wire needs an error-feedback residual "
                         "(Trainer.init_state builds it)")
    carried = v + residual
    q, scale = _quantize_int8(carried)
    new_residual = carried - q.astype(jnp.float32) * scale
    received = lax.all_to_all(q, names, split_axis=0, concat_axis=0,
                              tiled=True)  # (padded,) s8: peers' chunk j
    scales = lax.all_gather(scale[None], names, axis=0, tiled=True)
    per_replica = received.reshape(n_shards, -1).astype(jnp.float32)
    return jnp.sum(per_replica * scales[:, None], axis=0), new_residual


# ---------------------------------------------------------------------------
# Error-feedback state constructors (host-side; Trainer.init_state calls)
# ---------------------------------------------------------------------------


def _born_sharded_zeros(structs: Any, mesh):
    """Zeros pytree (of jax.ShapeDtypeStruct leaves) created ALREADY
    sharded over the batch axes (the optim.zero1_opt_state idiom): jit
    with out_shardings makes XLA allocate each replica's rows in place —
    no full-array transient on device 0 (for gpt2-scale params,
    n_shards x param bytes would be a multi-GB spike at init_state)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import BATCH_AXES

    shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(BATCH_AXES)), structs)
    make = jax.jit(
        lambda: jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), structs),
        out_shardings=shardings)
    return make()


def ef_state_bucketed(params: Any, mesh, n_shards: int):
    """Per-replica error-feedback residual for the bucketed reducer: one
    (n_shards, total_size) fp32 array, row r = replica r's residual,
    sharded over the batch axes so each replica materializes only its row.
    """
    total = int(sum(np.prod(np.shape(leaf)) or 1
                    for leaf in jax.tree_util.tree_leaves(params)))
    struct = jax.ShapeDtypeStruct((n_shards, total), jnp.float32)
    return {"ef": _born_sharded_zeros(struct, mesh)}


def ef_state_zero1(params: Any, mesh, n_shards: int):
    """Per-replica residuals for the zero1 int8 scatter: one
    (n_shards, flat_padded_size) fp32 array PER LEAF (the scatter is
    per-leaf there), sharded over the batch axes."""
    from .sharding import flat_padded_size

    structs = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(
            (n_shards,
             flat_padded_size(int(np.prod(np.shape(p)) or 1), n_shards)),
            jnp.float32),
        params)
    return {"ef": _born_sharded_zeros(structs, mesh)}
