"""Gradient synchronization as an explicit, configurable, profiled stage —
the TPU-native rebuild of DDP's C++ reducer (bucketed, backward-overlapped,
optionally compressed all-reduce; /root/reference/train_ddp.py:305-310 wraps
it, README.md:35 promises to profile it).

The repo's default data-parallel path leaves gradient sync to XLA: the batch
is sharded over the mesh, the loss mean contracts over the global batch, and
the compiler inserts one all-reduce per gradient leaf wherever its scheduler
likes. That is correct but opaque — O(leaves) small collectives, no knob for
wire precision, nothing to profile against. This module makes the reducer
explicit, with the three levers DDP exposes (and two it doesn't):

* **Bucketing** (`BucketPlan`): gradients are flattened into ONE fp32 vector
  (leaf order = `jax.tree_util.tree_leaves` order, the documented
  reassociation order) and cut into contiguous size-capped buckets — the
  `bucket_cap_mb` analog. The compiled step then carries
  ``ceil(total_grad_bytes / cap)`` large collectives instead of one per
  leaf. Unlike DDP, bucket boundaries may split a leaf: the plan chunks the
  concatenated vector, so the bucket count meets the ceil bound exactly
  (DDP's greedy per-tensor packing can only promise 2x it).
* **Wire compression** (`reduce_flat`, `compressed_psum_scatter`): the
  collective operand dtype is a choice, not a given. ``bf16`` halves wire
  bytes (sum accumulates in bf16 on TPU — bounded error, no state);
  ``int8`` uses per-bucket max-abs scales plus **error feedback**
  (Karimireddy et al.; the DynamiQ lever, PAPERS.md): the quantization
  residual is carried to the next reduction so the bias telescopes instead
  of accumulating. Master accumulation is always fp32 — compression
  touches only the wire. Honest accounting for the int8 BUCKETED form
  (gather-based, see below): per-replica ring traffic is ~(n-1)·S bytes
  vs ~8·S for an uncompressed fp32 all-reduce, so the byte saving is real
  only for small DP degrees (break-even near n=9); the zero1 int8 scatter
  (s8 all-to-all, ~1 B/element regardless of n) does not have this
  scaling. ``int8_multihop`` is the n-independent fix for the bucketed
  path (DynamiQ's multi-hop scheme, arxiv 2602.08923): each bucket is
  padded to the shard count, quantized PER DESTINATION CHUNK (one scale
  per chunk, so each receiver dequantizes exactly the chunks it sums),
  reduce-scattered as s8 over an all-to-all (hop 1, error feedback on
  this first quantization), dequant-summed locally in fp32, then the
  partial sum is REQUANTIZED and all-gathered as s8 (hop 2) — exactly
  two gradient-sized collectives per bucket and ~2 wire bytes/element
  regardless of n (`wire_bytes_per_replica` is the accounting). Hop 2
  is a broadcast of identical data, so its quantization error is the
  SAME perturbation on every replica — a bounded per-step bias (no
  divergence), not covered by EF (the hop-1 residual is). On zero1,
  ``int8_multihop`` means the FULLY compressed wire: the scatter half is
  the s8 all-to-all of ``int8`` (error-fed-back), and the param
  all-gather compresses as s8 UPDATE codes + per-chunk fp32 scales
  (`quantized_delta_all_gather` — the hop-2 error model applied to the
  parameter delta).
* **Topology awareness** (``int8_hier``): the two-tier hierarchical wire
  for multi-slice fleets (ICI islands joined by DCN — the mesh's ``slice``
  axis). Per bucket: (1) an EXACT fp32 reduce-scatter inside the slice over
  the fast tier, (2) the DynamiQ multi-hop s8 codec (per-chunk scales +
  error feedback, `_int8_multihop_sum` reused verbatim) ACROSS slices on
  the 1/n_inner partial — the only tier that quantizes, and the only EF
  site — then (3) an exact intra-slice all-gather back. Slow-link traffic
  per slice is ~2 bytes/element regardless of the slice count
  (`hier_wire_bytes` is the accounting); intra-slice arithmetic is exact,
  so the error model is EXACTLY the flat multihop wire's, at slice
  granularity (PARITY.md "Exactness model: two-tier sync").
* **Overlap** is the caller's third lever: `training/loop.py` reduces
  microbatch *i*'s buckets INSIDE the grad-accum scan body, so the
  collective for step *i* has no data dependency on step *i+1*'s compute
  and XLA's latency-hiding scheduler can run them concurrently — exposed
  comm time becomes hidden time (measured by
  `experiments.trace_analysis.comm_overlap_split`).

Everything here is shard_map-body code: collectives take bound mesh axis
names, never a Mesh. The int8 wire uses all-gather / all-to-all (each
replica's quantized contribution travels with its own scale and is summed
AFTER dequantization) because a SUM all-reduce of int8 operands would
overflow at 2 replicas — the gather form is what keeps s8 on the wire.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

WIRE_DTYPES = ("fp32", "bf16", "int8", "int8_multihop", "int8_hier")

# Wire modes whose codec carries an error-feedback residual (built by
# Trainer.init_state into TrainState.grad_sync).
EF_WIRE_DTYPES = ("int8", "int8_multihop", "int8_hier")

# Quantization grid half-width: int8 values in [-127, 127] (symmetric; -128
# unused so the grid is zero-centered and dequantization is a pure scale).
_QMAX = 127.0


# ---------------------------------------------------------------------------
# Hierarchy spec (the int8_hier wire's static topology)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HierSpec:
    """Static two-tier topology of the ``int8_hier`` wire.

    ``slice_axis`` is the slow (DCN) mesh axis, ``fast_axes`` the intra-
    slice (ICI) batch axes the exact tier reduces over; ``n_slices`` /
    ``n_inner`` are their sizes (world = n_slices * n_inner). Chunk
    ownership under the two-stage scatter is FAST-MAJOR: the fast-tier
    reduce-scatter hands fast-rank j contiguous chunk j, the slow-tier
    all-to-all then hands slice s sub-chunk s of it — so replica (s, j)
    owns global chunk ``j * n_slices + s``, which is exactly
    ``lax.axis_index(fast_axes + (slice_axis,))``. Every hier gather
    therefore runs slice-axis FIRST, then fast axes, to reassemble chunks
    in order (``hier_axes`` is the index/PartitionSpec order)."""

    slice_axis: str
    fast_axes: Tuple[str, ...]
    n_slices: int
    n_inner: int

    def __post_init__(self):
        if self.n_slices < 2:
            raise ValueError(
                f"HierSpec needs >= 2 slices (got {self.n_slices}); a "
                "1-slice mesh has no slow tier — the trainer resolves "
                "int8_hier to the flat fp32 path there")
        if self.n_inner < 1:
            raise ValueError(f"n_inner must be >= 1, got {self.n_inner}")

    @property
    def world(self) -> int:
        return self.n_slices * self.n_inner

    @property
    def hier_axes(self) -> Tuple[str, ...]:
        """Fast-major ownership order (axis_index / PartitionSpec order)."""
        return tuple(self.fast_axes) + (self.slice_axis,)


# ---------------------------------------------------------------------------
# Bucket plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static layout of the flattened gradient vector.

    ``bounds`` are cumulative element offsets cutting the concatenated fp32
    gradient vector into buckets: bucket k is ``flat[bounds[k]:bounds[k+1]]``.
    Built from parameter SHAPES only, so it is identical at trace time and
    across processes (no data-dependent layout).
    """

    total_size: int           # elements in the concatenated gradient vector
    bounds: Tuple[int, ...]   # len == n_buckets + 1; bounds[0] == 0

    @property
    def n_buckets(self) -> int:
        return len(self.bounds) - 1

    @property
    def total_bytes(self) -> int:
        """fp32 master bytes of one full gradient (the bucket-cap currency)."""
        return self.total_size * 4

    def bucket_sizes(self) -> Tuple[int, ...]:
        return tuple(b - a for a, b in zip(self.bounds, self.bounds[1:]))


def build_bucket_plan(params: Any, bucket_cap_mb: float) -> BucketPlan:
    """Cut the flattened gradient of ``params`` into size-capped buckets.

    ``bucket_cap_mb`` caps each bucket at that many MB of fp32 elements
    (DDP's ``bucket_cap_mb``, default 25 there). ``<= 0`` means one bucket —
    a single fused collective, the fully-flat extreme. The bucket count is
    exactly ``ceil(total_fp32_bytes / cap_bytes)``: boundaries cut the
    concatenated vector, not the leaf list, so no greedy-packing slack.
    """
    total = int(sum(np.prod(np.shape(leaf)) or 1
                    for leaf in jax.tree_util.tree_leaves(params)))
    if total == 0:
        return BucketPlan(total_size=0, bounds=(0,) * 2)
    cap_elems = int(bucket_cap_mb * (1024 ** 2) // 4)
    if bucket_cap_mb <= 0 or cap_elems >= total:
        return BucketPlan(total_size=total, bounds=(0, total))
    cap_elems = max(1, cap_elems)
    bounds = tuple(range(0, total, cap_elems)) + (total,)
    plan = BucketPlan(total_size=total, bounds=bounds)
    assert plan.n_buckets == math.ceil(total / cap_elems)
    return plan


def padded_bucket_bounds(plan: BucketPlan, n_shards: int) -> Tuple[int, ...]:
    """Cumulative offsets of the multihop wire layout: each bucket padded up
    to a multiple of ``n_shards`` (the all-to-all needs equal destination
    chunks). This is the layout of the hop-1 error-feedback residual — one
    padded slot per bucket element INCLUDING the pad tail, so the residual
    slices align with the codec's padded view of each bucket."""
    bounds = [0]
    for size in plan.bucket_sizes():
        bounds.append(bounds[-1] + -(-size // n_shards) * n_shards)
    return tuple(bounds)


def padded_total_size(plan: BucketPlan, n_shards: int) -> int:
    """Total elements of the multihop (padded-to-n) flat layout — the hop-1
    residual length `ef_state_bucketed` allocates per replica."""
    return padded_bucket_bounds(plan, n_shards)[-1]


def wire_bytes_per_replica(plan: BucketPlan, wire_dtype: str,
                           n_shards: int, n_slices: int = 1) -> int:
    """Per-replica wire bytes of ONE full gradient sync under `wire_dtype` —
    the accounting behind the mode table (README) as a measured/recorded
    number in bench and scaling rows, not a docstring claim.

    Conventions (payload only — the fp32 scale sideband, O(n) bytes per
    bucket, is excluded as noise):

    * ``fp32``/``bf16`` ride a ring all-reduce: ~2 hops x dtype bytes x S
      (the large-n ring volume 2·(n-1)/n·S rounds up to 2·S) — 8·S and 4·S.
    * ``int8`` (gather form): every replica RECEIVES each peer's full-size
      s8 codes — (n-1)·S bytes, growing with the DP degree (break-even vs
      fp32 near n=9).
    * ``int8_multihop``: hop 1 all-to-all moves ~S_padded s8 bytes, hop 2
      all-gather moves ~S_padded s8 bytes — 2·S_padded, independent of n
      (padding adds < n elements per bucket).
    * ``int8_hier`` (pass ``n_slices``): the fast tier is a flat fp32
      half+half all-reduce inside the slice — 8·S, exactly the flat fp32
      formula at the per-slice degree — plus the multihop wire on the
      1/n_inner partial across slices: 2·S_padded/n_inner slow-tier bytes
      per replica, i.e. ~2·S DCN bytes PER SLICE independent of the slice
      count (`hier_wire_bytes` returns the split).
    """
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(choose from {WIRE_DTYPES})")
    if n_shards <= 1:
        return 0  # passthrough: nothing rides the wire
    s = plan.total_size
    if wire_dtype == "int8_hier":
        split = hier_wire_bytes(plan, n_shards, n_slices)
        return split["ici"] + split["dcn"]
    if wire_dtype == "fp32":
        return 8 * s
    if wire_dtype == "bf16":
        return 4 * s
    if wire_dtype == "int8":
        return (n_shards - 1) * s
    return 2 * padded_total_size(plan, n_shards)


def hier_wire_bytes(plan: BucketPlan, n_shards: int,
                    n_slices: int) -> dict:
    """Per-replica bytes of one ``int8_hier`` sync, split by tier:
    ``{"ici": fast-tier bytes, "dcn": slow-tier bytes}``.

    Fast tier: exact fp32 reduce-scatter + all-gather inside the slice —
    together one ring all-reduce's volume, 8·S (identical to the flat fp32
    formula at the per-slice degree; per-bucket padding, < world elements,
    is excluded like every formula here excludes sideband noise). Slow
    tier: the multihop codec on this replica's 1/n_inner partial —
    2·S_padded/n_inner s8 bytes. Summed over a slice's n_inner replicas
    that is 2·S_padded DCN bytes per slice, INDEPENDENT of the slice count
    — the whole point of the hierarchy, and the property tests pin it.

    Raises loudly on infeasible factorizations (world not divisible by
    the slice count) — the same guard the trainer applies."""
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if n_shards % n_slices:
        raise ValueError(
            f"int8_hier: {n_shards} batch shards do not factor into "
            f"{n_slices} slices (world % slices != 0)")
    s = plan.total_size
    if n_shards <= 1:
        return {"ici": 0, "dcn": 0}
    if n_slices == 1:
        # slices=1 passthrough: the trainer resolves to the flat fp32 path.
        return {"ici": 8 * s, "dcn": 0}
    n_inner = n_shards // n_slices
    return {"ici": 8 * s if n_inner > 1 else 0,
            "dcn": 2 * padded_total_size(plan, n_shards) // n_inner}


def _flat_padded_total(params: Any, n_shards: int) -> int:
    """Sum of every leaf's flat-padded size — the element count that rides
    the explicit-FSDP wire (gathers and scatters both operate on the
    padded-to-n per-leaf layout)."""
    from .sharding import flat_padded_size

    return int(sum(
        flat_padded_size(int(np.prod(np.shape(leaf)) or 1), n_shards)
        for leaf in jax.tree_util.tree_leaves(params)))


def fsdp_gather_bytes(params: Any, wire_dtype: str, n_shards: int,
                      n_slices: int = 1) -> int:
    """Per-replica wire bytes of ONE full per-layer parameter gather pass
    under explicit FSDP (`fsdp_explicit`) — the gather-traffic term
    `wire_bytes_for_config` adds for that mode, recorded in bench/scaling
    rows (satellite of ISSUE 7).

    Conventions (payload only, scale sidebands excluded as noise): the
    fp32/bf16/int8 wires gather parameters EXACTLY (fp32 on the wire,
    mirroring zero1's exact param gather) — ~4 bytes x padded elements per
    replica. ``int8_multihop`` gathers s8 codes + per-chunk fp32 scales
    (`quantized_shard_all_gather`) — ~1 byte/element, independent of the
    shard count (the delta-gather n-independence argument, applied to the
    absolute shard values). ``int8_hier`` gathers s8 across slices first
    (~total/n_inner slow bytes per replica) then exact fp32 inside the
    slice (~4·total fast bytes) — the slow-tier term is what the mode
    exists to shrink."""
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(choose from {WIRE_DTYPES})")
    if n_shards <= 1:
        return 0  # passthrough: nothing rides the wire
    total = _flat_padded_total(params, n_shards)
    if wire_dtype == "int8_hier":
        if n_slices <= 1:
            return 4 * total  # passthrough: the flat exact fp32 gather
        n_inner = n_shards // n_slices
        return (4 * total if n_inner > 1 else 0) + total // n_inner
    return total if wire_dtype == "int8_multihop" else 4 * total


def tp_psum_bytes_per_step(hidden: int, depth: int, local_batch: int,
                           seq: int, model_n: int, tp_vocab: bool = False,
                           padded_vocab: int = 0) -> int:
    """Per-replica MODEL-axis wire bytes of ONE explicit-TP train step
    (ISSUE 13) — the TP term `wire_bytes_for_config` grows and
    `emit_wire_accounting` tags with its own tier row.

    Conventions (payload only, matching `wire_bytes_per_replica`): each
    megatron psum is an fp32 ring all-reduce of one (local_batch, seq,
    hidden) activation — ~8 bytes/element; the step carries 4 per block
    (forward g + backward f mirrors) plus 2 with the vocab-parallel
    embedding (`Trainer.tp_expected_model_collectives` is the same
    arithmetic read off the trainer). The vocab-parallel head adds the
    parallel-vocab cross-entropy's two (local_batch, seq, 2)-sized stat
    all-reduces (~32 bytes x local_batch x seq total) — the vocab-scale
    logits gather it replaced cost ~4 bytes x (local_batch, seq,
    padded_vocab), i.e. the head's wire shrank by ~padded_vocab/8 per
    token (collectives.tp_parallel_cross_entropy). ``padded_vocab`` is
    kept in the signature for callers recording the replaced-gather
    delta."""
    del padded_vocab  # the gather this sized is gone; see docstring
    if model_n <= 1:
        return 0
    act = local_batch * seq * hidden
    n_psums = 4 * depth + (2 if tp_vocab else 0)
    total = 8 * act * n_psums
    if tp_vocab:
        total += 32 * local_batch * seq
    return total


def wire_bytes_for_config(params: Any, grad_sync_cfg: Optional[dict],
                          n_shards: int) -> int:
    """`wire_bytes_per_replica` from a TrainConfig-style override dict
    (``bucket_cap_mb`` / ``wire_dtype`` / ``fsdp_explicit``, with the
    TrainConfig defaults) — the ONE accounting call both bench
    (`harness.measure_config`) and scaling (`run_grad_sync` / `run_fsdp` /
    `run_tp`) record, so their rows cannot drift apart.

    For ``fsdp_explicit`` configs the number is scatter + gather: the
    gradient reduce-scatter at the wire dtype (4/2/1/1 bytes per padded
    element for fp32/bf16/int8/int8_multihop — a reduce-scatter is half an
    all-reduce) plus the `fsdp_gather_bytes` per-layer gather term. Only
    ``int8_multihop`` compresses both directions (~2 B/element total,
    independent of n — asserted by tests, like the multihop gradient
    wire's).

    Explicit TP x FSDP: pass the TP-LOCAL parameter template as
    ``params`` (the trainer's `_fsdp_local_template` — gathers/scatters
    move each model shard's local slice only, the 1/M reduction) and the
    model-axis activation term via ``cfg["tp_psum_bytes"]``
    (`tp_psum_bytes_per_step`); the result is the TOTAL data-axis +
    model-axis per-replica bytes.

    ``int8_hier`` configs carry ``cfg["slices"]`` (the slice-axis size);
    `wire_bytes_split_for_config` returns the same number split by tier."""
    split = wire_bytes_split_for_config(params, grad_sync_cfg, n_shards)
    return split["ici"] + split["dcn"]


def wire_bytes_split_for_config(params: Any, grad_sync_cfg: Optional[dict],
                                n_shards: int) -> dict:
    """`wire_bytes_for_config`, split by interconnect tier:
    ``{"ici": fast-tier bytes, "dcn": slow-tier bytes}``. Every flat wire
    mode is all-ICI (dcn = 0); ``int8_hier`` puts the cross-slice s8
    traffic in "dcn" (the `hier_wire_bytes` split, extended with the
    fsdp gather/scatter terms). Raises loudly when ``cfg["slices"]`` does
    not divide the batch-shard world."""
    cfg = dict(grad_sync_cfg or {})
    wire = cfg.get("wire_dtype", "fp32")
    if wire not in WIRE_DTYPES:
        raise ValueError(f"unknown wire dtype {wire!r} "
                         f"(choose from {WIRE_DTYPES})")
    n_slices = int(cfg.get("slices", 1))
    if n_slices >= 1 and n_shards > 1 and n_shards % n_slices:
        raise ValueError(
            f"int8_hier: {n_shards} batch shards do not factor into "
            f"{n_slices} slices (world % slices != 0)")
    tp_bytes = int(cfg.get("tp_psum_bytes", 0))
    hier = wire == "int8_hier" and n_slices > 1 and n_shards > 1
    if cfg.get("fsdp_explicit"):
        if n_shards <= 1:
            return {"ici": tp_bytes, "dcn": 0}
        total = _flat_padded_total(params, n_shards)
        if hier:
            n_inner = n_shards // n_slices
            # scatter: fast fp32 reduce-scatter (4 B/elem) + slow s8
            # all-to-all on the 1/n_inner partial; gather: the mirror
            # (fsdp_gather_bytes) — slow-tier total 2·total/n_inner.
            fast = 8 * total if n_inner > 1 else 0
            return {"ici": fast + tp_bytes,
                    "dcn": 2 * (total // n_inner)}
        scatter = {"fp32": 4, "bf16": 2, "int8": 1, "int8_multihop": 1,
                   "int8_hier": 4}[wire] * total
        return {"ici": scatter + fsdp_gather_bytes(params, wire, n_shards)
                + tp_bytes, "dcn": 0}
    plan = build_bucket_plan(params, float(cfg.get("bucket_cap_mb", 0.0)))
    if hier:
        split = hier_wire_bytes(plan, n_shards, n_slices)
        return {"ici": split["ici"], "dcn": split["dcn"]}
    return {"ici": wire_bytes_per_replica(plan, wire, n_shards), "dcn": 0}


def emit_wire_accounting(params: Any, grad_sync_cfg: Optional[dict],
                         n_shards: int, tier: str = "ici",
                         **attrs: Any) -> dict:
    """Record the configured sync mode's per-replica wire accounting as
    telemetry counters (host-side, setup-time — called once by train.py /
    the bench harness, NEVER from traced code) and return the numbers —
    THE one emission site, so the stream and the bench rows cannot drift.

    ``tier`` names the interconnect the bytes ride — "ici" is the only
    tier today; the ROADMAP's two-tier (ICI + DCN) hierarchical sync will
    emit one counter set per tier through this same call, which is why
    the attribute exists now (per-tier byte/time telemetry is the
    substrate that item presumes). Extra ``attrs`` (e.g. the bench's
    ``model=...``) ride every emitted counter.

    Explicit TP x FSDP (``cfg["model_shards"]`` > 1 with
    ``cfg["tp_psum_bytes"]``): the model-axis activation bytes land in
    their OWN counter row (``tp_psum_bytes_per_replica``, axis="model")
    so ``telemetry summary`` splits TP psum traffic from the data-axis
    gradient sync, and ``wire_bytes_per_replica`` stays the data-axis
    number (tagged axis="data"). With no model axis the emission is
    byte-identical to before.

    ``int8_hier`` configs (``cfg["slices"]`` > 1): TWO
    ``wire_bytes_per_replica`` rows, one per interconnect tier —
    (tier="ici", axis="data") for the exact intra-slice half and
    (tier="dcn", axis="slice") for the compressed cross-slice half. The
    rows flow through `telemetry aggregate` and /metrics with zero schema
    change — (name, tier, axis) was already the rollup key."""
    from .. import telemetry

    cfg = dict(grad_sync_cfg or {})
    wire = cfg.get("wire_dtype", "fp32")
    model_shards = int(cfg.get("model_shards", 1))
    n_slices = int(cfg.get("slices", 1))
    tp_bytes = int(cfg.get("tp_psum_bytes", 0)) if model_shards > 1 else 0
    data_cfg = {k: v for k, v in cfg.items() if k != "tp_psum_bytes"}
    hier = (wire == "int8_hier" and n_slices > 1 and n_shards > 1)
    split = wire_bytes_split_for_config(params, data_cfg, n_shards)
    out = {"tier": tier, "wire_dtype": wire, "n_shards": n_shards,
           "wire_bytes_per_replica": split["ici"] + split["dcn"]}
    axis_attr = {"axis": "data"} if model_shards > 1 else {}
    if hier:
        out["wire_bytes_ici"] = split["ici"]
        out["wire_bytes_dcn"] = split["dcn"]
        out["n_slices"] = n_slices
        telemetry.counter("wire_bytes_per_replica", split["ici"],
                          tier="ici", axis="data", wire_dtype=wire,
                          n_shards=n_shards, n_slices=n_slices, **attrs)
        telemetry.counter("wire_bytes_per_replica", split["dcn"],
                          tier="dcn", axis="slice", wire_dtype=wire,
                          n_shards=n_shards, n_slices=n_slices, **attrs)
    else:
        telemetry.counter("wire_bytes_per_replica",
                          out["wire_bytes_per_replica"], tier=tier,
                          wire_dtype=wire, n_shards=n_shards, **axis_attr,
                          **attrs)
    if cfg.get("fsdp_explicit"):
        out["fsdp_gather_bytes"] = fsdp_gather_bytes(params, wire, n_shards,
                                                     n_slices)
        telemetry.counter("fsdp_gather_bytes", out["fsdp_gather_bytes"],
                          tier=tier, wire_dtype=wire, n_shards=n_shards,
                          **axis_attr, **attrs)
    if tp_bytes:
        out["tp_psum_bytes_per_replica"] = tp_bytes
        telemetry.counter("tp_psum_bytes_per_replica", tp_bytes, tier=tier,
                          axis="model", model_shards=model_shards, **attrs)
    return out


# ---------------------------------------------------------------------------
# Layer plan (explicit FSDP): the per-layer cut of the parameter tree
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LayerGroup:
    """One per-layer gather/scatter unit of the explicit-FSDP wire layout.

    ``leaf_slots`` index into the params tree's ``tree_leaves`` order;
    ``chunk_sizes[i]`` is leaf ``leaf_slots[i]``'s per-replica chunk
    (flat-padded size / n_shards). The group's WIRE LAYOUT is
    destination-major: row j = the concatenation of every member leaf's
    chunk j — so ONE tiled all-gather of this replica's row rebuilds every
    member leaf's flat-padded vector, and ONE reduce-scatter of the
    row-stacked gradient lands each leaf's chunk back on its owner.
    """

    name: str
    leaf_slots: Tuple[int, ...]
    chunk_sizes: Tuple[int, ...]

    @property
    def row_size(self) -> int:
        """Per-replica elements of this group (one gather/scatter row)."""
        return int(sum(self.chunk_sizes))


@dataclasses.dataclass(frozen=True)
class LayerPlan:
    """Static per-layer layout of a parameter tree for explicit FSDP —
    the BucketPlan idea applied to the MODEL's structure instead of a byte
    cap: one group per top-level module (`wte`, `block0`, ..., `ln_f`), so
    the step carries one just-in-time param gather and one gradient
    reduce-scatter per layer. Built from SHAPES only (host-side, identical
    at trace time and across processes)."""

    groups: Tuple[LayerGroup, ...]
    n_shards: int

    @property
    def total_padded(self) -> int:
        return self.n_shards * sum(g.row_size for g in self.groups)

    @property
    def padded_group_sizes(self) -> Tuple[int, ...]:
        """Full padded elements per group (n_shards x row_size) — the ONE
        budget the analysis/ fsdp rules read (contract evaluator and bench
        `_contract_check` both snapshot this, so their expectations cannot
        drift)."""
        return tuple(self.n_shards * g.row_size for g in self.groups)


def _top_level_key(path) -> str:
    if not path:
        return "params"
    p = path[0]
    for attr in ("key", "name", "idx"):
        if hasattr(p, attr):
            return str(getattr(p, attr))
    return str(p)


def build_layer_plan(params: Any, n_shards: int) -> LayerPlan:
    """Group ``params`` into per-layer gather units by top-level key.

    Grouping by the first path component makes each transformer block (and
    each standalone module: embeddings, final layernorm) one gather — the
    per-layer granularity SimpleFSDP gathers at. Leaves keep their
    ``tree_leaves`` order inside a group, so slicing a gathered row back
    into leaves is pure static arithmetic."""
    from .sharding import flat_padded_size

    by_key: dict = {}
    order: List[str] = []
    leaves = jax.tree_util.tree_leaves_with_path(params)
    for slot, (path, leaf) in enumerate(leaves):
        key = _top_level_key(path)
        if key not in by_key:
            by_key[key] = []
            order.append(key)
        size = int(np.prod(np.shape(leaf)) or 1)
        by_key[key].append((slot, flat_padded_size(size, n_shards)
                            // n_shards))
    groups = tuple(
        LayerGroup(name=k,
                   leaf_slots=tuple(s for s, _ in by_key[k]),
                   chunk_sizes=tuple(c for _, c in by_key[k]))
        for k in order)
    return LayerPlan(groups=groups, n_shards=n_shards)


def flatten_tree(tree: Any) -> jnp.ndarray:
    """Concatenate every leaf (ravelled, cast fp32) in tree-leaves order —
    the master flat gradient the buckets slice. This fixed order IS the
    documented reassociation order of the bucketed reducer."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate(
        [jnp.ravel(leaf).astype(jnp.float32) for leaf in leaves])


def unflatten_tree(flat: jnp.ndarray, like: Any) -> Any:
    """Rebuild a pytree shaped like ``like`` from the flat vector, casting
    each leaf back to its template's dtype (fp32 master -> param dtype)."""
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out = []
    offset = 0
    for leaf in leaves:
        size = int(np.prod(np.shape(leaf)) or 1)
        out.append(
            lax.slice_in_dim(flat, offset, offset + size)
            .reshape(np.shape(leaf)).astype(jnp.result_type(leaf)))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Compressed collectives (shard_map-body code: axis names must be bound)
# ---------------------------------------------------------------------------


def _quantize_int8_rows(rows: jnp.ndarray, fused: Optional[bool] = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Row-wise symmetric quantization of a (n, chunk) matrix: one fp32
    max-abs scale PER ROW (= per destination chunk), int8 codes. The single
    quantization-grid definition every int8 wire shares.

    ``fused=None`` resolves via ``ops.quantize.resolve_fused`` (TPU-gated,
    ``DPT_FUSED_QUANTIZE`` override); True routes through the Pallas fused
    kernel — BIT-IDENTICAL by contract (PARITY.md), a scheduling change
    only. The scale is an explicit multiply by 1/127 (not a division):
    XLA's simplifier rewrites division-by-constant to exactly that inside
    compiled steps, so writing the multiply keeps this function
    bit-reproducible across eager/jit/kernel contexts instead of depending
    on whether the rewrite fired."""
    from ..ops.quantize import quantize_int8_rows_fused, resolve_fused

    if resolve_fused(fused):
        return quantize_int8_rows_fused(rows)
    scales = jnp.maximum(jnp.max(jnp.abs(rows), axis=1), 1e-30) \
        * (1.0 / _QMAX)
    q = jnp.clip(jnp.round(rows / scales[:, None]),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return q, scales


def _quantize_int8(v: jnp.ndarray, fused: Optional[bool] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(int8 codes, fp32 scale): symmetric per-bucket max-abs scaling —
    the one-row case of `_quantize_int8_rows`."""
    q, scales = _quantize_int8_rows(v[None], fused=fused)
    return q[0], scales[0]


def _dequant_sum_rows(q: jnp.ndarray, scales: jnp.ndarray,
                      fused: Optional[bool] = None) -> jnp.ndarray:
    """SUM of dequantized rows — (n, chunk) s8 x (n,) fp32 scales ->
    (chunk,) fp32: the receive-side accumulate every int8 wire shares
    (hop-1 partial sums, the zero1 s8 scatter, the gather-form sum).
    ``fused`` routes through the Pallas kernel (bit-identical contract,
    ops/quantize.py)."""
    from ..ops.quantize import dequant_sum_rows_fused, resolve_fused

    if resolve_fused(fused):
        return dequant_sum_rows_fused(q, scales)
    return jnp.sum(q.astype(jnp.float32) * scales[:, None], axis=0)


def _int8_gather_sum(q: jnp.ndarray, scale: jnp.ndarray,
                     axis_names: Sequence[str], n_shards: int,
                     fused: Optional[bool] = None) -> jnp.ndarray:
    """SUM-of-dequantized across replicas via an s8 all-gather.

    Each replica contributes (codes, scale); codes ride the wire as s8
    (the compression), scales as one fp32 scalar per replica (noise). The
    sum happens AFTER dequantization, locally and in the same axis order on
    every replica — so the result is exactly replicated, and no int8
    overflow is possible. Wire scaling caveat: an all-gather moves every
    replica's codes to every replica (~(n-1)·S bytes each), so the saving
    over a fp32 all-reduce (~8·S) erodes as n grows — see the module
    docstring.
    """
    gathered = lax.all_gather(q, axis_names, axis=0, tiled=True)
    scales = lax.all_gather(scale[None], axis_names, axis=0, tiled=True)
    return _dequant_sum_rows(gathered.reshape(n_shards, -1), scales,
                             fused=fused)


def _int8_multihop_sum(v: jnp.ndarray, residual: jnp.ndarray,
                       axis_names: Sequence[str], n_shards: int,
                       fused: Optional[bool] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """DynamiQ-style two-hop compressed SUM of one bucket: s8 all-to-all
    reduce-scatter, local fp32 dequant-sum, requantize, s8 all-gather.

    ``v``: this replica's (S,) fp32 bucket contribution. ``residual``: the
    (S_padded,) hop-1 error-feedback residual (S_padded = S rounded up to a
    multiple of ``n_shards``). Returns ``(fp32 (S,) global sum, new
    residual)``.

    Hop 1 quantizes PER DESTINATION CHUNK — one scale per (n_shards,)-row
    of the padded bucket — so replica j dequantizes each received chunk
    with exactly the scale its sender used for chunk j (a per-bucket scale
    would make the receiver's dequant depend on elements it never sees).
    The s8 all-to-all moves each chunk to its owner (~S_padded wire bytes);
    the scales ride a tiny fp32 all-to-all (n scalars, under any census
    floor). Error feedback covers THIS quantization: the residual is what
    this replica's codes dropped, re-injected at its next reduction, so the
    hop-1 bias telescopes across steps.

    Hop 2 requantizes the fp32 partial sum of the n received chunks (one
    scale for this replica's chunk) and all-gathers the codes
    (~S_padded wire bytes) + scales (n fp32 scalars). Every replica
    dequantizes the same (codes, scales), so the result is exactly
    replicated. Hop-2 error is NOT error-fed-back — the partial sum is
    owned by one replica but consumed by all, so a residual would have to
    ride the wire to help; instead the error is bounded (<= scale2/2 per
    element, scale2 = maxabs(partial)/127) and identical everywhere,
    a per-step perturbation like the bf16 wire's (PARITY.md documents it).

    Total: exactly TWO gradient-sized collectives per bucket and ~2 wire
    bytes/element regardless of n — the census bound
    `analysis.contracts.collectives_per_bucket("int8_multihop") == 2`.
    """
    names = tuple(axis_names)
    size = v.shape[0]
    padded = residual.shape[0]
    chunk = padded // n_shards
    carried = jnp.pad(v, (0, padded - size)) + residual
    rows = carried.reshape(n_shards, chunk)
    q, scales = _quantize_int8_rows(rows, fused=fused)
    new_residual = carried - (q.astype(jnp.float32)
                              * scales[:, None]).reshape(-1)
    # hop 1: replica j receives every peer's chunk j (+ the scale each
    # peer used for chunk j) — an s8 reduce-scatter, sum deferred to fp32
    recv_q = lax.all_to_all(q.reshape(-1), names, split_axis=0,
                            concat_axis=0, tiled=True)  # (padded,) s8
    recv_scales = lax.all_to_all(scales, names, split_axis=0,
                                 concat_axis=0, tiled=True)  # (n,) fp32
    partial = _dequant_sum_rows(recv_q.reshape(n_shards, chunk),
                                recv_scales, fused=fused)  # (chunk,) fp32
    # hop 2: requantize the partial sum, gather codes + scales, dequant
    out = _s8_all_gather_dequant(partial, names, fused=fused)
    return out[:size], new_residual


def _int8_hier_sum(v: jnp.ndarray, residual: jnp.ndarray,
                   spec: HierSpec, fused: Optional[bool] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-tier topology-aware SUM of one bucket (the ``int8_hier`` wire):
    exact fp32 reduce-scatter inside the slice, the multihop s8 codec
    across slices, exact all-gather back.

    ``v``: this replica's (S,) fp32 bucket contribution. ``residual``: the
    (S_padded / n_inner,) slow-tier error-feedback residual — S_padded is
    the bucket rounded up to a multiple of the WORLD (`padded_bucket_bounds`
    at world), so the fast-tier chunk S_padded/n_inner is itself divisible
    by n_slices and the reused multihop codec pads nothing further. Returns
    ``(fp32 (S,) global sum, new residual)``.

    Stage 1 — fast tier, EXACT: a tiled fp32 ``psum_scatter`` over the
    intra-slice batch axes. Fast-rank j now holds chunk j of the
    within-slice sum; no quantization, no residual — intra-slice
    arithmetic is bitwise the same reassociation class as the flat
    reducer's.

    Stage 2 — slow tier, COMPRESSED: `_int8_multihop_sum` over the slice
    axis on the 1/n_inner partial, verbatim — per-destination-chunk s8
    quantization with error feedback (the ONE EF site of the hier wire;
    the residual telescopes across steps exactly as in the flat multihop
    wire), s8 all-to-all + requantized s8 all-gather. Its output is
    replica-identical ACROSS slices at each fast rank, so stage 3's
    reassembly never mixes divergent values.

    Stage 3 — fast tier, EXACT: a tiled all-gather over the intra-slice
    axes rebuilds the full bucket (chunks are fast-indexed, so order is
    restored by construction).

    Four gradient-sized collectives per bucket — two exact f32 on ICI,
    two s8 on DCN (`analysis.contracts.collectives_per_bucket` == 4; the
    `hier-tier-signature` HLO rule pins dtype-per-tier). Slow-tier wire
    bytes: ~2·S per SLICE, independent of the slice count."""
    size = v.shape[0]
    padded = residual.shape[0] * spec.n_inner
    carried = jnp.pad(v, (0, padded - size))
    if spec.fast_axes:
        part = lax.psum_scatter(carried, spec.fast_axes,
                                scatter_dimension=0, tiled=True)
    else:  # pure cross-slice mesh (n_inner == 1): no fast tier
        part = carried
    summed, new_residual = _int8_multihop_sum(
        part, residual, (spec.slice_axis,), spec.n_slices, fused=fused)
    if spec.fast_axes:
        summed = lax.all_gather(summed, spec.fast_axes, axis=0, tiled=True)
    return summed[:size], new_residual


def _compressed_psum(v: jnp.ndarray, axis_names: Sequence[str],
                     n_shards: int, wire_dtype: str,
                     residual: Optional[jnp.ndarray],
                     fused: Optional[bool] = None
                     ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """One bucket's SUM all-reduce at the chosen wire dtype.

    Returns ``(fp32 global sum, new residual)``; the residual is None unless
    ``wire_dtype == 'int8'`` (error feedback: what this replica's
    quantization dropped, to be re-injected at its next reduction).
    """
    names = tuple(axis_names)
    if wire_dtype == "fp32":
        return lax.psum(v, names), residual
    if wire_dtype == "bf16":
        # wire + accumulation in bf16 (that is the point: half the bytes);
        # the caller keeps the fp32 master copy
        return lax.psum(v.astype(jnp.bfloat16), names).astype(jnp.float32), \
            residual
    if wire_dtype == "int8_multihop":
        raise ValueError("int8_multihop buckets reduce via "
                         "_int8_multihop_sum (reduce_flat routes them — "
                         "the residual layout is padded-to-n, not flat)")
    if wire_dtype != "int8":
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(choose from {WIRE_DTYPES})")
    if residual is None:
        raise ValueError("int8 wire needs an error-feedback residual "
                         "(Trainer.init_state builds it)")
    carried = v + residual
    q, scale = _quantize_int8(carried, fused=fused)
    new_residual = carried - q.astype(jnp.float32) * scale
    return _int8_gather_sum(q, scale, names, n_shards, fused=fused), \
        new_residual


def reduce_flat(flat: jnp.ndarray, plan: BucketPlan,
                axis_names: Sequence[str], n_shards: int, wire_dtype: str,
                residual: Optional[jnp.ndarray] = None,
                fused: Optional[bool] = None,
                hier: Optional[HierSpec] = None
                ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Reduce the flat local gradient vector bucket-by-bucket.

    ``flat``: this replica's (total_size,) fp32 contribution (weight-scaled
    gradient sums). Returns the globally-summed fp32 vector and the updated
    error-feedback residual (int8 wires only; same shape for ``int8``, the
    `padded_bucket_bounds` layout for ``int8_multihop``, that layout's
    1/n_inner slow-tier view for ``int8_hier`` — which also requires the
    ``hier`` spec). One collective per bucket (TWO for the multi-hop wire,
    FOUR for the hierarchical wire: 2 exact f32 on ICI + 2 s8 on DCN) —
    the O(buckets) contract `grad_sync_census` verifies in HLO.
    """
    multihop = wire_dtype == "int8_multihop"
    if wire_dtype == "int8_hier":
        if hier is None:
            raise ValueError("int8_hier wire needs a HierSpec (the trainer "
                             "builds it from the mesh's slice axis)")
        if residual is None:
            raise ValueError("int8_hier wire needs a slow-tier error-"
                             "feedback residual (Trainer.init_state "
                             "builds it)")
    elif multihop and residual is None:
        raise ValueError("int8_multihop wire needs a hop-1 error-feedback "
                         "residual (Trainer.init_state builds it)")
    pbounds = (padded_bucket_bounds(plan, n_shards)
               if (multihop or wire_dtype == "int8_hier") else None)
    outs: List[jnp.ndarray] = []
    res_outs: List[jnp.ndarray] = []
    for k, (a, b) in enumerate(zip(plan.bounds, plan.bounds[1:])):
        v = lax.slice_in_dim(flat, a, b)
        if wire_dtype == "int8_hier":
            r = lax.slice_in_dim(residual, pbounds[k] // hier.n_inner,
                                 pbounds[k + 1] // hier.n_inner)
            summed, new_r = _int8_hier_sum(v, r, hier, fused=fused)
        elif multihop:
            r = lax.slice_in_dim(residual, pbounds[k], pbounds[k + 1])
            summed, new_r = _int8_multihop_sum(v, r, axis_names, n_shards,
                                               fused=fused)
        else:
            r = (lax.slice_in_dim(residual, a, b)
                 if residual is not None else None)
            summed, new_r = _compressed_psum(v, axis_names, n_shards,
                                             wire_dtype, r, fused=fused)
        outs.append(summed)
        if new_r is not None:
            res_outs.append(new_r)
    synced = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    new_residual = (jnp.concatenate(res_outs) if len(res_outs) > 1
                    else res_outs[0]) if res_outs else None
    return synced, new_residual


def _s8_all_gather_dequant(chunk: jnp.ndarray, names: Tuple[str, ...],
                           fused: Optional[bool] = None) -> jnp.ndarray:
    """The shared s8 gather wire: quantize this replica's (chunk,) fp32
    vector with ONE max-abs scale, all-gather codes (s8 on the wire) +
    scales (n fp32 scalars, noise), dequantize identically everywhere.
    Returns the full (n x chunk,) fp32 reconstruction — exactly
    replica-identical because every replica dequantizes the same
    (codes, scales). One convention, three wires: multihop's hop 2,
    zero1's delta gather, and the explicit-FSDP shard gather."""
    q, scale = _quantize_int8(chunk, fused=fused)
    gathered = lax.all_gather(q, names, axis=0, tiled=True)
    scales = lax.all_gather(scale[None], names, axis=0, tiled=True)
    n = scales.shape[0]
    return (gathered.reshape(n, -1).astype(jnp.float32)
            * scales[:, None]).reshape(-1)


def quantized_delta_all_gather(new_shard: jnp.ndarray,
                               old_shard: jnp.ndarray,
                               old_flat: jnp.ndarray,
                               axis_names: Sequence[str],
                               fused: Optional[bool] = None) -> jnp.ndarray:
    """Compressed zero1 PARAM all-gather (the `int8_multihop` composition):
    gather s8 codes of each replica's UPDATE, not fp32 new params.

    ``new_shard``/``old_shard``: this replica's (padded/n,) fp32 chunk of
    one leaf's flat-padded parameters, after/before the optimizer update.
    ``old_flat``: the full (padded,) flat-padded OLD parameters — replicated
    in zero1 (the layout the mode shards is the update, not the model), so
    every replica already holds them exactly. Each replica quantizes its
    chunk's delta with one fp32 max-abs scale (the per-destination-chunk
    rule of the multihop gradient wire, reused: the scale travels with the
    codes it scales), all-gathers codes (s8 on the wire, ~1 B per fp32
    param byte saved x4) + scales (n fp32 scalars, noise), and adds the
    dequantized full delta to ``old_flat``.

    Error model (the hop-2 story, verbatim): every replica dequantizes the
    SAME (codes, scales), so the reconstructed parameters are exactly
    replicated — quantization perturbs the trajectory by a bounded,
    replica-identical amount per step (<= scale/2 per element, scale =
    maxabs(update)/127 per chunk; the UPDATE is lr-sized, so the absolute
    param error is ~lr * grad-scale / 254 per step). NOT error-fed-back:
    the delta is owned by one replica but consumed by all, so a residual
    would have to ride the wire to help; tests pin the 20-step fp32-parity
    instead (tests/test_grad_sync.py).
    """
    names = tuple(axis_names)
    full_delta = _s8_all_gather_dequant(new_shard - old_shard, names,
                                        fused=fused)
    return old_flat + full_delta


def quantized_shard_all_gather(shard: jnp.ndarray,
                               axis_names: Sequence[str],
                               fused: Optional[bool] = None) -> jnp.ndarray:
    """Compressed explicit-FSDP PARAM all-gather: s8 codes of each
    replica's shard (absolute values, one fp32 max-abs scale per chunk —
    the per-destination-chunk rule again), gathered and dequantized
    identically everywhere.

    ``shard``: this replica's (chunk,) fp32 row of one layer group's
    flat-padded parameters (at rest — explicit FSDP never holds a
    replicated copy, so unlike zero1's `quantized_delta_all_gather` there
    is no old_flat base to delta against; the codes carry the values
    themselves). Returns the full (n x chunk,) fp32 reconstruction.

    Error model (the hop-2 story applied to parameter VALUES, stated
    honestly): every replica dequantizes the SAME (codes, scales), so the
    gathered working parameters are exactly replica-identical; the at-rest
    shards stay exact fp32 (only the per-step gathered copy is perturbed,
    by <= scale/2 per element with scale = maxabs(chunk)/127 — coarser
    than the delta gather's lr-sized error because it scales with the
    PARAMETER magnitude, not the update). NOT error-fed-back (the same
    one-owner/all-consumers argument); pinned by convergence tests, not
    fp32 parity (tests/test_fsdp_explicit.py)."""
    return _s8_all_gather_dequant(shard, tuple(axis_names), fused=fused)


def compressed_psum_scatter(v: jnp.ndarray, axis_names: Sequence[str],
                            n_shards: int, wire_dtype: str,
                            residual: Optional[jnp.ndarray] = None,
                            fused: Optional[bool] = None
                            ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Reduce-scatter one flat-padded leaf at the chosen wire dtype — the
    compressed half-all-reduce of the ZeRO-1 update (training/loop.py).

    ``v``: (padded,) local fp32, padded size divisible by ``n_shards``.
    Returns this replica's (padded/n,) fp32 chunk of the cross-replica sum
    plus the updated error-feedback residual (int8 only, full padded size —
    EF must remember what was dropped from EVERY chunk, not just the kept
    one). int8 rides an s8 all-to-all: replica j receives every peer's
    chunk j (2 wire bytes per 8 fp32 bytes, scatter-half included), then
    dequantizes with the peers' gathered scales and sums in fp32.
    """
    names = tuple(axis_names)
    if wire_dtype == "fp32":
        return lax.psum_scatter(v, names, scatter_dimension=0, tiled=True), \
            residual
    if wire_dtype == "bf16":
        return lax.psum_scatter(v.astype(jnp.bfloat16), names,
                                scatter_dimension=0,
                                tiled=True).astype(jnp.float32), residual
    if wire_dtype == "int8_multihop":
        raise ValueError(
            "the zero1 scatter half is ALREADY the n-independent s8 "
            "all-to-all: the zero1 step maps wire_dtype='int8_multihop' "
            "to the 'int8' scatter codec before calling here (what "
            "multihop adds on zero1 is the compressed param gather — "
            "quantized_delta_all_gather)")
    if wire_dtype != "int8":
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(choose from {WIRE_DTYPES})")
    if residual is None:
        raise ValueError("int8 wire needs an error-feedback residual "
                         "(Trainer.init_state builds it)")
    carried = v + residual
    q, scale = _quantize_int8(carried, fused=fused)
    new_residual = carried - q.astype(jnp.float32) * scale
    received = lax.all_to_all(q, names, split_axis=0, concat_axis=0,
                              tiled=True)  # (padded,) s8: peers' chunk j
    scales = lax.all_gather(scale[None], names, axis=0, tiled=True)
    return _dequant_sum_rows(received.reshape(n_shards, -1), scales,
                             fused=fused), new_residual


def hier_psum_scatter(v: jnp.ndarray, spec: HierSpec,
                      residual: Optional[jnp.ndarray],
                      fused: Optional[bool] = None
                      ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Two-tier reduce-scatter of one flat-padded leaf (zero1) or layer-
    group row stack (explicit FSDP) under the ``int8_hier`` wire.

    ``v``: (padded,) local fp32, padded divisible by the WORLD. Stage 1 is
    the exact fp32 ``psum_scatter`` over the intra-slice axes (fast-rank j
    takes chunk j); stage 2 is the s8 all-to-all scatter of `int8` mode
    over the slice axis on that 1/n_inner partial — the one quantization,
    with error feedback (``residual`` spans the FULL partial,
    padded/n_inner elements — EF remembers what was dropped from every
    destination chunk, the `compressed_psum_scatter` convention). Returns
    this replica's (padded/world,) chunk of the global sum: chunk index
    ``j * n_slices + s`` — the FAST-MAJOR ownership `HierSpec.hier_axes`
    names — plus the updated residual."""
    if spec.fast_axes:
        part = lax.psum_scatter(v, spec.fast_axes, scatter_dimension=0,
                                tiled=True)
    else:
        part = v
    return compressed_psum_scatter(part, (spec.slice_axis,), spec.n_slices,
                                   "int8", residual, fused=fused)


def hier_delta_all_gather(new_shard: jnp.ndarray, old_shard: jnp.ndarray,
                          old_flat: jnp.ndarray, spec: HierSpec,
                          fused: Optional[bool] = None) -> jnp.ndarray:
    """`quantized_delta_all_gather` on the two-tier wire (zero1 x hier
    param gather): s8 UPDATE codes cross slices, exact fp32 crosses ICI.

    Gather order is slice-axis FIRST: under fast-major ownership replica
    (s, j) holds chunk ``j * n_slices + s``, so the slice gather rebuilds
    fast-rank j's contiguous stage-1 chunk, and the fast gather then
    concatenates those in order. The slow hop carries ~1 byte/element of
    the 1/n_inner partial; the fast hop is exact (the intra-slice tier
    never quantizes). Error model: identical to the flat delta gather —
    every replica dequantizes the same (codes, scales) per slow hop, then
    gathers exactly, so the reconstruction is replica-identical."""
    delta = new_shard - old_shard
    part = _s8_all_gather_dequant(delta, (spec.slice_axis,), fused=fused)
    if spec.fast_axes:
        full = lax.all_gather(part, spec.fast_axes, axis=0, tiled=True)
    else:
        full = part
    return old_flat + full


def hier_shard_all_gather(shard: jnp.ndarray, spec: HierSpec,
                          fused: Optional[bool] = None) -> jnp.ndarray:
    """`quantized_shard_all_gather` on the two-tier wire (explicit FSDP x
    hier param gather): s8 codes of this replica's at-rest row cross
    slices (~1 B/element of the partial), then an exact fp32 intra-slice
    gather rebuilds the full layer group. Same slice-first order as
    `hier_delta_all_gather` (fast-major ownership); at-rest shards stay
    exact fp32 — only the per-step gathered working copy carries the
    bounded slow-hop perturbation."""
    part = _s8_all_gather_dequant(shard, (spec.slice_axis,), fused=fused)
    if spec.fast_axes:
        return lax.all_gather(part, spec.fast_axes, axis=0, tiled=True)
    return part


# ---------------------------------------------------------------------------
# Error-feedback state constructors (host-side; Trainer.init_state calls)
# ---------------------------------------------------------------------------


def _born_sharded_zeros(structs: Any, mesh, axes=None):
    """Zeros pytree (of jax.ShapeDtypeStruct leaves) created ALREADY
    sharded over ``axes`` (default: the batch axes — the
    optim.zero1_opt_state idiom; explicit TP passes (model,) + batch):
    jit with out_shardings makes XLA allocate each replica's rows in
    place — no full-array transient on device 0 (for gpt2-scale params,
    n_shards x param bytes would be a multi-GB spike at init_state)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import BATCH_AXES

    axes = tuple(axes) if axes is not None else BATCH_AXES
    shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(axes)), structs)
    make = jax.jit(
        lambda: jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), structs),
        out_shardings=shardings)
    return make()


def ef_state_bucketed(params: Any, mesh, n_shards: int,
                      bucket_cap_mb: float = 0.0,
                      wire_dtype: str = "int8", n_slices: int = 1):
    """Per-replica error-feedback residual for the bucketed reducer: one
    (n_shards, R) fp32 array, row r = replica r's residual, sharded over
    the batch axes so each replica materializes only its row. R is the
    flat gradient size for the ``int8`` gather wire; for ``int8_multihop``
    it is the `padded_bucket_bounds` layout (each bucket padded to a
    multiple of n_shards — the hop-1 residual lives in the codec's padded
    view, so the bucket cap and wire dtype size the buffer); for
    ``int8_hier`` it is 1/n_inner of that padded layout (each replica's
    residual covers only its fast-tier partial — the slow tier is the one
    quantization site, and it only ever sees the partial). Consequence:
    a multihop/hier residual is only meaningful under the bucket plan it
    was built for — resuming such a checkpoint with a different
    ``bucket_cap_mb`` is unsupported (the step rejects mismatched residual
    lengths; keep the cap or rebuild the state and let EF restart from
    zero residuals).
    """
    plan = build_bucket_plan(params, bucket_cap_mb)
    if wire_dtype == "int8_multihop":
        total = padded_total_size(plan, n_shards)
    elif wire_dtype == "int8_hier":
        if n_slices < 2 or n_shards % n_slices:
            raise ValueError(
                f"int8_hier EF state needs a feasible factorization; got "
                f"{n_shards} shards over {n_slices} slices")
        total = padded_total_size(plan, n_shards) // (n_shards // n_slices)
    else:
        total = plan.total_size
    struct = jax.ShapeDtypeStruct((n_shards, total), jnp.float32)
    return {"ef": _born_sharded_zeros(struct, mesh)}


def ef_state_fsdp(params: Any, mesh, n_shards: int, model_n: int = 1,
                  n_inner: int = 1):
    """Per-replica residuals for the explicit-FSDP int8 gradient scatter:
    one (n_shards, n_shards * row_size) fp32 array PER LAYER GROUP (the
    scatter is per layer there — `build_layer_plan`), keyed by group name,
    sharded over the batch axes so each replica materializes only its row.
    The residual length is the group's full padded size: EF must remember
    what was dropped from EVERY destination chunk, not just the kept one
    (the `compressed_psum_scatter` convention).

    Explicit TP x FSDP (``model_n`` > 1): ``params`` is the TP-LOCAL
    template — each (model shard, data replica) pair runs its own
    data-axis scatter over its local row, so the row dim grows to
    ``model_n * n_shards`` (model-major, matching the at-rest layout) and
    the rows shard over (model,) + batch axes.

    Under the ``int8_hier`` wire pass ``n_inner``: the slow-tier scatter
    quantizes only the 1/n_inner fast-tier partial of each group, so each
    residual row shrinks by that factor (n_shards * row_size is a multiple
    of the world, hence of n_inner; TP x hier is rejected upstream, so
    model_n and n_inner never both exceed 1)."""
    from .mesh import BATCH_AXES, MODEL

    plan = build_layer_plan(params, n_shards)
    structs = {
        g.name: jax.ShapeDtypeStruct(
            (model_n * n_shards,
             n_shards * g.row_size // max(1, n_inner)), jnp.float32)
        for g in plan.groups}
    axes = ((MODEL,) + BATCH_AXES) if model_n > 1 else BATCH_AXES
    return {"ef": _born_sharded_zeros(structs, mesh, axes=axes)}


def fold_ef_rows(rows, new_n: int):
    """Re-chunk per-replica error-feedback ROWS from old-N to new-M
    replicas: new row m is the sum of old rows ``{m, m + M, m + 2M, ...}``
    (growing, M > N: the extra rows are zero).

    The invariant this preserves EXACTLY (element-wise, in order-fixed fp
    summation) is the column-wise TOTAL — the telescoping sum of carried
    quantization error across replicas, which is what re-enters the next
    reduction (each replica adds its row to its contribution before
    quantizing, and the collective sums all rows). The per-row DISTRIBUTION
    changes, so post-resize quantization scales differ from either
    fixed-world run — a bounded, deterministic re-association the elastic
    exactness model documents (PARITY.md). Host-side numpy, restore time.
    """
    import numpy as np

    rows = np.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(f"fold_ef_rows expects (n, R) rows, got shape "
                         f"{rows.shape}")
    old_n, r = rows.shape
    out = np.zeros((new_n, r), rows.dtype)
    for i in range(old_n):
        out[i % new_n] += rows[i]
    return out


def reshard_multihop_ef_row(row, plan: BucketPlan, old_n: int,
                            new_n: int):
    """Re-chunk ONE multihop hop-1 residual row from the old-N
    `padded_bucket_bounds` layout to the new-M one: each bucket's padded
    region is truncated-or-zero-extended independently (the pad tail of
    every bucket is exactly zero — the carried value at a pad slot is
    always 0, so the hop-1 residual never accumulates there)."""
    import numpy as np

    from .sharding import reshard_flat_padded

    old_b = padded_bucket_bounds(plan, old_n)
    new_b = padded_bucket_bounds(plan, new_n)
    parts = [
        reshard_flat_padded(row[a:b], nb - na, name=f"bucket {k}")
        for k, (a, b, na, nb) in enumerate(
            zip(old_b, old_b[1:], new_b, new_b[1:]))]
    return np.concatenate(parts) if len(parts) > 1 else parts[0]


def reshard_fsdp_ef_row(row, old_group: LayerGroup, new_group: LayerGroup,
                        old_n: int, new_n: int):
    """Re-chunk ONE explicit-FSDP group residual row from the old-N
    destination-major stacking to the new-M one, leaf by leaf (never
    materializing more than this one layer group): column block i of the
    (n, row_size) view is leaf i's flat-padded vector reshaped (n, chunk),
    so per leaf the re-chunk is exactly `reshard_flat_padded` on the
    unstacked flat vector, restacked at the new chunking."""
    import numpy as np

    from .sharding import reshard_flat_padded

    row = np.asarray(row)
    mat = row.reshape(old_n, old_group.row_size)
    parts = []
    off = 0
    for (slot, c_old), c_new in zip(
            zip(old_group.leaf_slots, old_group.chunk_sizes),
            new_group.chunk_sizes):
        leaf_flat = np.ascontiguousarray(
            mat[:, off:off + c_old]).reshape(-1)
        leaf_new = reshard_flat_padded(leaf_flat, new_n * c_new,
                                       name=f"{old_group.name}[{slot}]")
        parts.append(leaf_new.reshape(new_n, c_new))
        off += c_old
    out = np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    return out.reshape(-1)


def ef_state_zero1(params: Any, mesh, n_shards: int, n_inner: int = 1):
    """Per-replica residuals for the zero1 int8 scatter: one
    (n_shards, flat_padded_size) fp32 array PER LEAF (the scatter is
    per-leaf there), sharded over the batch axes. Under the ``int8_hier``
    wire pass ``n_inner``: the slow-tier scatter quantizes only the
    1/n_inner fast-tier partial, so each residual row shrinks by the same
    factor (flat_padded_size is a multiple of the world, hence of
    n_inner)."""
    from .sharding import flat_padded_size

    structs = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(
            (n_shards,
             flat_padded_size(int(np.prod(np.shape(p)) or 1), n_shards)
             // max(1, n_inner)),
            jnp.float32),
        params)
    return {"ef": _born_sharded_zeros(structs, mesh)}
