"""Device mesh construction.

The reference has no mesh: DDP is a flat world of one-process-per-GPU over
NCCL (/root/reference/train_ddp.py:65). The TPU-native design makes the device
topology explicit as a named `jax.sharding.Mesh`; every parallelism strategy
(DP / FSDP-style / TP / SP / PP / EP) is an axis of that mesh, and a model's
PartitionSpecs say which axes each tensor dimension is split over.

Axis naming convention (used by all partition rules in `models/`):

* ``data``  — data parallelism: batch dimension sharded; gradient psum rides
              this axis (the DDP all-reduce equivalent, ref :305-310).
* ``fsdp``  — parameter/optimizer-state sharding (ZeRO-ish); batch is sharded
              over (data, fsdp) jointly, params gathered per-layer by XLA.
* ``model`` — tensor parallelism (megatron-style split of weight matrices).
* ``seq``   — sequence/context parallelism (ring attention KV rotation).
* ``pipe``  — pipeline stages.
* ``expert``— expert parallelism for MoE layers.
* ``slice`` — the slow-interconnect outer tier (ICI islands joined by DCN):
              batch is sharded over it like ``data``, but the hierarchical
              gradient wire (``--wire-dtype int8_hier``) treats collectives
              over it as expensive and compresses them (grad_sync.py).

Axis order in the physical mesh matters on TPU: `mesh_utils.create_device_mesh`
maps the *last* axes onto the tightest ICI rings, so the most
communication-hungry axes (model, seq) go last.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis names.
DATA = "data"
FSDP = "fsdp"
MODEL = "model"
SEQ = "seq"
PIPE = "pipe"
EXPERT = "expert"
SLICE = "slice"

# The order axes are laid out in the physical mesh — bandwidth-hungry last.
# ``slice`` is OUTERMOST (most-major): linear replica ids group by slice, so
# consecutive ids share an ICI island and the hierarchical wire's "fast tier"
# replica groups are contiguous ranges (analysis/hlo_rules.py classifies
# tiers from exactly this layout).
AXIS_ORDER: tuple[str, ...] = (SLICE, PIPE, DATA, FSDP, EXPERT, SEQ, MODEL)

# The canonical axis-name registry. Code elsewhere must use the constants
# above (or AXIS_ORDER/BATCH_AXES), never the string literals: the
# `axis-name-registry` lint (analysis/ast_rules.py) flags literals in
# collective/PartitionSpec positions outside this module, and its
# import-free mirror of this set is pinned to AXIS_NAMES by a tier-1 test.
AXIS_NAMES: frozenset = frozenset(AXIS_ORDER)

# Axes a batch dimension may be sharded over (see sharding.batch_spec).
# ``slice`` is a batch axis: a multi-slice fleet runs data parallelism
# across slices, so every slice-axis size folds into the global batch.
BATCH_AXES: tuple[str, ...] = (SLICE, DATA, FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. ``-1`` on exactly one axis means "all remaining
    devices". The default is pure data parallelism — the reference's only
    strategy (SURVEY.md §2c)."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1
    slice: int = 1

    def resolved(self, n_devices: int) -> dict[str, int]:
        sizes = {
            SLICE: self.slice,
            PIPE: self.pipe,
            DATA: self.data,
            FSDP: self.fsdp,
            EXPERT: self.expert,
            SEQ: self.seq,
            MODEL: self.model,
        }
        bad = {k: v for k, v in sizes.items() if v < 1 and v != -1}
        if bad:
            raise ValueError(
                f"axis sizes must be >= 1 (or -1 for 'all remaining'), got {bad}")
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but {n_devices} are present"
            )
        return sizes

    @staticmethod
    def parse(text: str) -> "MeshSpec":
        """Parse ``"data=4,model=2"`` (CLI ``--mesh`` flag)."""
        valid = {f.name for f in dataclasses.fields(MeshSpec)}
        kwargs = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            k, eq, v = part.partition("=")
            k = k.strip()
            if k not in valid:
                raise ValueError(
                    f"--mesh: unknown axis {k!r}; valid axes: {sorted(valid)}"
                )
            if not eq or not v.strip().lstrip("-").isdigit():
                raise ValueError(
                    f"--mesh: expected '<axis>=<int>' pairs, got {part!r} "
                    f"(e.g. 'data=4,model=2')"
                )
            size = int(v)
            if size < 1 and size != -1:
                raise ValueError(
                    f"--mesh: axis size must be >= 1 (or -1 for 'all "
                    f"remaining devices'), got {part!r}"
                )
            kwargs[k] = size
        return MeshSpec(**kwargs)


def dcn_factors(sizes: dict, n_slices: int) -> tuple[dict, dict]:
    """Split a logical mesh shape into (per_slice, dcn) factors for a
    multi-slice pod: ``sizes[a] == per_slice[a] * dcn[a]`` and
    ``prod(dcn) == n_slices``.

    Only the latency-tolerant axes may span DCN — the explicit ``slice``
    axis first (it exists to name the DCN tier), then ``data`` (gradient
    all-reduce is once per step and overlappable), then ``pipe``
    (per-microbatch point-to-point activations are small), then ``fsdp``.
    ``model``/``seq``/``expert`` collectives are per-layer and
    bandwidth-hungry: they stay inside a slice, on ICI, always. This is the
    scaling-book recipe the reference's flat NCCL world cannot express
    (train_ddp.py:65 — one undifferentiated process group for everything)."""
    dcn = {a: 1 for a in AXIS_ORDER}
    rem = n_slices
    # callers may pass shapes without the (newer) slice axis — absent
    # axes have size 1 and cannot absorb a DCN factor
    for a in (SLICE, DATA, PIPE, FSDP):
        g = math.gcd(sizes.get(a, 1), rem)
        dcn[a] = g
        rem //= g
    if rem != 1:
        raise ValueError(
            f"mesh {sizes} cannot span {n_slices} slices: the slice count "
            f"must divide into the slice/data/pipe/fsdp axes (model/seq/"
            f"expert stay within a slice — their collectives need ICI). "
            f"E.g. for {n_slices} slices use data={n_slices}*k.")
    per = {a: sizes.get(a, 1) // dcn[a] for a in AXIS_ORDER}
    return per, dcn


def _slice_count(devices: Sequence[jax.Device]) -> int:
    ids = {getattr(d, "slice_index", None) for d in devices}
    ids.discard(None)
    return max(1, len(ids))


def _unwrap_devices(dev_array: np.ndarray) -> np.ndarray:
    """Virtual-slice proxies (testing) are only for LAYOUT — every Mesh must
    hold the real devices underneath, including on hybrid-construction
    fallback paths."""
    return np.array(
        [getattr(d, "base_device", d) for d in dev_array.flat],
        dtype=object).reshape(dev_array.shape)


class _VirtualSliceDevice:
    """A device dressed with a synthetic ``slice_index``.

    Lets the multi-slice path (``dcn_factors`` ->
    ``mesh_utils.create_hybrid_device_mesh``) run END-TO-END on hosts with
    no multi-slice hardware (CPU test meshes, the driver's dry-run).
    ``build_mesh`` unwraps ``base_device`` after the layout is computed, so
    the resulting Mesh holds real devices and executes normally."""

    def __init__(self, device, slice_index: int):
        self.base_device = device
        self.slice_index = slice_index

    def __getattr__(self, name):
        return getattr(self.base_device, name)

    def __repr__(self):
        return f"VirtualSlice({self.slice_index}, {self.base_device!r})"


def with_virtual_slices(devices: Sequence[jax.Device],
                        n_slices: int) -> list:
    """Partition `devices` into `n_slices` equal contiguous virtual slices
    (testing helper; see _VirtualSliceDevice)."""
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices")
    per = len(devices) // n_slices
    return [_VirtualSliceDevice(d, i // per) for i, d in enumerate(devices)]


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named device mesh; TPU-topology-aware when possible.

    With the default spec this produces a 1-D ``data`` mesh over all devices —
    the TPU-native equivalent of the reference's DDP world (train_ddp.py:65).

    Multi-slice pods (devices reporting distinct ``slice_index``, i.e.
    ICI islands joined by DCN) get a HYBRID mesh: ``dcn_factors`` sends the
    slice-spanning parallelism to the latency-tolerant axes and
    ``mesh_utils.create_hybrid_device_mesh`` lays devices out so every
    other axis's collectives ride ICI within a slice.
    """
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolved(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)

    n_slices = _slice_count(devices)
    if n_slices > 1:
        per, dcn = dcn_factors(sizes, n_slices)  # raises on un-splittable
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                tuple(per[a] for a in AXIS_ORDER),
                tuple(dcn[a] for a in AXIS_ORDER),
                devices=list(devices))
            return Mesh(_unwrap_devices(dev_array), AXIS_ORDER)
        except (ValueError, AssertionError, NotImplementedError) as e:
            logging.getLogger(__name__).warning(
                "hybrid mesh construction failed (%s); falling back to the "
                "single-slice layout — DCN-crossing collectives may land on "
                "model/seq axes", e)

    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except (ValueError, AssertionError, NotImplementedError):
        # Non-TPU backends (CPU test meshes) or odd shapes: plain reshape.
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(_unwrap_devices(dev_array), AXIS_ORDER)


def validate_mesh_usage(
    mesh: Mesh,
    *,
    rules=None,
    attention: str = "xla",
    is_moe: bool = False,
    pipelined: bool = False,
) -> None:
    """Reject meshes with axes the selected config cannot use.

    The reference cannot express this failure mode (DDP's world is one flat
    axis), but here ``--mesh pipe=2`` with a non-pipelined model would
    replicate all work across half the devices with no warning — devices
    silently wasted. Each check names the flag combination that would
    actually use the axis.

    ``rules`` is the model's PartitionRules (or None); an axis is "usable"
    for params only if some rule can place a dim on it.
    """
    rule_axes = rules.axes_used() if rules is not None else set()
    problems = []
    if mesh.shape[PIPE] > 1 and not pipelined:
        problems.append(
            f"pipe={mesh.shape[PIPE]} but the selected model does not run "
            "through the pipeline (use a pipelined model config, e.g. "
            "gpt2_*_pipe, or drop the pipe axis)")
    if mesh.shape[SEQ] > 1 and attention not in ("ring", "ulysses"):
        problems.append(
            f"seq={mesh.shape[SEQ]} but --attention {attention!r} does not "
            "shard the sequence (use --attention ring or ulysses)")
    if mesh.shape[EXPERT] > 1 and not is_moe:
        problems.append(
            f"expert={mesh.shape[EXPERT]} but the model has no MoE layers "
            "(use an *_moe model or drop the expert axis)")
    if mesh.shape[MODEL] > 1 and MODEL not in rule_axes:
        problems.append(
            f"model={mesh.shape[MODEL]} but the model's partition rules "
            "never use the tensor-parallel axis (ResNets ship replicated-"
            "only rules; transformers support TP)")
    if problems:
        raise ValueError(
            "mesh axes that would silently waste devices:\n  - "
            + "\n  - ".join(problems))
    if mesh.shape[FSDP] > 1 and FSDP not in rule_axes:
        # fsdp devices still do data-parallel work (batch is sharded over
        # (data, fsdp)) so this is a degradation, not a waste — warn.
        logging.getLogger(__name__).warning(
            "fsdp=%d but the model's partition rules never shard params on "
            "the fsdp axis — running as plain data parallelism (no ZeRO "
            "memory win)", mesh.shape[FSDP])


# `validate_mesh_usage` under the name the --mesh CLI threading uses
# (ISSUE 13 satellite): every --mesh consumer — train.py and the serving
# CLI — must reject axes the selected model/config cannot use LOUDLY
# instead of silently replicating work across them. A true alias (not a
# forwarding wrapper), so the two names can never drift apart.
validate_mesh = validate_mesh_usage


def batch_shard_count(mesh: Mesh) -> int:
    """Number of ways the global batch is split (product of batch axes)."""
    return int(np.prod([mesh.shape[a] for a in BATCH_AXES]))


def local_batch_size(per_device_batch: int, mesh: Mesh) -> int:
    """This host's share of the global batch.

    Preserves the reference's per-device batch semantic (train_ddp.py:27
    "mini-batch size *per GPU*"): global batch = per_device_batch x
    (#devices on batch axes); each host feeds its local slice.
    """
    local_devices = [d for d in mesh.devices.flat if d.process_index == jax.process_index()]
    num, den = per_device_batch * len(local_devices) * batch_shard_count(mesh), mesh.size
    if num % den:
        raise ValueError(
            f"batch shards ({batch_shard_count(mesh)}) do not divide evenly "
            f"across this host's {len(local_devices)} of {mesh.size} devices "
            f"at per-device batch {per_device_batch}"
        )
    return num // den
