"""Parallelism layer: device mesh, collectives, sharding rules.

TPU-native replacement for the reference's NCCL process group + DDP reducer
(/root/reference/train_ddp.py:65, :303-311). Parallelism here is expressed as
a named `jax.sharding.Mesh` plus `PartitionSpec` rules; XLA inserts and
overlaps the collectives that DDP's C++ reducer performs by hand.
"""

from .mesh import (  # noqa: F401
    DATA,
    EXPERT,
    FSDP,
    MODEL,
    PIPE,
    SEQ,
    SLICE,
    MeshSpec,
    batch_shard_count,
    build_mesh,
    local_batch_size,
    validate_mesh,
)
from .collectives import (  # noqa: F401
    all_gather,
    all_to_all,
    barrier,
    broadcast_from_main,
    copy_to_tp,
    host_all_gather,
    pmax,
    pmean,
    ppermute_ring,
    psum,
    psum_scatter,
    reduce_from_tp,
    reduce_scalar,
    shard_map,
    tp_all_gather,
)
from .grad_sync import (  # noqa: F401
    WIRE_DTYPES,
    BucketPlan,
    build_bucket_plan,
    compressed_psum_scatter,
    flatten_tree,
    reduce_flat,
    unflatten_tree,
)
from .sharding import (  # noqa: F401
    PartitionRules,
    batch_sharding,
    batch_spec,
    replicated,
    shard_batch,
    shard_pytree,
    spec_for_path,
    tree_specs,
)
