"""Pipeline parallelism over the mesh ``pipe`` axis (GPipe-style SPMD).

Absent from the reference (pure DDP, SURVEY.md §2c "PP: absent"); built here
the TPU-native way: no per-stage processes or send/recv threads — ONE jitted
SPMD program in which the stage-stacked layer parameters are sharded over the
``pipe`` mesh axis and activations rotate between neighbor stages with
``lax.ppermute`` (one ICI hop per tick).

Schedule: classic GPipe. The local batch splits into M microbatches; at tick
t, stage p computes microbatch ``t - p`` (valid when 0 <= t-p < M), so the
pipeline fills for P-1 ticks, streams, and drains for P-1 ticks — bubble
fraction (P-1)/(M+P-1). All control flow is a ``lax.scan`` over M+P-1 ticks
with uniform per-device computation, exactly what XLA wants; autodiff of the
scan+ppermute yields the reverse schedule (cotangents ride the ring backward),
so no hand-written backward pass is needed.

Layers inside a stage run under a second ``lax.scan`` over the stacked layer
params (the standard scan-over-layers trick — one compiled block body,
L iterations).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import BATCH_AXES, PIPE
from .collectives import shard_map
from .sharding import batch_spec


def init_stacked_layers(module, rng: jax.Array, sample: jnp.ndarray,
                        num_layers: int, **apply_kwargs) -> Any:
    """Init `num_layers` i.i.d. copies of a layer module, stacked on a new
    leading axis (leaf shapes (L, ...)). The stack feeds scan-over-layers and,
    reshaped to (P, L/P, ...), the pipeline."""
    keys = jax.random.split(rng, num_layers)

    def init_one(key):
        return module.init(key, sample, **apply_kwargs)["params"]

    return jax.vmap(init_one)(keys)


def stack_to_stages(stacked: Any, num_stages: int) -> Any:
    """(L, ...) layer stack -> (P, L/P, ...) stage-major stack (leading axis
    shardable over ``pipe``)."""

    def reshape(leaf):
        l = leaf.shape[0]
        if l % num_stages:
            raise ValueError(
                f"{l} layers not divisible into {num_stages} pipeline stages")
        return leaf.reshape(num_stages, l // num_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, stacked)


def stage_params_spec(stage_params: Any) -> Any:
    """PartitionSpec pytree: leading (stage) axis on ``pipe``, rest replicated."""
    return jax.tree_util.tree_map(
        lambda leaf: P(PIPE, *([None] * (leaf.ndim - 1))), stage_params)


def pipeline_apply(
    apply_layer: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    mesh: Mesh,
    num_microbatches: int,
) -> jnp.ndarray:
    """Run a stage-stacked layer sequence as a GPipe pipeline.

    Args:
      apply_layer: ``(layer_params, x) -> y`` for ONE layer (unstacked leaves).
      stage_params: leaves shaped (P, L/P, ...), leading axis sharded on
        ``pipe`` (see `stack_to_stages` / `stage_params_spec`).
      x: (B, ...) activations, batch-sharded over (data, fsdp).
      mesh: device mesh; ``mesh.shape['pipe']`` = number of stages.
      num_microbatches: M; local batch per device must divide by it.

    Returns (B, ...) outputs, batch-sharded, identical (up to fp reassoc) to
    applying all P*L layers sequentially.
    """
    n_stages = mesh.shape[PIPE]
    if n_stages == 1:
        # Degenerate single-stage pipeline: plain scan over layers.
        def body(h, layer):
            return apply_layer(layer, h), None

        merged = jax.tree_util.tree_map(
            lambda leaf: leaf.reshape(-1, *leaf.shape[2:]), stage_params)
        return lax.scan(body, x, merged)[0]

    p_spec = stage_params_spec(stage_params)
    x_spec = batch_spec(x.ndim)

    def spmd(params, xs):  # params leaves (1, L/P, ...); xs local batch shard
        my_params = jax.tree_util.tree_map(lambda a: a[0], params)
        p = lax.axis_index(PIPE)
        n = lax.psum(1, PIPE)
        b = xs.shape[0]
        m = num_microbatches
        if b % m:
            raise ValueError(
                f"local batch {b} not divisible into {m} microbatches")
        mb = xs.reshape(m, b // m, *xs.shape[1:])

        def run_stage(h):
            def body(h, layer):
                return apply_layer(layer, h), None

            return lax.scan(body, h, my_params)[0]

        fwd_perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(carry, t):
            state, outs = carry
            # stage 0 injects microbatch t (clipped during drain ticks)
            inject = mb[jnp.clip(t, 0, m - 1)]
            h_in = jnp.where(p == 0, inject, state)
            y = run_stage(h_in)
            # last stage emits microbatch t-(n-1) (invalid during fill ticks)
            m_out = t - (n - 1)
            emit = (p == n - 1) & (m_out >= 0)
            outs = jnp.where(
                emit, outs.at[jnp.clip(m_out, 0, m - 1)].set(y), outs)
            state = lax.ppermute(y, PIPE, fwd_perm)
            return (state, outs), None

        init = (jnp.zeros_like(mb[0]), jnp.zeros_like(mb))
        (_, outs), _ = lax.scan(tick, init, jnp.arange(m + n - 1))
        # Broadcast the finished microbatches from the last stage to every
        # stage (one psum), so downstream (head/loss) is stage-agnostic.
        outs = lax.psum(jnp.where(p == n - 1, outs, jnp.zeros_like(outs)),
                        PIPE)
        return outs.reshape(b, *xs.shape[1:])

    return shard_map(spmd, mesh=mesh, in_specs=(p_spec, x_spec),
                         out_specs=x_spec)(stage_params, x)


def sequential_apply(apply_layer: Callable, stacked_params: Any,
                     x: jnp.ndarray) -> jnp.ndarray:
    """Reference semantics for tests: the same layers, applied in order
    without a pipeline ((L, ...) leaves)."""

    def body(h, layer):
        return apply_layer(layer, h), None

    return lax.scan(body, x, stacked_params)[0]
