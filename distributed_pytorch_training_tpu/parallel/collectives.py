"""Collectives — the visible-API parity surface for the reference's NCCL usage.

The reference touches NCCL in four ways (/root/reference/train_ddp.py):
(a) rendezvous (:65)        -> runtime.dist.setup_distributed
(b) dist.barrier (:112)     -> `barrier()` here (host-level sync)
(c) DDP bucketed gradient all-reduce (:305-310, implicit C++ reducer)
                            -> NOT an API here at all: gradients sync because
                               the batch is sharded over the mesh and the loss
                               mean contracts over the global batch — XLA
                               inserts (and overlaps) the all-reduce.
(d) scalar metric all-reduce via `reduce_tensor` (:159-167, :251-253, :290-292)
                            -> `psum`/`pmean` (in-jit) and `reduce_scalar`
                               (host-level), both with the reference's
                               "identity when single-device" convention
                               (ref :164-165).

Two distinct layers, never to be confused:

* **In-program collectives** (`psum`, `pmean`, `pmax`, `psum_scatter`,
  `all_gather`, `ppermute_ring`, `all_to_all`): used inside `shard_map`-ped
  functions where mesh axis names are bound. These lower to XLA collectives
  riding ICI. `psum_scatter`/`all_gather` are the two halves of an
  all-reduce, split so the ZeRO-1 weight update (training/loop.py) can do
  per-replica work between them.
* **Host-level collectives** (`barrier`, `broadcast_from_main`,
  `host_all_gather`, `reduce_scalar`): process-level synchronization across
  hosts, used for data-download gating (ref :111-112) and metric fan-in.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

AxisName = Union[str, Sequence[str]]

try:  # jax >= 0.6 exposes shard_map at the top level
    _shard_map_impl = jax.shard_map
except AttributeError:  # older jax keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def shard_map(f: Callable, mesh: Mesh, in_specs: Any, out_specs: Any):
    """`jax.shard_map` across jax versions, with replication checking off.

    One compat point for every shard_map in the repo: the entry point moved
    (experimental -> top level) and the check flag was renamed
    (``check_rep`` -> ``check_vma``) across the jax versions this code runs
    under. Checking is disabled because the bodies here use collectives
    whose replication the checker cannot always prove (psum_scatter /
    all_gather chains)."""
    params = inspect.signature(_shard_map_impl).parameters
    kwargs = {}
    if "check_vma" in params:
        kwargs["check_vma"] = False
    elif "check_rep" in params:
        kwargs["check_rep"] = False
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)


def _axes_present(axis_name: AxisName, mesh: Optional[Mesh]) -> bool:
    """Static (trace-time) check: does `axis_name` have size > 1?

    Implements the reference's single-process passthrough
    (train_ddp.py:164-165) as a *compile-time* no-op rather than a runtime
    branch — XLA never even sees a collective on trivial axes.
    """
    if mesh is None:
        return True  # caller is inside shard_map and asserts the axis exists
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    unknown = [n for n in names if n not in mesh.shape]
    if unknown:
        # A typo'd axis must not silently become a no-op — that would
        # silently disable gradient sync.
        raise KeyError(f"axis {unknown} not in mesh axes {tuple(mesh.shape)}")
    return any(mesh.shape[n] > 1 for n in names)


def psum(x: Any, axis_name: AxisName, *, mesh: Optional[Mesh] = None) -> Any:
    """SUM all-reduce over mesh axes (maps reduce_tensor, train_ddp.py:159-167).

    Identity when the axes are trivial, mirroring ref :164-165.
    """
    if not _axes_present(axis_name, mesh):
        return x
    return lax.psum(x, axis_name)


def pmean(x: Any, axis_name: AxisName, *, mesh: Optional[Mesh] = None) -> Any:
    """MEAN all-reduce (the gradient-sync op DDP performs implicitly)."""
    if not _axes_present(axis_name, mesh):
        return x
    return lax.pmean(x, axis_name)


def pmax(x: Any, axis_name: AxisName, *, mesh: Optional[Mesh] = None) -> Any:
    if not _axes_present(axis_name, mesh):
        return x
    return lax.pmax(x, axis_name)


def psum_scatter(x: Any, axis_name: AxisName, *, scatter_dimension: int = 0,
                 tiled: bool = True, mesh: Optional[Mesh] = None) -> Any:
    """SUM-reduce across the axes, each replica keeping only ITS chunk of the
    result — the first half of an all-reduce (all-reduce = reduce-scatter +
    all-gather), and the gradient-sync primitive of the ZeRO-1 sharded
    weight update (Xu et al., PAPERS.md): every replica receives 1/N of the
    synchronized gradient instead of all of it.

    Identity when the axes are trivial (reducing over one replica and
    keeping its single chunk is the value itself) — the same single-device
    passthrough convention as `psum` (ref train_ddp.py:164-165).
    """
    if not _axes_present(axis_name, mesh):
        return x
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def all_gather(x: Any, axis_name: AxisName, *, axis: int = 0,
               tiled: bool = True, mesh: Optional[Mesh] = None) -> Any:
    """Concatenate every replica's chunk along `axis` — the second half of an
    all-reduce, and the ZeRO-1 weight-update epilogue (each replica gathers
    the 1/N of the new parameters every other replica just updated).

    Identity when the axes are trivial, like `psum`/`psum_scatter`.
    """
    if not _axes_present(axis_name, mesh):
        return x
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_ring(x: Any, axis_name: str, *, shift: int = 1) -> Any:
    """Rotate `x` around the ring of `axis_name` — the building block of ring
    attention (KV blocks circulate over the ICI ring). No NCCL analogue in the
    reference (max sequence there is a 32x32 image); this is the long-context
    primitive SURVEY.md §5 requires."""
    if hasattr(lax, "axis_size"):
        n = lax.axis_size(axis_name)
    else:  # older jax: psum of a Python literal constant-folds to the size
        n = int(lax.psum(1, axis_name))
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x: Any, axis_name: str, split_axis: int, concat_axis: int) -> Any:
    """All-to-all over a mesh axis — the Ulysses (head-sharding) primitive."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


# ---------------------------------------------------------------------------
# Explicit tensor-parallel region operators (Megatron's f / g).
#
# Inside a shard_map'd train step the TP layers consume a replicated
# activation with per-shard weight slices; autodiff must then produce
# (a) a full (cross-shard-summed) cotangent flowing UPSTREAM of each
# parallel region — each shard's slice contributes an independent partial —
# and (b) an identity backward through the output psum (the cotangent of a
# replicated value consumed replicatedly is itself). jax's built-in
# transpose rules for psum/all_gather encode a different cotangent
# convention under check-free shard_map (per-device cotangents SUM across
# replicas), which would scale gradients by the TP degree here. These
# custom_vjp wrappers pin the exact collective structure of both passes BY
# CONSTRUCTION, independent of jax-version transpose conventions — one
# model-axis psum per residual join in the forward, its mirror at the
# region input in the backward (models/layers.py uses them; the
# `tp-psum-signature` analysis rule counts them in HLO).
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp(x: jnp.ndarray, axis_name: AxisName) -> jnp.ndarray:
    """Megatron's ``f``: identity forward into a tensor-parallel region,
    SUM over the TP axis in the backward. Placed at each parallel region's
    input (the qkv / fc1 projection input, the tied-head matmul input), so
    every upstream consumer — layernorms, embeddings, the residual stream —
    receives the full cotangent instead of one shard's partial."""
    return x


def _copy_to_tp_fwd(x, axis_name):
    return x, None


def _copy_to_tp_bwd(axis_name, _res, ct):
    return (lax.psum(ct, axis_name),)


copy_to_tp.defvjp(_copy_to_tp_fwd, _copy_to_tp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp(x: jnp.ndarray, axis_name: AxisName) -> jnp.ndarray:
    """Megatron's ``g``: SUM the row-parallel partial outputs over the TP
    axis in the forward (THE one psum per residual join), identity in the
    backward (the summed output is replicated; each shard's partial gets
    the replicated cotangent unchanged)."""
    return lax.psum(x, axis_name)


def _reduce_from_tp_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_from_tp_bwd(axis_name, _res, ct):
    return (ct,)


reduce_from_tp.defvjp(_reduce_from_tp_fwd, _reduce_from_tp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def tp_all_gather(x: jnp.ndarray, axis_name: AxisName,
                  dim: int) -> jnp.ndarray:
    """Concatenate per-shard slices along ``dim`` over the TP axis
    (the vocab-parallel logits gather), with the exact backward: each
    shard takes ITS slice of the (replicated) cotangent — a dynamic
    slice, no collective. jax's built-in all_gather transpose is a
    psum_scatter, which under the check-free shard_map convention would
    scale the cotangent by the TP degree (see `copy_to_tp`)."""
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _tp_all_gather_fwd(x, axis_name, dim):
    return lax.all_gather(x, axis_name, axis=dim, tiled=True), x.shape[dim]


def _tp_all_gather_bwd(axis_name, dim, size, ct):
    idx = lax.axis_index(axis_name)
    return (lax.dynamic_slice_in_dim(ct, idx * size, size, axis=dim),)


tp_all_gather.defvjp(_tp_all_gather_fwd, _tp_all_gather_bwd)


# ---------------------------------------------------------------------------
# Megatron parallel-vocab cross-entropy (Shoeybi et al., arXiv:1909.08053
# §3): the loss over vocab-SHARDED logit columns, without ever gathering
# the (B, S, vocab) logits over the model axis. The softmax denominator
# and the target-column logit are the only cross-shard facts CE needs —
# two (B, S)-sized stats instead of a vocab-sized gather, shrinking the
# head's model-axis wire by ~padded_vocab/4 per token.
# ---------------------------------------------------------------------------


class TpShardedLogits:
    """This shard's logit COLUMNS ``local`` = full_logits[..., lo:hi) with
    ``lo = axis_index(axis_name) * vocab_rows`` — what the vocab-parallel
    LM head returns instead of gathered logits (models/gpt2.py). The task
    layer branches on this type (training/tasks.py) and computes CE via
    `tp_parallel_cross_entropy`. Registered as a pytree so it can cross
    transform boundaries like the plain logits array it replaces."""

    def __init__(self, local: jnp.ndarray, axis_name: AxisName,
                 vocab_rows: int, vocab_size: int):
        self.local = local
        self.axis_name = axis_name
        self.vocab_rows = int(vocab_rows)
        self.vocab_size = int(vocab_size)

    def map_local(self, fn: Callable) -> "TpShardedLogits":
        """Same shards, ``fn`` applied to the local columns (the task's
        next-token shift: ``lg = logits.map_local(lambda x: x[:, :-1])``)."""
        return TpShardedLogits(fn(self.local), self.axis_name,
                               self.vocab_rows, self.vocab_size)


jax.tree_util.register_pytree_node(
    TpShardedLogits,
    lambda s: ((s.local,), (s.axis_name, s.vocab_rows, s.vocab_size)),
    lambda aux, children: TpShardedLogits(children[0], *aux))


def tp_parallel_cross_entropy(
        logits: TpShardedLogits,
        targets: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(per-position CE, predicted-correct) from vocab-sharded logit
    columns, exactly equal (at fp32 reassociation tolerance) to softmax CE
    over the gathered logits.

    Two model-axis collectives total, both (targets.shape, 2)-sized fp32:
    a stop-gradient pmax for the safe-softmax max, and ONE stacked psum
    carrying [sum_j exp(l_j - m), l_target-partial] (`reduce_from_tp`, so
    the backward is identity — the gradient of CE w.r.t. the local
    columns is softmax - onehot with no further collective, each shard
    producing exactly its own columns' cotangents). The pmax operand is
    deliberately stacked to width 2 as well: both stats then share ONE
    census size class, so the `tp-psum-signature` budget's floor logic is
    a single threshold instead of a straddle window (analysis/hlo_rules).

    ``correct`` is target-logit == global max — argmax-up-to-ties, which
    matches ``argmax(gathered) == target`` everywhere the max is unique.
    """
    local = logits.local.astype(jnp.float32)
    axis, rows = logits.axis_name, logits.vocab_rows
    shard = lax.axis_index(axis)
    # stop_gradient on the OPERAND (not the result): the tangent is then
    # a symbolic zero and the pmax — which has no differentiation rule —
    # is never linearized; the max is a shift, so it carries no gradient
    local_max = lax.stop_gradient(jnp.max(local, axis=-1))
    m = lax.pmax(jnp.stack([local_max, local_max], -1), axis)[..., 0]
    sumexp = jnp.sum(jnp.exp(local - m[..., None]), axis=-1)
    local_ids = targets - shard * rows
    valid = (local_ids >= 0) & (local_ids < rows)
    picked = jnp.take_along_axis(
        local, jnp.clip(local_ids, 0, rows - 1)[..., None], axis=-1)[..., 0]
    tgt_partial = jnp.where(valid, picked, 0.0)
    stats = reduce_from_tp(jnp.stack([sumexp, tgt_partial], -1), axis)
    total, tgt_logit = stats[..., 0], stats[..., 1]
    ce = jnp.log(total) + m - tgt_logit
    return ce, tgt_logit >= m


# ---------------------------------------------------------------------------
# Host-level (cross-process) collectives.
# ---------------------------------------------------------------------------


def barrier(name: str = "barrier") -> None:
    """Block until every process arrives (maps dist.barrier, train_ddp.py:112).

    Single-process: immediate return (ref is_distributed() gate, :111).
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_from_main(x: Any) -> Any:
    """Process-0 value to every process (DDP broadcasts params rank0->all at
    wrap time, train_ddp.py:305-310; we broadcast explicitly at init)."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(x)


def host_all_gather(x: Any) -> Any:
    """Gather a host value from every process -> stacked numpy array."""
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(lambda a: np.asarray(a)[None], x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x)


def reduce_scalar(x: Union[float, int, jnp.ndarray], op: str = "sum") -> float:
    """Host-level scalar reduction across processes — the literal parity API
    for `reduce_tensor` (train_ddp.py:159-167): SUM all-reduce, identity when
    single-process. Used for end-of-epoch metric fan-in (ref :251-253)."""
    val = float(np.asarray(x))
    if jax.process_count() == 1:
        return val
    gathered = np.asarray(host_all_gather(val))
    if op == "sum":
        return float(gathered.sum())
    if op == "max":
        return float(gathered.max())
    if op == "mean":
        return float(gathered.mean())
    raise ValueError(f"unknown op {op!r}")
