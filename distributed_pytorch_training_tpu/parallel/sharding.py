"""Sharding rules: param-path regex -> PartitionSpec.

The DDP wrapper (/root/reference/train_ddp.py:303-311) has exactly one layout:
every parameter replicated on every device. Here layout is first-class: each
model ships `PartitionRules` — an ordered list of (path-regex, PartitionSpec)
— and `shard_pytree` places params/optimizer state on the mesh accordingly.
Pure DP reproduces DDP (all params replicated); TP/FSDP are just different
rule tables over the same machinery (SURVEY.md §2c).
"""

from __future__ import annotations

import logging
import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import BATCH_AXES

logger = logging.getLogger(__name__)

# Degraded layouts warned about already (one warning per unique shape/spec —
# rule tables hit the same shapes for params+optimizer state repeatedly).
_degraded_warned: set = set()


def reset_degradation_warnings() -> None:
    """Clear the warn-once state so a new mesh/model setup warns afresh
    (long-lived processes and tests would otherwise inherit stale state)."""
    _degraded_warned.clear()


class PartitionRules:
    """Ordered (regex, PartitionSpec) table; first match on the '/'-joined
    param path wins; no match -> fully replicated (the DDP default layout)."""

    def __init__(self, rules: Sequence[Tuple[str, P]] = ()):  # noqa: D401
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str, ndim: Optional[int] = None) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                if ndim is not None and len(spec) > ndim:
                    raise ValueError(
                        f"rule {pat.pattern!r} spec {spec} has more axes than "
                        f"param {path!r} with ndim={ndim}"
                    )
                return spec
        return P()  # replicated

    def __add__(self, other: "PartitionRules") -> "PartitionRules":
        out = PartitionRules()
        out._rules = self._rules + other._rules
        return out

    def axes_used(self) -> set:
        """Mesh axis names any rule in the table can place a dim on (used by
        mesh validation: an axis no rule mentions cannot shard a param)."""
        axes = set()
        for _, spec in self._rules:
            for entry in spec:
                if entry is None:
                    continue
                names = (entry,) if isinstance(entry, str) else tuple(entry)
                axes.update(names)
        return axes


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for_path(rules: Optional[PartitionRules], path: str, ndim: int) -> P:
    if rules is None:
        return P()
    return rules.spec_for(path, ndim)


def tree_specs(tree: Any, rules: Optional[PartitionRules]) -> Any:
    """PartitionSpec pytree matching `tree` (for jit in_shardings / orbax)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for_path(rules, _path_str(path), np.ndim(leaf)),
        tree,
    )


def feasible_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Drop spec entries whose mesh axes do not divide the dimension.

    Rules describe the *intended* layout; some tensors cannot honor it (e.g.
    a (50257, d) GPT-2 vocab embedding is not divisible by a model axis of
    2 — Megatron pads the vocab; we keep exact parity shapes and replicate
    that dim instead). Infeasible dims degrade to replication, per-dim."""
    if not len(spec):
        return spec
    if len(spec) > len(shape):
        # A rule matching a tensor of smaller rank is a bug in the rule
        # table, not a layout infeasibility — keep the loud failure.
        raise ValueError(
            f"PartitionSpec {spec} has more entries than tensor rank "
            f"{len(shape)} (shape {shape})")
    entries = []
    changed = False
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if entry is None:
            entries.append(None)
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        size = int(np.prod([mesh.shape[n] for n in names]))
        if dim % size:
            entries.append(None)
            changed = True
        else:
            entries.append(entry)
    if changed:
        # Warn (once per shape/spec) — a silently-replicated tensor the rules
        # meant to split multiplies per-device memory and hides rule bugs.
        key = (tuple(spec), shape, tuple(sorted(mesh.shape.items())))
        if key not in _degraded_warned:
            _degraded_warned.add(key)
            logger.warning(
                "sharding %s infeasible for shape %s (indivisible dims) — "
                "degraded to %s (replicating those dims)",
                spec, shape, P(*entries))
    return P(*entries)


def shard_pytree(tree: Any, mesh: Mesh, rules: Optional[PartitionRules] = None) -> Any:
    """Place a pytree on the mesh per the rules (replicated by default).

    This is the moment DDP performs its rank0->all param broadcast
    (train_ddp.py:305-310); here placement and layout are one operation.
    Dims the rules would split unevenly are replicated instead (see
    `feasible_spec`).
    """
    specs = tree_specs(tree, rules)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(
            leaf,
            NamedSharding(mesh, feasible_spec(spec, np.shape(leaf), mesh))),
        tree,
        specs,
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# ZeRO-1 flat-shard layout (Xu et al., "Automatic Cross-Replica Sharding of
# Weight Update in Data-Parallel Training", PAPERS.md).
#
# The sharded weight update partitions every parameter's *flattened* value
# over the data-parallel axes: tensor shapes never constrain divisibility
# (a (1000,) bias on 8 replicas pads 1000 -> 1008 and shards 126 elements
# per replica), and the optimizer update becomes shape-agnostic elementwise
# work on (padded_size / N,) chunks. Padding elements carry zero gradient,
# so they stay zero through any elementwise optimizer chain.
# ---------------------------------------------------------------------------


def flat_padded_size(size: int, n_shards: int) -> int:
    """`size` rounded up to a multiple of `n_shards` (0-padding at the end)."""
    return size + (-size % n_shards)


def flatten_pad(x, n_shards: int):
    """1-D view of `x`, zero-padded so it splits evenly into `n_shards`."""
    import jax.numpy as jnp

    flat = jnp.ravel(x)
    pad = -flat.size % n_shards
    return jnp.pad(flat, (0, pad)) if pad else flat


def dp_flat_specs(tree: Any, axes: Sequence[str] = BATCH_AXES) -> Any:
    """Spec tree for a ZeRO-1 flat-sharded pytree: every array leaf is 1-D
    and sharded over the data-parallel axes; scalars (optimizer step counts)
    stay replicated."""
    return jax.tree_util.tree_map(
        lambda leaf: P(tuple(axes)) if np.ndim(leaf) else P(), tree)


def fsdp_flat_params(params: Any, mesh: Mesh, n_shards: int) -> Any:
    """Rewrite a (replicated, model-shaped) parameter tree into the
    explicit-FSDP at-rest layout: every leaf flat-padded to a multiple of
    ``n_shards`` and sharded 1/N over the batch axes — the zero1 moment
    layout (`optim.zero1_opt_state`) applied to the PARAMETERS themselves.

    Built under jit with ``out_shardings`` so XLA writes each replica's
    chunk in place (the `_born_sharded_zeros` idiom: no full-tree flat
    transient on one device). The original shapes/dtypes live on the
    caller (Trainer keeps a ShapeDtypeStruct template for the per-layer
    gather's unflatten)."""
    specs = dp_flat_specs(jax.eval_shape(
        lambda p: jax.tree_util.tree_map(
            lambda x: flatten_pad(x, n_shards), p), params))
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs)
    make = jax.jit(
        lambda p: jax.tree_util.tree_map(
            lambda x: flatten_pad(x, n_shards), p),
        out_shardings=shardings)
    return make(params)


# ---------------------------------------------------------------------------
# Explicit TP x FSDP layout (ISSUE 13): the tp_fsdp_rules() table read as an
# EXPLICIT layout contract. Each leaf gets a model-axis split dim from the
# rules (None = model-replicated); the at-rest layout is then model-major
# flat-padded: (M * flat_padded(local_size, N),) where "local" is the leaf's
# contiguous TP slice (split leaves) or a full per-model-shard copy
# (replicated leaves — same per-device bytes as plain model-axis
# replication, but a UNIFORM one-spec layout so the moments/EF machinery of
# explicit FSDP applies verbatim). Sharded P((model, data, fsdp)) on dim 0,
# so inside the step's shard_map each device holds exactly its (padded/N,)
# chunk of its model shard's slice.
# ---------------------------------------------------------------------------


def tp_split_dims(template: Any, rules: Optional[PartitionRules],
                  model_n: int) -> Any:
    """Per-leaf model-axis split dim (or None) — the tp_fsdp_rules() table
    read as the explicit-TP layout contract.

    A leaf splits on the first spec dim whose entry names the ``model``
    axis, IF that dim divides by ``model_n``; indivisible dims degrade to
    model-replication with the same warn-once `feasible_spec` issues (the
    GPT-2 vocab embedding without Megatron padding is the canonical case).
    The EXPLICIT TP forward (models/layers.py tp_size>1) derives its local
    shapes from the same divisibility conditions, so plan and computation
    cannot disagree."""
    from .mesh import MODEL

    def one(path, leaf):
        spec = spec_for_path(rules, _path_str(path), np.ndim(leaf))
        shape = np.shape(leaf)
        for dim, entry in enumerate(tuple(spec)):
            if entry is None:
                continue
            names = (entry,) if isinstance(entry, str) else tuple(entry)
            if MODEL not in names:
                continue
            if shape[dim] % model_n:
                key = (("tp", tuple(spec)), shape, model_n)
                if key not in _degraded_warned:
                    _degraded_warned.add(key)
                    logger.warning(
                        "explicit TP: %s dim %d (size %d) not divisible by "
                        "model=%d — leaf stays model-replicated (Megatron "
                        "vocab padding un-degrades embeddings)",
                        _path_str(path), dim, shape[dim], model_n)
                return None
            return dim
        return None

    return jax.tree_util.tree_map_with_path(one, template)


def tp_local_struct(template: Any, split_dims: Any, model_n: int) -> Any:
    """ShapeDtypeStruct tree of the per-model-shard LOCAL shapes: split
    leaves shrink their split dim by 1/M, replicated leaves keep their full
    shape (each model shard holds a copy)."""

    import jax.numpy as jnp

    def one(leaf, dim):
        shape = list(np.shape(leaf))
        if dim is not None:
            shape[dim] //= model_n
        return jax.ShapeDtypeStruct(tuple(shape), jnp.result_type(leaf))

    return jax.tree_util.tree_map(one, template, split_dims)


def _tp_slice(x, dim: Optional[int], model_n: int, shard: int):
    """Model shard ``shard``'s contiguous local slice of one leaf (the full
    leaf when dim is None)."""
    import jax.numpy as jnp  # noqa: F401

    if dim is None:
        return x
    c = x.shape[dim] // model_n
    return jax.lax.slice_in_dim(x, shard * c, (shard + 1) * c, axis=dim)


def tp_flat_leaf(x, dim: Optional[int], model_n: int, n_shards: int):
    """One leaf's model-major flat-padded at-rest vector: the concatenation
    over model shards of flat_padded(ravel(local slice), N). Trace-time
    Python loop over M (small); C-order ravel of each LOCAL slice, so the
    in-step per-layer gather's reshape-to-local-shape is pure arithmetic."""
    import jax.numpy as jnp

    rows = [flatten_pad(_tp_slice(x, dim, model_n, s), n_shards)
            for s in range(model_n)]
    return jnp.concatenate(rows) if model_n > 1 else rows[0]


def fsdp_tp_flat_params(params: Any, mesh: Mesh, n_shards: int,
                        model_n: int, split_dims: Any,
                        axes: Sequence[str]) -> Any:
    """`fsdp_flat_params` for the 2-D (TP x FSDP) layout: every leaf lands
    in the model-major flat-padded form (`tp_flat_leaf`), born sharded over
    ``axes`` so each device writes only its chunk in place."""
    structs = jax.eval_shape(
        lambda p: jax.tree_util.tree_map(
            lambda x, d: tp_flat_leaf(x, d, model_n, n_shards),
            p, split_dims), params)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P(tuple(axes)) if np.ndim(s) else P()),
        structs)
    make = jax.jit(
        lambda p: jax.tree_util.tree_map(
            lambda x, d: tp_flat_leaf(x, d, model_n, n_shards),
            p, split_dims),
        out_shardings=shardings)
    return make(params)


def tp_unflatten_leaf(flat, full_shape: Tuple[int, ...], dtype,
                      dim: Optional[int], model_n: int):
    """Model-shaped leaf from its model-major flat-padded at-rest vector
    (outside shard_map — eval/diagnostics; GSPMD inserts the movement).
    Split leaves re-concatenate their M local slices along the split dim;
    replicated leaves take copy 0 (all copies are bit-identical — each
    model group runs the same data-axis scatter on the same grads)."""
    import jax.numpy as jnp

    full_shape = tuple(full_shape)
    local_shape = list(full_shape)
    if dim is not None:
        local_shape[dim] //= model_n
    size = int(np.prod(local_shape) or 1)
    mat = flat.reshape(model_n, -1)[:, :size]
    if dim is None:
        return mat[0].reshape(full_shape).astype(dtype)
    rows = [mat[s].reshape(local_shape) for s in range(model_n)]
    return jnp.concatenate(rows, axis=dim).astype(dtype)


def tp_clip_weights_for_model(model, rules: Optional[PartitionRules],
                              model_n: int, sample_input) -> dict:
    """`tp_clip_weights` derived straight from a model + its rules — THE
    one derivation both train.py and the bench harness use (a weighting
    rule living in two hand-rolled copies would silently diverge between
    the CLI and the bench arms). One abstract trace of ``model.init`` on
    ``sample_input`` recovers the leaf paths/shapes the divisibility
    decisions need."""
    import functools

    import jax.numpy as jnp

    template = jax.eval_shape(
        functools.partial(model.init, train=False), jax.random.PRNGKey(0),
        jnp.asarray(sample_input))["params"]
    split_dims = tp_split_dims(template, rules, model_n)
    return tp_clip_weights(template, split_dims, model_n)


def tp_clip_weights(template: Any, split_dims: Any, model_n: int) -> dict:
    """{'/'.joined leaf path: squared-norm weight} for the TP-aware global
    norm clip (optim.clip_by_global_norm_dp): a psum over
    (model,) + batch axes counts model-replicated leaves M times (each
    model shard holds a copy), so their squared contribution weighs 1/M;
    TP-split leaves' disjoint slices weigh 1. Exact in fp32 for
    power-of-two M (the usual TP degrees); otherwise a reassociation-level
    perturbation PARITY.md documents."""
    out = {}
    flat = jax.tree_util.tree_leaves_with_path(
        jax.tree_util.tree_map(lambda l, d: (d is not None), template,
                               split_dims))
    for path, is_split in flat:
        out[_path_str(path)] = 1.0 if is_split else 1.0 / model_n
    return out


def reshard_flat_padded(x, new_padded_len: int, name: str = "") -> "np.ndarray":
    """Re-slice one flat-padded leaf from old-N chunking to new-M chunking.

    A valid flat-padded vector holds its true content in ``[0, true_size)``
    and zeros beyond (``flatten_pad`` pads with zeros; gradients/updates on
    pad elements are zero through any elementwise optimizer chain, and the
    int8 codecs' residuals stay zero there too — the carried value at a pad
    slot is always 0). Since ``true_size <= flat_padded_size(true_size, M)``
    for ANY shard count M, re-chunking reduces to truncate-or-zero-extend
    to the new padded length — no true size needed. Host-side numpy (this
    runs at restore time, one leaf at a time — never on the step path).

    Shrinking asserts the dropped tail really is zero: a nonzero tail means
    the input was NOT a flat-padded layout (or carried real content into
    the pad region) and silently dropping it would corrupt the trajectory.
    """
    x = np.asarray(x)
    if x.ndim != 1:
        raise ValueError(
            f"reshard_flat_padded expects a 1-D flat-padded vector, got "
            f"shape {x.shape}" + (f" for {name}" if name else ""))
    old_len = x.shape[0]
    if new_padded_len < old_len:
        tail = x[new_padded_len:]
        if np.any(tail):
            raise ValueError(
                f"re-chunking {old_len} -> {new_padded_len} elements would "
                f"drop {int(np.count_nonzero(tail))} NONZERO tail "
                "element(s) — the input is not a zero-padded flat layout"
                + (f" ({name})" if name else ""))
        return np.array(x[:new_padded_len])
    if new_padded_len > old_len:
        return np.pad(x, (0, new_padded_len - old_len))
    return np.array(x)


def reshard_flat_leaf(value, new_shape: Tuple[int, ...],
                      name: str = "") -> "np.ndarray":
    """The ONE per-leaf reshard dispatch (both the whole-tree helper below
    and the elastic restore path route through it, so the invariant cannot
    fork): same shape -> passthrough, 1-D length change -> flat-padded
    re-chunk, anything else -> loud structure error naming the leaf."""
    v = np.asarray(value)
    t = tuple(new_shape)
    if v.shape == t:
        return v
    if v.ndim == 1 and len(t) == 1:
        return reshard_flat_padded(v, t[0], name=name)
    raise ValueError(
        f"cannot reshard leaf {name!r} from shape {v.shape} to {t} — "
        "only flat-padded 1-D leaves change shape across world sizes")


def reshard_flat_tree(old_tree: Any, template_tree: Any) -> Any:
    """Re-slice every flat-padded leaf of ``old_tree`` into the shapes of
    ``template_tree`` (the new-world layout) via `reshard_flat_leaf`.
    Values are host numpy — the caller places them on the new mesh.
    (The elastic restore uses the leaf-at-a-time placing variant,
    `resilience.elastic._reshard_and_place`, to keep host memory bounded
    by one leaf; both share `reshard_flat_leaf`.)"""
    return jax.tree_util.tree_map_with_path(
        lambda path, old, tmpl: reshard_flat_leaf(
            old, np.shape(tmpl), name=_path_str(path)),
        old_tree, template_tree)


def batch_spec(ndim: int = 1) -> P:
    """Leading dim sharded over the batch axes (data, fsdp); rest replicated.

    This single annotation replaces DistributedSampler + DDP: the global batch
    is one array split over the mesh (ref :122-127 does this with per-rank
    index slicing; here it is a layout fact XLA reasons about). Scalars
    (ndim=0) have no batch dimension and are replicated.
    """
    if ndim == 0:
        return P()
    return P(BATCH_AXES, *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(ndim))


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    """Make each process-local batch shard into one global device array.

    Single-host: a plain device_put with the batch sharding. Multi-host: each
    process contributes its local slice (the generalization of the reference's
    per-rank DistributedSampler shard, train_ddp.py:122-127) via
    `make_array_from_process_local_data`.
    """
    def _one(x):
        x = np.asarray(x)
        sharding = batch_sharding(mesh, x.ndim)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(_one, batch)
