"""Ring attention — sequence/context parallelism over a mesh axis.

The long-context strategy SURVEY.md §5/§2c requires (absent from the
reference, whose max "sequence" is a 32x32 image): the sequence dimension is
sharded over the mesh ``seq`` axis; each device keeps its Q shard resident
and the K/V shards rotate around the ICI ring via ``lax.ppermute``, one hop
per step, so every device sees every K/V block while only ever holding 1/n of
the sequence — O(S/n) memory and fully overlapped neighbor exchange.

Partial results merge with the standard online-softmax (log-sum-exp) rule in
fp32, so the output is numerically equivalent to full attention. Causal
masking uses global position offsets derived from ``lax.axis_index``; steps
entirely above the diagonal contribute zero weight (masked p=0) — control
flow stays uniform across devices, as XLA requires.

Implemented with ``lax.scan`` (reverse-differentiable; ``ppermute`` has a
transpose rule, so gradients also ride the ring — no custom VJP needed) and
wrapped in ``shard_map`` so it composes inside a jitted train step.

Memory note: the cross-DEVICE memory is the O(S/n) ring win. The inner block
has two formulations, picked by ``use_pallas`` (auto: the flash kernel on
TPU when the shard length has a usable block size):

* **fused ring+flash** (the fast path): each ring step runs the blockwise
  Pallas forward kernel on the local (Q, K_j, V_j) block and merges the
  normalized partials with the fp32 log-sum-exp rule; the backward re-runs
  the ring calling the flash dq/dkv kernels against the GLOBAL lse (the
  p = exp(s - lse_final) identity makes per-block grads exact), with dk/dv
  accumulators rotating alongside K/V so they arrive home after n hops.
  Causal rings skip future blocks entirely (lax.cond, ~2x at scale); the
  diagonal block runs the causal kernel, past blocks the full kernel.
* **einsum + q-chunking** (the fallback): the local score block is computed
  in Q row chunks under ``jax.checkpoint`` (``q_chunk``, default 512),
  bounding live memory to O(q_chunk x S/n) instead of the full (S/n, S/n)
  block.

`ops.ulysses_attention` offers the alternative all-to-all layout that runs
the Pallas kernel on full sequences.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, MODEL, SEQ
from ..parallel.collectives import shard_map

NEG_INF = float(np.finfo(np.float32).min)


def _ring_body(q, k, v, axis_name: str, causal: bool, sm_scale: float,
               q_chunk: int = 512):
    """Per-device body (inside shard_map). q/k/v: (B, S_loc, H, D) local.

    Within each ring step the local score block is computed in Q row chunks
    of `q_chunk` under ``jax.checkpoint``, so live memory per step is
    O(q_chunk * S_loc) instead of O(S_loc^2) — the blockwise-attention trick
    applied along the ring (shards with S_loc <= q_chunk take the single
    straight-through block, identical to the unchunked formulation).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape

    qf = q.astype(jnp.float32) * sm_scale

    # Largest divisor of s_loc in [q_chunk/2, q_chunk]; shard lengths are
    # normally 128-multiples so this finds q_chunk itself. Pathological
    # lengths (e.g. primes) get NO near-size divisor — falling through to
    # tiny chunks would serialize the MXU (c=1 means s_loc scan steps of
    # rank-1 matmuls), so those take the single straight-through block
    # instead: correctness and throughput over the memory bound.
    c = min(q_chunk, s_loc)
    while s_loc % c and c > q_chunk // 2:
        c -= 1
    if s_loc % c:
        c = s_loc
    nc = s_loc // c

    def block_update(q_blk, k_cur, v_cur, m, l, acc, row0, j):
        """Online-softmax update of one (c, S_loc) score block.
        q_blk: (B, c, H, D); m/l: (B, H, c); acc: (B, H, c, D)."""
        s = jnp.einsum("bshd,bthd->bhst", q_blk, k_cur.astype(jnp.float32))
        if causal:
            rows = my_idx * s_loc + row0 + lax.broadcasted_iota(
                jnp.int32, (c, s_loc), 0)
            cols = j * s_loc + lax.broadcasted_iota(
                jnp.int32, (c, s_loc), 1)
            valid = (rows >= cols)[None, None]
            s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))  # (B, H, c)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = (acc * alpha[..., None]
                   + jnp.einsum("bhst,bthd->bhsd", p,
                                v_cur.astype(jnp.float32)))
        return m_new, l_new, acc_new

    if nc > 1:
        # recompute each block in the backward instead of storing its p
        block_update = jax.checkpoint(block_update)

    def step(carry, t):
        k_cur, v_cur, m, l, acc = carry
        j = (my_idx - t) % n  # which global shard this K/V block is
        if nc == 1:
            m, l, acc = block_update(qf, k_cur, v_cur, m, l, acc, 0, j)
        else:
            # chunks are independent rows: map over them, threading only
            # that chunk's (m, l, acc) slice
            q_c = qf.reshape(b, nc, c, h, d).transpose(1, 0, 2, 3, 4)
            m_c = m.reshape(b, h, nc, c).transpose(2, 0, 1, 3)
            l_c = l.reshape(b, h, nc, c).transpose(2, 0, 1, 3)
            acc_c = acc.reshape(b, h, nc, c, d).transpose(2, 0, 1, 3, 4)

            def one_chunk(i, args):
                qb, mb, lb, ab = args
                return block_update(qb, k_cur, v_cur, mb, lb, ab, i * c, j)

            def scan_fn(_, xs):
                i, args = xs
                return None, one_chunk(i, args)

            _, (m_c, l_c, acc_c) = lax.scan(
                scan_fn, None, (jnp.arange(nc), (q_c, m_c, l_c, acc_c)))
            m = m_c.transpose(1, 2, 0, 3).reshape(b, h, s_loc)
            l = l_c.transpose(1, 2, 0, 3).reshape(b, h, s_loc)
            acc = acc_c.transpose(1, 2, 0, 3, 4).reshape(b, h, s_loc, d)
        # rotate K/V to the next device on the ring (one ICI hop)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (_, _, m, l, acc), _ = lax.scan(step, (k, v, m0, l0, acc0),
                                    jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, H, S, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, S, H, D)


# ---------------------------------------------------------------------------
# fused ring + flash inner block
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name: str, causal: bool, sm_scale: float,
                block_q: int, block_k: int):
    """Per-device fused ring body (inside shard_map): the Pallas flash
    forward on each ring step's local block, fp32 lse-merge across steps.
    Differentiable via an explicit ring backward (below) — the flash
    kernels' own grads against the global lse, not autodiff through the
    scan's einsum."""
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                  block_q, block_k)
    return out


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                         block_q, block_k):
    from .flash_attention import _flash_fwd_lse

    n = lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def fwd_block(k_cur, v_cur, blk_causal):
        o_j, lse_j = _flash_fwd_lse(q, k_cur, v_cur, blk_causal, sm_scale,
                                    block_q, block_k)
        return o_j.astype(jnp.float32), lse_j

    def step(carry, t):
        k_cur, v_cur, o, lse = carry
        if causal:
            # which global shard this K/V block is. my/j are computed only
            # when consumed: left dead (the non-causal path never reads
            # them), the axis_index survives the custom_vjp partial-eval
            # un-DCE'd in the scan body and lowers to a bare partition-id
            # HLO op the SPMD partitioner rejects (jax 0.4.x — the
            # TestRingFlashFused PartitionId failure)
            my = lax.axis_index(axis_name)
            j = (my - t) % n
            # diagonal -> causal kernel; past -> full kernel; future ->
            # skipped entirely (the ~2x causal win the einsum ring only
            # gets as masked-but-computed blocks)
            o_j, lse_j = lax.cond(
                j == my,
                lambda: fwd_block(k_cur, v_cur, True),
                lambda: lax.cond(
                    j < my,
                    lambda: fwd_block(k_cur, v_cur, False),
                    lambda: (jnp.zeros((b, s_loc, h, d), jnp.float32),
                             jnp.full((b * h, 1, s_loc), NEG_INF,
                                      jnp.float32))))
        else:
            o_j, lse_j = fwd_block(k_cur, v_cur, False)
        # merge normalized partials: o = sum_j exp(lse_j - lse) o_j
        lse_new = jnp.logaddexp(lse, lse_j)

        def rw(wx):  # (BH, 1, S) weight -> (B, S, H, 1)
            return wx.reshape(b, h, s_loc).transpose(0, 2, 1)[..., None]

        o = o * rw(jnp.exp(lse - lse_new)) + o_j * rw(jnp.exp(lse_j - lse_new))
        return (lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm), o, lse_new), None

    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    lse0 = jnp.full((b * h, 1, s_loc), NEG_INF, jnp.float32)
    (_, _, o, lse), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    return o.astype(q.dtype), lse


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, sm_scale,
                        block_q, block_k):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                    block_q, block_k)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, sm_scale, block_q, block_k,
                        residuals, g):
    """Ring backward: rotate K/V around again, run the flash dq/dkv kernels
    per block against the GLOBAL lse (p = exp(s - lse_final) gives exact
    per-block partials), and rotate the dk/dv accumulators alongside so
    each shard's gradients arrive back at their owner after n hops."""
    from .flash_attention import _flash_bwd

    q, k, v, out, lse = residuals
    n = lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def bwd_block(k_cur, v_cur, blk_causal):
        dq_j, dk_j, dv_j = _flash_bwd(q, k_cur, v_cur, out, lse, g,
                                      blk_causal, sm_scale, block_q, block_k)
        return (dq_j.astype(jnp.float32), dk_j.astype(jnp.float32),
                dv_j.astype(jnp.float32))

    def zeros3():
        z = jnp.zeros((b, s_loc, h, d), jnp.float32)
        return z, z, z

    def step(carry, t):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        if causal:
            my = lax.axis_index(axis_name)
            j = (my - t) % n  # only computed when consumed — see fwd
            dq_j, dk_j, dv_j = lax.cond(
                j == my,
                lambda: bwd_block(k_cur, v_cur, True),
                lambda: lax.cond(
                    j < my,
                    lambda: bwd_block(k_cur, v_cur, False),
                    zeros3))
        else:
            dq_j, dk_j, dv_j = bwd_block(k_cur, v_cur, False)
        return (lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm),
                lax.ppermute(dk_cur + dk_j, axis_name, perm),
                lax.ppermute(dv_cur + dv_j, axis_name, perm),
                dq + dq_j), None

    z = jnp.zeros((b, s_loc, h, d), jnp.float32)
    (_, _, dk_acc, dv_acc, dq_acc), _ = lax.scan(
        step, (k, v, z, z, z), jnp.arange(n))
    return (dq_acc.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(
    q: jnp.ndarray,  # (B, S, H, D) — S sharded over `axis_name`
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    axis_name: str = SEQ,
    q_chunk: int = 512,
    use_pallas: Optional[bool] = None,
    block_q: int = 512,
    block_k: int = 512,
) -> jnp.ndarray:
    """Sequence-parallel attention over the mesh `seq` axis.

    Composes inside jit: shard_map forces the (B, S, H, D) operands onto
    (batch-axes, seq, model, -) layout; XLA reshards neighbors as needed.
    With seq axis size 1 this degrades to ordinary attention semantics.

    ``use_pallas`` picks the inner block: None (default) auto-selects the
    fused ring+flash path on TPU when the SHARD length (S / seq-axis) has a
    usable block size, else the q-chunked einsum (``q_chunk`` bounds its
    per-ring-step score memory, see `_ring_body`). Tests force either path
    explicitly (the flash kernels run in interpreter mode on CPU)."""
    from .flash_attention import flash_backend_supported, flash_supports_length

    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    n_seq = dict(mesh.shape).get(axis_name, 1)
    s_loc = q.shape[1] // max(n_seq, 1)
    if use_pallas is None:
        use_pallas = (flash_backend_supported()
                      and flash_supports_length(s_loc, block_q)
                      and flash_supports_length(s_loc, block_k))
    spec = P(BATCH_AXES, axis_name, MODEL, None)
    if use_pallas:
        # positional call: custom_vjp nondiff_argnums are positional
        def body(q, k, v):
            return _ring_flash(q, k, v, axis_name, causal, scale,
                               block_q, block_k)
    else:
        body = functools.partial(_ring_body, axis_name=axis_name,
                                 causal=causal, sm_scale=scale,
                                 q_chunk=q_chunk)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def ring_attention_sharded(
    q: jnp.ndarray,  # (B, S_loc, H, D) — THIS shard's sequence block
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = SEQ,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    q_chunk: int = 512,
    use_pallas: Optional[bool] = None,
    block_q: int = 512,
    block_k: int = 512,
) -> jnp.ndarray:
    """`ring_attention`'s body for callers ALREADY inside a shard_map —
    the explicit TP x FSDP step runs its whole program in one shard_map
    over the 2-D ("data","model") mesh (training/loop.py), where a nested
    shard_map cannot open; this entry takes the bound ``axis_name``
    directly (any axis of that mesh — ``seq`` for sequence-length scaling
    beside the TP axes) and operands that are the per-shard blocks.
    Same kernel dispatch as `ring_attention` (fused ring+flash on TPU
    when the shard length has a usable block, q-chunked einsum
    otherwise), resolved from the LOCAL shard length — the caller's
    shapes are already per-shard."""
    from .flash_attention import flash_backend_supported, flash_supports_length

    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    s_loc = q.shape[1]
    if use_pallas is None:
        use_pallas = (flash_backend_supported()
                      and flash_supports_length(s_loc, block_q)
                      and flash_supports_length(s_loc, block_k))
    if use_pallas:
        return _ring_flash(q, k, v, axis_name, causal, scale,
                           block_q, block_k)
    return _ring_body(q, k, v, axis_name=axis_name, causal=causal,
                      sm_scale=scale, q_chunk=q_chunk)


def make_ring_attention_fn(mesh: Mesh, causal: bool, axis_name: str = SEQ,
                           q_chunk: int = 512,
                           use_pallas: Optional[bool] = None):
    """Adapter matching models.layers' `attention_fn(q, k, v, mask, dtype)`.

    Explicit masks are unsupported — causal structure is positional,
    computed from global offsets on each shard. `q_chunk` bounds the
    einsum fallback's per-ring-step score memory; `use_pallas` forwards
    the inner-block choice (None = auto: flash on TPU).
    """

    def attention_fn(q, k, v, mask=None, dtype=jnp.float32):
        if mask is not None:
            raise ValueError(
                "ring attention handles causal masking internally; explicit "
                "masks require the XLA attention path")
        return ring_attention(q, k, v, mesh, causal=causal,
                              axis_name=axis_name, q_chunk=q_chunk,
                              use_pallas=use_pallas).astype(dtype)

    return attention_fn
