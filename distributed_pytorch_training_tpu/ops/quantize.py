"""Fused int8 quantization — Pallas TPU kernels for the gradient-wire codecs.

The int8 wire codecs in ``parallel/grad_sync.py`` are XLA-composed today:
abs → max → divide → round → clip → convert for the quantize, and
convert → multiply → reduce for the dequant-accumulate. XLA schedules those
as separate HBM-roundtripping ops around the collective (visible as a fusion
chain on profiles), so each bucket pays several extra read/write passes of
bucket-sized fp32 data on the step's critical path. These kernels fuse each
codec hot loop into ONE VMEM pass (the ``ops/flash_attention.py`` machinery
applied to the wire):

* ``quantize_int8_rows_fused`` — the row-wise symmetric quantizer
  (``_quantize_int8_rows``'s grid): one running-absmax pass and one
  scale+round+clip pass over (block-sized) VMEM tiles, two-phase on the same
  Pallas grid so the input streams HBM→VMEM exactly twice and the s8 codes +
  fp32 scales are produced by one kernel launch.
* ``dequant_sum_rows_fused`` — the receive-side dequant-accumulate (the
  hop-1 local fp32 partial sum of ``_int8_multihop_sum``, and the same
  shape in the zero1 s8 scatter and the gather-form int8 sum): s8 codes ×
  per-row scales summed over rows in VMEM, one pass.

EXACTNESS CONTRACT (PARITY.md): both kernels are BIT-IDENTICAL to the
XLA-composed reference on the int8 grid — same absmax (exact, associative),
same ``max(amax, 1e-30)/127`` scale, same round/clip, same fp32
dequant-sum reduction order over the row axis. The fused path is a
scheduling change, never a numerics change; tests/test_quantize.py pins
code-for-code and bit-for-bit equality, and the int8/int8_multihop parity
suites run unchanged with the kernel path selected.

Gating (the ``flash_backend_supported`` convention): the kernels are worth
running only on real TPU — ``quantize_backend_supported()`` is the one
gate, and on CPU backends they run in interpreter mode (tests force the
fused path there to pin parity; the XLA-composed path stays the CPU/tier-1
reference by default). Selection order: an explicit
``TrainConfig.fused_quantize`` wins; else the ``DPT_FUSED_QUANTIZE`` env
("1"/"0") wins; else the backend gate decides.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Quantization grid half-width — MUST match parallel/grad_sync.py's _QMAX
# (symmetric [-127, 127]; -128 unused so dequantization is a pure scale).
QMAX = 127.0

# Env override for the fused-path default ("1" forces the kernels — on CPU
# that means interpreter mode, the parity-test configuration; "0" forces the
# XLA-composed reference). An explicit TrainConfig.fused_quantize beats it.
FUSED_QUANTIZE_ENV = "DPT_FUSED_QUANTIZE"


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def quantize_backend_supported(backend: Optional[str] = None) -> bool:
    """ONE place for the backend gate (the ``flash_backend_supported``
    convention): the fused codec kernels are worth running only on real
    TPU. CPU would run them in interpreter mode (pure overhead outside
    tests); the pltpu VMEM scratch shapes cannot lower on GPU."""
    return (backend or jax.default_backend()) == "tpu"


def fused_quantize_default() -> bool:
    """The auto gate: ``DPT_FUSED_QUANTIZE`` env override when set,
    otherwise TPU-only (`quantize_backend_supported`)."""
    env = os.environ.get(FUSED_QUANTIZE_ENV)
    if env is not None and env.strip() in ("0", "1"):
        return env.strip() == "1"
    return quantize_backend_supported()


def resolve_fused(flag: Optional[bool]) -> bool:
    """Resolve a TrainConfig-style tri-state (None = auto) to a concrete
    trace-time choice. Called at trace time by the grad_sync codecs."""
    return fused_quantize_default() if flag is None else bool(flag)


# fp32 input-tile budget per grid step: well under VMEM (~16MB on current
# parts) with room for the output/scratch refs riding the same step.
_TILE_BUDGET_BYTES = 512 * 1024


def _fit_block(s: int, n: int = 1) -> Tuple[int, int]:
    """(block_c, padded_s) for a length-``s`` lane axis of an ``n``-row
    tile: lane blocks must be multiples of 128 (TPU lane width) and tile
    the padded axis exactly. The block width scales inversely with the row
    count so one grid step streams ~``_TILE_BUDGET_BYTES`` of fp32 input
    regardless of shape — a single-row whole-bucket codec (the plain int8
    wire quantizes each bucket as one (1, ~1M) row) must not decay into
    thousands of DMA-latency-bound 2KB-tile steps. Block width never
    changes the numerics: row absmax is order-invariant and the dequant
    sum reduces over rows within a column, never across lane blocks.
    Inputs are zero-padded to ``padded_s`` by the wrappers — zeros never
    change a row's absmax (>= 0 with the 1e-30 floor) and dequantize-sum
    to exactly 0, so padding is invisible to the numerics."""
    if s <= 0:
        raise ValueError(f"quantize kernels need a non-empty row, got {s}")
    requested = max(512, _TILE_BUDGET_BYTES // (max(n, 1) * 4) // 128 * 128)
    block = min(requested, -(-s // 128) * 128)
    return block, -(-s // block) * block


# ---------------------------------------------------------------------------
# fused quantize: running absmax pass + scale/round/clip pass, one launch
# ---------------------------------------------------------------------------


def _quantize_kernel(x_ref, q_ref, s_ref, amax_scr, *, nblocks: int):
    phase, j = pl.program_id(0), pl.program_id(1)

    @pl.when((phase == 0) & (j == 0))
    def _init():
        amax_scr[...] = jnp.zeros_like(amax_scr)

    @pl.when(phase == 0)
    def _accumulate():
        # running per-row absmax across lane blocks — fp32 max is exact and
        # associative, so the blockwise running max IS the reference's
        # jnp.max(jnp.abs(rows), axis=1)
        amax_scr[...] = jnp.maximum(
            amax_scr[...],
            jnp.max(jnp.abs(x_ref[...]), axis=1, keepdims=True))

    # scale = amax * (1/127), an explicit multiply: XLA rewrites division
    # by a constant to exactly this inside compiled steps, so the multiply
    # IS the reference arithmetic (grad_sync._quantize_int8_rows matches).
    @pl.when((phase == 0) & (j == nblocks - 1))
    def _scales():
        s_ref[...] = jnp.maximum(amax_scr[...], 1e-30) * (1.0 / QMAX)

    @pl.when(phase == 1)
    def _codes():
        scale = jnp.maximum(amax_scr[...], 1e-30) * (1.0 / QMAX)
        q_ref[...] = jnp.clip(jnp.round(x_ref[...] / scale),
                              -QMAX, QMAX).astype(jnp.int8)


def quantize_int8_rows_fused(rows: jnp.ndarray
                             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused row-wise symmetric int8 quantization of a (n, s) fp32 matrix:
    one fp32 max-abs scale per row, s8 codes. Bit-identical to
    ``parallel.grad_sync._quantize_int8_rows`` (the XLA-composed
    reference) — same grid, same scale arithmetic, same round/clip."""
    n, s = rows.shape
    block_c, padded = _fit_block(s, n)
    nblocks = padded // block_c
    x = rows if padded == s else jnp.pad(rows, ((0, 0), (0, padded - s)))
    q, scales = pl.pallas_call(
        functools.partial(_quantize_kernel, nblocks=nblocks),
        grid=(2, nblocks),
        in_specs=[pl.BlockSpec((n, block_c), lambda phase, j: (0, j))],
        out_shape=[
            jax.ShapeDtypeStruct((n, padded), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        out_specs=[
            pl.BlockSpec((n, block_c), lambda phase, j: (0, j)),
            pl.BlockSpec((n, 1), lambda phase, j: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((n, 1), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            # two streaming passes (abs/max + div/round/clip), ~4 vector
            # ops per element; no transcendentals, no MXU
            flops=8 * n * padded, transcendentals=0,
            bytes_accessed=2 * n * padded * 4 + n * padded + n * 4),
        interpret=_interpret(),
        name="fused_quantize_int8_rows",
    )(x)
    return q[:, :s], scales[:, 0]


# ---------------------------------------------------------------------------
# fused dequant-accumulate: codes x per-row scales summed over rows
# ---------------------------------------------------------------------------


def _dequant_sum_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = jnp.sum(q_ref[...].astype(jnp.float32) * s_ref[...],
                         axis=0, keepdims=True)


def dequant_sum_rows_fused(q: jnp.ndarray,
                           scales: jnp.ndarray) -> jnp.ndarray:
    """Fused SUM of dequantized rows: (n, s) s8 codes x (n,) fp32 per-row
    scales -> (s,) fp32 column sums — the receive-side accumulate of every
    int8 wire (the hop-1 local partial sum of ``_int8_multihop_sum``, the
    zero1 s8 scatter's sum, the gather-form int8 sum). Bit-identical to
    ``jnp.sum(q.astype(f32) * scales[:, None], axis=0)``: the reduction
    runs over the full row axis inside one VMEM tile, same order."""
    n, s = q.shape
    block_c, padded = _fit_block(s, n)
    x = q if padded == s else jnp.pad(q, ((0, 0), (0, padded - s)))
    out = pl.pallas_call(
        _dequant_sum_kernel,
        grid=(padded // block_c,),
        in_specs=[
            pl.BlockSpec((n, block_c), lambda j: (0, j)),
            pl.BlockSpec((n, 1), lambda j: (0, 0)),
        ],
        out_shape=jax.ShapeDtypeStruct((1, padded), jnp.float32),
        out_specs=pl.BlockSpec((1, block_c), lambda j: (0, j)),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * padded, transcendentals=0,
            bytes_accessed=n * padded + n * 4 + padded * 4),
        interpret=_interpret(),
        name="fused_dequant_sum_rows",
    )(x, scales[:, None])
    return out[0, :s]
