"""Pallas TPU kernels — the hot-op layer.

The reference leans on cuDNN/CUDA kernels via torch (SURVEY.md §2b); here the
XLA compiler covers most fusion, and Pallas supplies the ops XLA does not
schedule optimally: blockwise (flash) attention and the ring-attention
context-parallel primitive (SURVEY.md §5 long-context requirement).
"""

from .flash_attention import (  # noqa: F401
    flash_attention,
    flash_backend_supported,
    flash_supports_length,
    make_flash_attention_fn,
)
from .ring_attention import make_ring_attention_fn, ring_attention  # noqa: F401
from .ulysses_attention import (  # noqa: F401
    make_ulysses_attention_fn,
    ulysses_attention,
)
