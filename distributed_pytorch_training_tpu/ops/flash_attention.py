"""Blockwise (flash) attention — Pallas TPU kernels, forward AND backward.

Memory-efficient attention: O(S) live memory instead of materializing the
(S, S) score matrix, via online softmax over K/V blocks. This is the
long-context building block SURVEY.md §5 requires (the reference has no
attention at all — ResNet on 32x32 images; the capability enters through the
BERT-512/GPT-2 configs, BASELINE.json:11-12).

Design (per pallas_guide.md; FlashAttention-2 formulation):

* forward — grid (batch*heads, Sq/block_q, Sk/block_k), K block index
  innermost so VMEM scratch accumulators (running max m, denom l, output acc)
  carry across K iterations; ONLY one (block_q, d) + (block_k, d) tile lives
  in VMEM at a time — full K/V never does (the r2 kernel held all of K/V per
  (batch, head), capping sequence length at VMEM size). Emits the row
  logsumexp for the backward. MXU matmuls via jnp.dot(...,
  preferred_element_type=f32); softmax statistics in f32.
* causal masking skips whole K blocks past the diagonal (pl.when on the
  block index — no MXU work issued; the rectangular grid still walks the
  masked steps and their tile DMAs, which overlap live blocks' compute),
  masking only the diagonal blocks with broadcasted_iota.
* backward — two Pallas kernels, no O(S^2) rematerialization:
  - dK/dV: grid (..., Sk/block_k, Sq/block_q), Q innermost; for each Q block
    regenerate p = exp(s - lse), accumulate dv += p^T dO and
    dk += (p * (dO v^T - delta))^T q in VMEM scratch.
  - dQ: grid (..., Sq/block_q, Sk/block_k), K innermost; accumulate
    dq += (p * (dO v^T - delta)) k.
  delta = rowsum(dO * O) is a cheap elementwise XLA op outside the kernels.
  Causal variants skip fully-masked blocks entirely.
* on CPU backends (tests, dry-runs) the kernels run in interpreter mode —
  the S=4096 grad-parity test in tests/test_attention.py runs there.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def flash_backend_supported(backend: Optional[str] = None) -> bool:
    """ONE place for the backend gate shared by the bench harness and
    ``--attention auto``: the kernels are worth running only on real TPU.
    CPU would run pallas in interpreter mode (pure overhead); the pltpu
    VMEM scratch shapes cannot lower on GPU."""
    return (backend or jax.default_backend()) == "tpu"


def flash_supports_length(s: int, requested: int = 512) -> bool:
    """True iff `_fit_block` can pick a usable block for a length-`s` axis —
    lets ``--attention auto`` fall back to the einsum path instead of
    erroring on lengths with no multiple-of-8 divisor (> 1024)."""
    try:
        _fit_block(requested, s)
        return True
    except ValueError:
        return False


def _fit_block(requested: int, s: int) -> int:
    """Largest legal block size <= `requested` for a length-`s` axis.

    TPU lowering needs the sublane block dim divisible by 8 (or spanning the
    whole axis), and pallas grids need block | s. Prefers the largest
    divisor of s that is a multiple of 8 and <= requested; falls back to the
    full axis (always legal). 512 beat 128/256 on v5e for GPT-2 @ S=1024
    (90.7 vs 143.5 / 109.6 ms per train step), hence the public default.

    An explicit multiple-of-8 request that divides s is honored as-is (the
    %8 requirement is the TPU sublane rule; e.g. requested=100 with s=200
    divides evenly but still goes through the search) — a caller asking for
    small legal blocks gets them (minimal VMEM, their trade); the
    degenerate-grid floor below only guards the *auto-degradation* path
    where a large request would silently shrink to slivers."""
    b = min(max(requested, 8), s)
    if s % b == 0 and (b % 8 == 0 or b == s):
        return b
    # Degenerate divisors make degenerate grids (S=2056 = 8*257 would run
    # 8-wide tiles on a 128-wide MXU), so only accept blocks that keep the
    # grid reasonable: >= 128 wide, or at most 8 blocks along the axis.
    floor = min(128, max(1, s // 8))
    for cand in range(b - b % 8, 7, -8):
        if s % cand == 0 and cand >= floor:
            return cand
    # No usable divisor: spanning the axis is always legal and fine for
    # short sequences, but it would forfeit the blockwise VMEM bound for
    # long ones — fail loudly there instead.
    if s > 1024:
        raise ValueError(
            f"flash_attention: sequence length {s} has no usable block "
            f"size; pad the sequence to a multiple of 128")
    return s


def _reference_attention(q, k, v, causal: bool, sm_scale: float,
                         kv_valid=None):
    """XLA einsum attention — the parity oracle for tests."""
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * sm_scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))[None, None]
        logits = jnp.where(mask, logits, NEG_INF)
    if kv_valid is not None:
        logits = jnp.where(kv_valid[:, None, None, :] > 0, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", weights, v)


def _live_pairs(nqb: int, nkb: int, block_q: int, block_k: int,
                causal: bool) -> int:
    """Number of (q-block, k-block) grid pairs that issue MXU work — causal
    skips blocks fully above the diagonal, so FLOPs accounting that scales
    one tile by the whole grid would overcount attention ~2x."""
    if not causal:
        return nqb * nkb
    qb = np.arange(nqb)[:, None] * block_q + block_q - 1
    kb = np.arange(nkb)[None, :] * block_k
    return int(np.sum(qb >= kb))


def _cost(flops: float, transcendentals: float, bytes_accessed: float):
    """Exact per-call cost handed to pallas_call so FLOPs instruments (XLA's
    and experiments/flops.py's jaxpr walk) see the causal-aware count
    instead of scaling one tile's matmuls by the full rectangular grid."""
    return pl.CostEstimate(flops=int(flops),
                           transcendentals=int(transcendentals),
                           bytes_accessed=int(bytes_accessed))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *refs,
                block_q: int, block_k: int, causal: bool, sm_scale: float,
                masked: bool):
    if masked:
        kvm_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        kvm_ref, (o_ref, lse_ref, m_scr, l_scr, acc_scr) = None, refs
    qb, kb = pl.program_id(1), pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: K blocks fully above the diagonal contribute nothing
    live = (qb * block_q + block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # (bq, d)
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if masked:
            # key-padding: masked keys contribute nothing to any query row.
            # Safe online-softmax interaction: an all-masked block leaves m
            # at NEG_INF, so p==1 garbage can accumulate only until the
            # first live block, whose alpha rescales it to exactly 0.
            s = jnp.where(kvm_ref[0, 0][None, :] > 0, s, NEG_INF)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kb == nkb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[...] + jnp.log(l))[:, 0]


def _flash_fwd_lse(q, k, v, causal: bool, sm_scale: float,
                   block_q: int, block_k: int,
                   kv_valid=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (out (BH, Sq, d) folded back to (B, Sq, H, d), lse (BH, 1, Sq)).
    `kv_valid`: optional (B, Sk) float validity mask (1=real key, 0=pad)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    masked = kv_valid is not None

    grid = (b * h, sq // block_q, sk // block_k)
    live = _live_pairs(sq // block_q, sk // block_k, block_q, block_k, causal)
    # lse rides as (BH, 1, Sq): a 2-D (BH, Sq) output with block (1, block_q)
    # violates the TPU lowering rule that the second-to-last block dim be
    # divisible by 8 or span the array dim; the singleton middle axis spans
    # its dim, making the (1, 1, block_q) block legal on hardware.
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
    ]
    operands = [qf, kf, vf]
    if masked:
        # (B, 1, Sk) so the (1, 1, block_k) block lowers like lse does; the
        # index map folds heads back to the batch row — no BH-sized copy.
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bh, i, j, h=h: (bh // h, 0, j)))
        operands.append(kv_valid.astype(jnp.float32)[:, None, :])
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, sm_scale=sm_scale, masked=masked),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        cost_estimate=_cost(
            # per live pair per bh: QK^T + PV, 2*2*bq*bk*d
            flops=b * h * live * 4 * block_q * block_k * d,
            # exp(s - m_new) per live tile + the finalize log per q row
            transcendentals=b * h * (live * block_q * block_k + sq),
            bytes_accessed=(
                b * h * grid[1] * grid[2] *
                (block_q * d + 2 * block_k * d) * q.dtype.itemsize
                + b * h * sq * (d * q.dtype.itemsize + 4))),
        interpret=_interpret(),
    )(*operands)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    block_q: int, block_k: int, causal: bool,
                    sm_scale: float, masked: bool):
    if masked:
        kvm_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        kvm_ref, (dk_ref, dv_ref, dk_scr, dv_scr) = None, refs
    kb, qb = pl.program_id(1), pl.program_id(2)
    nqb = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (qb * block_q + block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)                  # (bq, d)
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)                # (bq, d)
        lse = lse_ref[0, 0][:, None]                      # (bq, 1)
        delta = delta_ref[0, 0][:, None]                  # (bq, 1)
        s = sm_scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if masked:
            # re-mask in the backward: without it p=exp(s-lse) would be
            # nonzero at padded keys and leak gradient into padding K/V
            s = jnp.where(kvm_ref[0, 0][None, :] > 0, s, NEG_INF)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dv_scr[...] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qb == nqb - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   block_q: int, block_k: int, causal: bool,
                   sm_scale: float, masked: bool):
    if masked:
        kvm_ref, dq_ref, dq_scr = refs
    else:
        kvm_ref, (dq_ref, dq_scr) = None, refs
    qb, kb = pl.program_id(1), pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (qb * block_q + block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = sm_scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if masked:
            s = jnp.where(kvm_ref[0, 0][None, :] > 0, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(kb == nkb - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal: bool, sm_scale: float,
               block_q: int, block_k: int, kv_valid=None):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    masked = kv_valid is not None

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dof = g.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    of = out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term;
    # (BH, 1, Sq) like lse so its (1, 1, block_q) block lowers on TPU.
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)[:, None, :]
    kvm = kv_valid.astype(jnp.float32)[:, None, :] if masked else None

    nqb, nkb = sq // block_q, sk // block_k
    live = _live_pairs(nqb, nkb, block_q, block_k, causal)
    read_bytes = (b * h * nqb * nkb *
                  (2 * block_q * d + 2 * block_k * d) * q.dtype.itemsize)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, j))
    dkv_in_specs = [
        q_spec,                                               # q by j
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
        q_spec,                                               # dO by j
        row_spec,                                             # lse by j
        row_spec,                                             # delta by j
    ]
    dkv_operands = [qf, kf, vf, dof, lse, delta]
    if masked:
        # the K-block index is i in this kernel's grid
        dkv_in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bh, i, j, h=h: (bh // h, 0, i)))
        dkv_operands.append(kvm)
    dkv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, sm_scale=sm_scale, masked=masked),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        grid=(b * h, nkb, nqb),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        cost_estimate=_cost(
            # per live pair: s, dv+=p^T dO, dp=dO v^T, dk+=ds^T q
            flops=b * h * live * 8 * block_q * block_k * d,
            transcendentals=b * h * live * block_q * block_k,
            bytes_accessed=read_bytes +
            b * h * 2 * sk * d * k.dtype.itemsize),
        interpret=_interpret(),
    )
    dk, dv = dkv(*dkv_operands)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
    ]
    dq_operands = [qf, kf, vf, dof, lse, delta]
    if masked:
        dq_in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bh, i, j, h=h: (bh // h, 0, j)))
        dq_operands.append(kvm)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, sm_scale=sm_scale, masked=masked),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, nqb, nkb),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        cost_estimate=_cost(
            # per live pair: s, dp=dO v^T, dq+=ds k
            flops=b * h * live * 6 * block_q * block_k * d,
            transcendentals=b * h * live * block_q * block_k,
            bytes_accessed=read_bytes +
            b * h * sq * d * q.dtype.itemsize),
        interpret=_interpret(),
    )(*dq_operands)

    def unflat(x, s):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jnp.ndarray,  # (B, S, H, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    kv_valid: Optional[jnp.ndarray] = None,  # (B, Sk), 1=real key, 0=pad
) -> jnp.ndarray:
    """Blockwise attention; numerically equivalent to softmax(QK^T*scale)V.

    `kv_valid` is a key-padding validity mask applied inside the blocks
    (forward AND backward recompute), so padded batches keep the flash fast
    path. Rows whose keys are ALL masked emit mean(V) — the standard
    contract that the loss zero-weights padded query rows (then their
    cotangent is exactly 0 and no gradient leaks through the garbage)."""
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out, _ = _flash_fwd_lse(q, k, v, causal, scale, block_q, block_k,
                            kv_valid)
    return out


def _vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_valid=None):
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out, lse = _flash_fwd_lse(q, k, v, causal, scale, block_q, block_k,
                              kv_valid)
    return out, (q, k, v, out, lse, kv_valid)


def _vjp_bwd(causal, sm_scale, block_q, block_k, residuals, g):
    q, k, v, out, lse, kv_valid = residuals
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q,
                            block_k, kv_valid)
    dmask = None if kv_valid is None else jnp.zeros_like(kv_valid)
    return dq, dk, dv, dmask


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def _as_kv_valid(mask, batch: int, sk: int) -> Optional[jnp.ndarray]:
    """Extract a (B, Sk) key-validity vector from a models.layers-style
    attention mask (broadcastable to (B, H, Sq, Sk), True=attend), or None
    when the mask is not a pure key-padding pattern."""
    if mask is None:
        return None
    shape = tuple(mask.shape)
    # the padding_mask() form: (B, 1, 1, Sk) — constant over heads and rows
    if len(shape) == 4 and shape[0] in (1, batch) and shape[1] == 1 \
            and shape[2] == 1 and shape[3] == sk:
        kv = mask[:, 0, 0, :]
        return jnp.broadcast_to(kv, (batch, sk))
    if len(shape) == 2 and shape == (batch, sk):
        return mask
    return None


def make_flash_attention_fn(causal: bool, block_q: int = 512, block_k: int = 512):
    """Adapter matching models.layers' `attention_fn(q, k, v, mask, dtype)`.

    Causal structure is handled inside the kernel via block skipping (faster
    than passing a causal mask to the einsum path). Key-padding masks — the
    (B, 1, 1, Sk) form layers.padding_mask produces — ride the kernel too,
    so real padded batches (BERT MLM) keep the flash path. Any other mask
    shape falls back to the XLA einsum path rather than erroring: the fast
    path must cover all data, and general (Sq, Sk)-structured masks have no
    blockwise formulation here."""

    def attention_fn(q, k, v, mask=None, dtype=jnp.float32):
        kv_valid = _as_kv_valid(mask, q.shape[0], k.shape[1])
        if mask is not None and kv_valid is None:
            from ..models.layers import dot_product_attention

            if causal:
                cm = jnp.tril(jnp.ones((q.shape[1], k.shape[1]),
                                       bool))[None, None]
                mask = mask.astype(bool) & cm
            return dot_product_attention(q, k, v, mask=mask, dtype=dtype)
        return flash_attention(q, k, v, causal, None, block_q, block_k,
                               kv_valid).astype(dtype)

    return attention_fn
