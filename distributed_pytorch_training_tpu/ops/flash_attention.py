"""Blockwise (flash) attention — Pallas TPU kernel.

Memory-efficient attention: O(S) live memory instead of materializing the
(S, S) score matrix, via online softmax over K/V blocks held in VMEM. This is
the long-context building block SURVEY.md §5 requires (the reference has no
attention at all — ResNet on 32x32 images; the capability enters through the
BERT-512/GPT-2 configs, BASELINE.json:11-12).

Design (per pallas_guide.md):
* grid = (batch*heads, Sq/block_q); K/V for one (batch, head) live in VMEM;
  the kernel fori_loops over K blocks with a running (max, denom, acc) online
  softmax in fp32; MXU matmuls via jnp.dot(..., preferred_element_type=f32).
* causal masking skips whole K blocks past the diagonal (loop bound, not a
  mask), masking only the diagonal block with broadcasted_iota.
* backward: custom_vjp that recomputes attention with the XLA reference path
  (rematerialization trades FLOPs for memory, the TPU-idiomatic default);
  a fully-blockwise backward kernel is a further optimization.
* on CPU backends (tests, dry-runs) the kernel runs in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(np.finfo(np.float32).min)


def _reference_attention(q, k, v, causal: bool, sm_scale: float):
    """XLA einsum attention (the recompute path for the backward pass)."""
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * sm_scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))[None, None]
        logits = jnp.where(mask, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", weights, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                causal: bool, sm_scale: float):
    # q_ref: (1, block_q, d); k_ref/v_ref: (1, Sk, d); o_ref: (1, block_q, d)
    qb = pl.program_id(1)
    d = q_ref.shape[-1]
    sk = k_ref.shape[1]
    nkb = sk // block_k

    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d)

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # only K blocks intersecting the lower triangle of this Q block
        upper = jax.lax.min(nkb, pl.cdiv((qb + 1) * block_q, block_k))
    else:
        upper = nkb

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, sm_scale: float,
               block_q: int, block_k: int):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # (B, S, H, D) -> (B*H, S, D): heads become independent grid rows.
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash_attention: seq lengths ({sq}, {sk}) must be divisible by "
            f"block sizes ({block_q}, {block_k})")

    grid = (b * h, sq // block_q)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, sm_scale=sm_scale),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        interpret=(jax.default_backend() == "cpu"),
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jnp.ndarray,  # (B, S, H, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Blockwise attention; numerically equivalent to softmax(QK^T*scale)V."""
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)


def _vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _vjp_bwd(causal, sm_scale, block_q, block_k, residuals, g):
    q, k, v = residuals
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    # Rematerialize through the XLA reference path (same math, O(S^2) scores
    # regenerated rather than stored — the jax.checkpoint idiom).
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def make_flash_attention_fn(causal: bool, block_q: int = 128, block_k: int = 128):
    """Adapter matching models.layers' `attention_fn(q, k, v, mask, dtype)`.

    The mask argument must be None (padding masks need the XLA path); causal
    structure is handled inside the kernel via block skipping, which is why
    this is faster than passing a causal mask to the einsum path.
    """

    def attention_fn(q, k, v, mask=None, dtype=jnp.float32):
        if mask is not None:
            raise ValueError(
                "flash attention path handles causal masking internally; "
                "explicit masks require the XLA attention path")
        return flash_attention(q, k, v, causal, None, block_q, block_k
                               ).astype(dtype)

    return attention_fn
