"""Ulysses attention — sequence parallelism by head-sharding (all-to-all).

The alternative SP mode SURVEY.md §2c lists alongside ring attention: instead
of rotating K/V blocks around the ring (O(n) ppermute hops), ONE all-to-all
re-shards the activations from sequence-sharded to head-sharded, every device
computes FULL-sequence attention for its head slice, and a second all-to-all
restores sequence sharding:

    (B, S/n, H, D)  --all_to_all-->  (B, S, H/n, D)
        full softmax(QK^T)V per local head group
    (B, S, H/n, D)  --all_to_all-->  (B, S/n, H, D)

Trade-off vs ring attention: 2 all-to-alls of the whole activation per layer
(bandwidth) but full-sequence attention locally (no per-step latency chain);
requires num_heads % n == 0, and memory is O(S) per device for the local
heads — use ring attention when S itself cannot fit. Both compose inside jit
via shard_map; `lax.all_to_all` has a transpose rule so gradients take the
mirrored path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, MODEL, SEQ
from ..parallel.collectives import shard_map

NEG_INF = float(np.finfo(np.float32).min)


def _local_attention(q, k, v, q0: int, causal: bool, sm_scale: float):
    """Plain attention over full sequence for a local head group. q may be a
    sub-block starting at global row q0 (used for causal masking)."""
    s_q, s_k = q.shape[1], k.shape[1]
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        rows = q0 + lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        cols = lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        logits = jnp.where((rows >= cols)[None, None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", weights,
                      v.astype(jnp.float32)).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,  # (B, S, H, D) — S sharded over `axis_name`
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    axis_name: str = SEQ,
) -> jnp.ndarray:
    """Head-sharded sequence-parallel attention over the mesh `seq` axis."""
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    n = mesh.shape[axis_name]
    if n == 1:
        return _local_attention(q, k, v, 0, causal, scale)
    # Heads are head-sharded over `model` first (tp_fsdp_rules) and then split
    # again over `seq` by the all-to-all, so the constraint is on the product.
    model_n = mesh.shape.get(MODEL, 1)
    if q.shape[2] % (n * model_n):
        raise ValueError(
            f"ulysses attention needs num_heads ({q.shape[2]}) divisible by "
            f"{axis_name!r} x 'model' axis sizes ({n} x {model_n}); use ring "
            "attention when heads are too few")

    # After the all-to-all every device holds the FULL sequence for its head
    # slice, so the local compute is exactly the single-device attention
    # problem — use the blockwise Pallas kernel (O(S) memory, MXU-tiled)
    # when the sequence divides its blocks; einsum otherwise (tiny S).
    s_full = q.shape[1]
    block = min(128, s_full)
    use_flash = (s_full % block == 0)

    def body(q_loc, k_loc, v_loc):  # (B, S/n, H, D) local shards
        # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1)
        to_heads = functools.partial(
            lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
            tiled=True)
        qh, kh, vh = to_heads(q_loc), to_heads(k_loc), to_heads(v_loc)
        if use_flash:
            from .flash_attention import flash_attention

            out = flash_attention(qh, kh, vh, causal, scale, block, block
                                  ).astype(qh.dtype)  # (B, S, H/n, D)
        else:
            out = _local_attention(qh, kh, vh, 0, causal, scale)
        # head-sharded -> seq-sharded: split seq (axis 1), gather heads (axis 2)
        return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    spec = P(BATCH_AXES, axis_name, MODEL, None)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def make_ulysses_attention_fn(mesh: Mesh, causal: bool, axis_name: str = SEQ):
    """Adapter matching models.layers' `attention_fn(q, k, v, mask, dtype)`."""

    def attention_fn(q, k, v, mask=None, dtype=jnp.float32):
        if mask is not None:
            raise ValueError(
                "ulysses attention handles causal masking internally; "
                "explicit masks require the XLA attention path")
        return ulysses_attention(q, k, v, mesh, causal=causal,
                                 axis_name=axis_name).astype(dtype)

    return attention_fn
