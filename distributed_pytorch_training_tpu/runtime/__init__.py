"""Process/device runtime — TPU-native equivalent of the reference's L0 layer."""

from .dist import (  # noqa: F401
    COMPILE_CACHE_ENV,
    DistContext,
    cleanup_distributed,
    compile_cache_dir,
    compile_cache_mode,
    enable_persistent_compile_cache,
    honor_platform_env,
    is_distributed,
    per_process_seed,
    set_seed,
    setup_distributed,
)
