"""Distributed process runtime.

TPU-native re-design of the reference's L0 layer
(/root/reference/train_ddp.py:49-73):

* ``is_distributed`` (ref :49-50) — reference reads ``WORLD_SIZE``; here a
  process is "distributed" when the JAX runtime reports >1 process (multi-host
  pod) OR when test overrides are set.
* ``setup_distributed`` (ref :53-68) — reference calls
  ``dist.init_process_group(backend="nccl", init_method="env://")`` and binds a
  CUDA device per process. On TPU there is ONE process per host (not per chip);
  ``jax.distributed.initialize()`` performs the rendezvous, and all local chips
  belong to this process. There is no per-device binding step.
* ``cleanup_distributed`` (ref :71-73) — ``jax.distributed.shutdown()``.

Environment contract
--------------------
The reference consumes ``WORLD_SIZE``/``RANK``/``LOCAL_RANK`` (the torchrun
contract, ref :61-63). The TPU pod runtime auto-discovers topology, so none of
those are required; for parity and for tests we honor optional overrides:

* ``DPT_COORDINATOR_ADDRESS`` / ``DPT_NUM_PROCESSES`` / ``DPT_PROCESS_ID`` —
  explicit multi-host rendezvous (forwarded to ``jax.distributed.initialize``).
* On GKE/Cloud TPU pods, ``jax.distributed.initialize()`` with no args works.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)

_INITIALIZED = False


def honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS=cpu`` work even where an early jax import (e.g. a
    sitecustomize that pins an accelerator platform list) has already captured
    the config default. Call before first device use; no-op once the backend
    is live. This is what lets one invocation run the same code on the real
    chip or an N-virtual-device CPU mesh (the test/dry-run backend)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # backend already initialized on cpu — fine
            pass


# The warm-restart compilation cache tri-state (ISSUE 11): elastic
# resizes, supervisor restarts and serving-fleet autoscaling all pay a
# full recompile of the (re)built step without it.
#   auto (default/unset) — enable on accelerator backends only (the
#        historical behavior: XLA:CPU reloads are unsafe, see below);
#   on   — enable regardless of backend (the operator vouches for the
#          environment; on CPU the known AOT-reload hazard applies);
#   off  — never enable (debugging stale-cache suspicion).
COMPILE_CACHE_ENV = "DPT_COMPILE_CACHE"
_COMPILE_CACHE_MODES = ("auto", "on", "off")


def compile_cache_mode(mode: Optional[str] = None) -> str:
    """Resolve the tri-state: explicit ``mode`` wins, else the
    ``DPT_COMPILE_CACHE`` env var, else "auto". Invalid values are a loud
    error — a typo'd "ON " silently meaning auto would be the
    silent-fallback class the analysis rules exist to kill."""
    resolved = mode if mode is not None else \
        os.environ.get(COMPILE_CACHE_ENV, "auto").strip().lower() or "auto"
    if resolved not in _COMPILE_CACHE_MODES:
        raise ValueError(
            f"{COMPILE_CACHE_ENV}={resolved!r} is not one of "
            f"{_COMPILE_CACHE_MODES}")
    return resolved


def compile_cache_dir(base_dir, topology: str, config_tag: str = ""):
    """The (topology, config)-keyed cache directory: entries compiled for
    one mesh shape / config never shadow another's (XLA's own cache key
    covers the computation, but keying the DIRECTORY keeps an elastic
    fleet's per-world entries enumerable and independently evictable).
    Key components are sanitized to filesystem-safe tokens."""
    import re as _re

    def clean(s: str) -> str:
        return _re.sub(r"[^A-Za-z0-9_.=-]+", "-", s).strip("-") or "default"

    from pathlib import Path

    name = clean(topology) + (f"__{clean(config_tag)}" if config_tag else "")
    return Path(base_dir) / name


def enable_persistent_compile_cache(cache_dir,
                                    mode: Optional[str] = None) -> bool:
    """Point XLA's persistent compile cache at ``cache_dir``. Returns True
    iff enabled. ``mode`` is the ``DPT_COMPILE_CACHE`` tri-state (see
    above; None reads the env var, default "auto").

    In "auto", gated on the RESOLVED backend, not env vars: an
    accelerator-init failure can silently fall back to XLA:CPU, whose
    persistent-cache reloads are unsafe here — AOT entries record pseudo
    machine features (+prefer-no-scatter/gather) that fail the feature
    match on reload, and the mismatch-loaded executables desynchronized an
    8-device collective rendezvous into a fatal abort (observed 2026-07-31
    on the virtual CPU mesh: ``cpu_aot_loader.cc`` mismatch warnings, then
    ``rendezvous.cc`` termination). Call only when backend init is
    acceptable (touching ``jax.default_backend()`` brings the backend up —
    on a wedged tunnel that can block, so callers probe first; see
    bench.py). The verdict is recorded as a ``compile_cache_enabled``
    telemetry counter so a restart-downtime A/B can attribute its win.
    """
    resolved = compile_cache_mode(mode)
    enabled = False
    backend = ""
    if resolved != "off":
        try:
            backend = jax.default_backend()
            if resolved == "on" or backend != "cpu":
                # dir LAST: the cache only activates once the dir is set,
                # so a failure in either update leaves it off and the
                # False is honest
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
                jax.config.update("jax_compilation_cache_dir",
                                  str(cache_dir))
                enabled = True
        except Exception:
            enabled = False
    try:
        from .. import telemetry

        telemetry.counter("compile_cache_enabled", int(enabled),
                          mode=resolved, backend=backend,
                          cache_dir=str(cache_dir))
    except Exception:  # telemetry must never break backend setup
        pass
    return enabled


@dataclasses.dataclass(frozen=True)
class DistContext:
    """What `setup_distributed` returns — the TPU analogue of the reference's
    ``(rank, world_size, local_rank)`` triple (train_ddp.py:68).

    ``process_index``/``process_count`` are host-level (one process per host);
    ``device_count`` is the number of addressable-from-anywhere chips in the
    global mesh, which is the number that plays the reference's ``world_size``
    role for per-device batch-size math (ref :27 "mini-batch size *per GPU*").
    """

    process_index: int
    process_count: int
    local_device_count: int
    device_count: int

    @property
    def is_main(self) -> bool:
        """True on the metrics/logging writer process (ref rank==0, :229, :350)."""
        return self.process_index == 0


def _pod_runtime_detected() -> bool:
    """True when env advertises a multi-host TPU pod whose rendezvous is
    auto-discoverable by a no-arg ``jax.distributed.initialize()``."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    num_slices = os.environ.get("MEGASCALE_NUM_SLICES")
    return bool(num_slices and int(num_slices) > 1)


def is_distributed() -> bool:
    """Multi-host? (Reference semantics: WORLD_SIZE>1, train_ddp.py:49-50.)

    Note the meaning shift: on GPU+DDP every *device* is a process, so
    single-host-4-GPU is "distributed". On TPU, 8 chips on one host are a
    plain single-process `Mesh` — collectives still happen, but no process
    group is needed. "Distributed" here therefore means multi-process
    (multi-host), which is the only case needing rendezvous.
    """
    if os.environ.get("DPT_NUM_PROCESSES"):
        return int(os.environ["DPT_NUM_PROCESSES"]) > 1
    return jax.process_count() > 1


def setup_distributed() -> DistContext:
    """Initialize the multi-host runtime if needed; return the process context.

    Maps train_ddp.py:53-68. Blocking rendezvous (like ``init_process_group``
    with ``env://``, ref :65) happens inside ``jax.distributed.initialize``.
    Safe to call when single-host: returns a trivial context, mirroring the
    reference's ``(0, 1, 0)`` fast path (ref :58-59).
    """
    global _INITIALIZED

    coord = os.environ.get("DPT_COORDINATOR_ADDRESS")
    nproc = os.environ.get("DPT_NUM_PROCESSES")
    if not _INITIALIZED:
        if coord and nproc and int(nproc) > 1:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(nproc),
                process_id=int(os.environ.get("DPT_PROCESS_ID", "0")),
            )
            _INITIALIZED = True
        elif _pod_runtime_detected():
            # Cloud TPU pod: topology is auto-discoverable; no-arg initialize
            # performs the rendezvous (the ref's env:// equivalent, :65).
            # Failures must NOT be swallowed — proceeding uninitialized would
            # silently train per-host un-synced models.
            jax.distributed.initialize()
            _INITIALIZED = True
    if _INITIALIZED:
        logger.info(
            "jax.distributed initialized: process %d/%d",
            jax.process_index(),
            jax.process_count(),
        )

    return DistContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        device_count=jax.device_count(),
    )


def cleanup_distributed() -> None:
    """Tear down the multi-host runtime (maps train_ddp.py:71-73)."""
    global _INITIALIZED
    if _INITIALIZED:
        jax.distributed.shutdown()
        _INITIALIZED = False


def per_process_seed(seed: int, process_index: Optional[int] = None) -> int:
    """The reference's per-rank seed rule: ``seed + rank``
    (/root/reference/train_ddp.py:76-78) — de-correlates host-side RNG streams
    across processes (e.g. CPU-side augmentation) on purpose.

    NOTE the split responsibility in the TPU design: *device-side* randomness
    (in-jit augmentation, dropout) uses ONE shared `PRNGKey(seed)` folded with
    the step counter — it operates on the global batch, so per-sample streams
    are already de-correlated and must be identical across hosts for SPMD to
    agree. *Host-side* randomness must use THIS rule, or every host would
    produce the same "random" numbers.
    """
    if process_index is None:
        process_index = jax.process_index()
    return seed + process_index


def set_seed(seed: int, process_index: Optional[int] = None) -> "np.random.Generator":
    """Seed host-side RNGs with ``seed + rank`` (maps set_seed, ref :76-78).

    Seeds Python's and NumPy's global generators (for any library code that
    reaches for them) and returns a dedicated ``np.random.Generator`` for
    framework host-side use. Device-side keys are NOT derived here — pass
    ``jax.random.PRNGKey(seed)`` (unfolded) to the Trainer so every host
    traces the same program with the same key.
    """
    import random

    import numpy as np

    s = per_process_seed(seed, process_index)
    random.seed(s)
    np.random.seed(s % (2 ** 32))
    return np.random.default_rng(s)
