"""Preemption-aware training — the failure-recovery story.

The reference has none (SURVEY.md §5 "Failure detection/elastic recovery:
Absent — a crashed rank hangs the NCCL job"). TPU pods are preemptible, so
the minimum useful story is: catch the preemption signal (SIGTERM), finish
the in-flight step, write a checkpoint, exit 0; the relaunched job resumes
from it (`--resume`). That turns a preemption from "lose the run" into "lose
at most one epoch slice".

No elastic re-sizing: XLA SPMD programs are compiled for a fixed mesh, so the
honest TPU design is checkpoint-restart at the same (or re-specified)
topology rather than DDP-style dynamic world resizing.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional

from ..resilience.heartbeat import hard_exit
from ..utils.logging import log_main

# Hard deadline for the graceful path. "Stop at the next epoch boundary"
# assumes the process is making progress; a SIGTERM that lands mid-compile
# (minutes) or while the backend is wedged (forever) must still kill the
# process — a zombie that swallowed SIGTERM keeps its device claim and
# blocks every subsequent job from acquiring the chip (observed live on the
# tunneled v5e: a killed-but-alive trainer wedged the device pool).
_GRACE_ENV = "DPT_PREEMPT_GRACE_SECONDS"
_GRACE_DEFAULT = 600.0


class PreemptionGuard:
    """Installs SIGTERM/SIGINT handlers that request a graceful stop.

    Usage::

        guard = PreemptionGuard.install()
        for epoch in range(...):
            train_epoch(...)
            if guard.should_stop:
                ckpt.save(epoch + 1, state, wait=True)
                break
        guard.disarm()  # graceful path completed; cancel the deadline

    Handlers chain to any previously-installed handler; `should_stop` is a
    plain flag so the hot loop pays nothing for it. Signals received twice
    fall through to the previous handler (second Ctrl-C still kills). The
    first signal also arms a hard deadline (``DPT_PREEMPT_GRACE_SECONDS``,
    default 600): if the process hasn't exited — or called ``disarm()`` —
    by then, it force-exits with status 143 rather than linger as a
    device-holding zombie.
    """

    _installed: Optional["PreemptionGuard"] = None

    def __init__(self):
        self._stop = threading.Event()
        self._prev = {}
        self._deadline: Optional[threading.Timer] = None
        # test seam: replaced to observe the force-exit without dying.
        # hard_exit is resilience/heartbeat.py's sanctioned abrupt exit
        # (the no-bare-os-exit analysis rule bans raw os._exit here): a
        # zombie that swallowed SIGTERM keeps its device claim, so the
        # deadline expiry is one of the two legitimate abrupt-exit cases.
        self._force_exit = lambda: hard_exit(143)

    @property
    def should_stop(self) -> bool:
        return self._stop.is_set()

    def request_stop(self) -> None:
        self._stop.set()

    def _handler(self, signum, frame):
        if self._stop.is_set():
            # second signal: defer to the previous behavior (hard exit)
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signum, prev or signal.SIG_DFL)
                signal.raise_signal(signum)
            return
        # never raise inside a signal handler: a malformed env value must
        # not turn SIGTERM into a crash-without-checkpoint
        try:
            grace = float(os.environ.get(_GRACE_ENV, _GRACE_DEFAULT))
        except (TypeError, ValueError):
            grace = _GRACE_DEFAULT
        log_main(f"Received signal {signum}: will checkpoint and stop at the "
                 f"next epoch boundary (hard exit in {grace:.0f}s if the "
                 "graceful path stalls)")
        self._stop.set()
        self._arm_deadline(grace)

    def _arm_deadline(self, grace: float) -> None:
        def expire():
            log_main(f"Graceful stop did not complete within {grace:.0f}s "
                     "of the signal; force-exiting (143)")
            self._force_exit()

        self._deadline = threading.Timer(grace, expire)
        self._deadline.daemon = True
        self._deadline.start()

    def disarm(self) -> None:
        """Cancel the hard-exit deadline — the graceful path completed (or
        the caller, e.g. a notebook, keeps the process for another run)."""
        if self._deadline is not None:
            self._deadline.cancel()
            self._deadline = None

    def reset(self) -> None:
        """Disarm a previously-set stop flag (a new run starts fresh)."""
        self._stop.clear()
        self.disarm()

    @classmethod
    def install(cls, reset: bool = True) -> "PreemptionGuard":
        """Idempotent: repeated calls return the same guard. By default the
        stale stop flag from a previous run in this process is cleared —
        otherwise a sweep/notebook calling main() twice would silently stop
        run 2 after one epoch because run 1 was preempted."""
        if cls._installed is not None:
            if reset:
                cls._installed.reset()
            return cls._installed
        guard = cls()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                guard._prev[sig] = signal.signal(sig, guard._handler)
            except (ValueError, OSError):
                # non-main thread or restricted env: degrade to manual
                # request_stop(); training still works
                pass
        cls._installed = guard
        return guard
