"""Checkpoint / resume — absent from the reference (no torch.save/load
anywhere; SURVEY.md §5 "Checkpoint/resume: Absent") but required for usable
multi-host training on preemptible TPU pods.

Orbax-backed: sharded async-capable writes, multi-host-safe (every process
participates; no rank-0 funnel). Only the array pytrees are persisted
(step/params/batch_stats/opt_state/grad_sync); `apply_fn`/`tx` are code,
reconstructed by the caller — restoring requires a template TrainState with
matching structure, which `train.py` always has before resume.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple

import numpy as np
import orbax.checkpoint as ocp

from .train_state import TrainState


def _arrays(state: TrainState, epoch: int = 0, step_in_epoch: int = 0) -> dict:
    arrays = {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        # step-granular resume coordinates: the sampler is deterministic in
        # (seed, epoch), so (epoch, step_in_epoch) fully locates the
        # trajectory — a preemption at minute 50 no longer replays the
        # epoch. 0-d ndarrays, NOT numpy scalars: orbax's restore-template
        # validation rejects np.int32(0) (not in its supported leaf types).
        "epoch": np.asarray(epoch, np.int32),
        "step_in_epoch": np.asarray(step_in_epoch, np.int32),
    }
    # int8-wire error-feedback residuals (parallel/grad_sync.py): the
    # carried quantization remainder IS trajectory state — dropping it at
    # resume re-introduces the bias EF exists to cancel. Included only
    # when non-empty so every other mode's checkpoints keep the legacy
    # structure (resumable across this feature's introduction, both ways).
    import jax

    if jax.tree_util.tree_leaves(state.grad_sync):
        arrays["grad_sync"] = state.grad_sync
    return arrays


class CheckpointManager:
    """Step-granular save/restore-latest (the resume story the reference's
    append-only CSV hints at but never implements, ref :349-354).

    `label` orders checkpoints (use epoch * steps_per_epoch + step so
    mid-epoch preemption saves sort between epoch boundaries); the restored
    (epoch, step_in_epoch) pair tells the caller exactly where to resume."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            Path(directory).resolve(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, label: int, state: TrainState, wait: bool = False,
             epoch: Optional[int] = None, step_in_epoch: int = 0) -> None:
        """`epoch` defaults to `label` (the legacy epoch-granular callers
        label saves by completed-epoch count)."""
        self._mgr.save(label, args=ocp.args.StandardSave(
            _arrays(state, label if epoch is None else epoch, step_in_epoch)))
        if wait:
            self._mgr.wait_until_finished()

    def restore_latest(
        self, template: TrainState,
    ) -> Optional[Tuple[TrainState, int, int]]:
        """Returns (state, epoch, step_in_epoch) or None if no checkpoint
        exists. `template` supplies structure/sharding for every restored
        array. step_in_epoch > 0 means the save was a mid-epoch preemption:
        resume epoch `epoch` AT that step (the loaders' start_step)."""
        label = self._mgr.latest_step()
        if label is None:
            return None
        want = _arrays(template)
        if "grad_sync" in want:
            # An int8-wire template resuming a checkpoint written WITHOUT
            # EF residuals (pre-feature, or the flag was just turned on):
            # orbax rejects a template key the checkpoint lacks outright,
            # so drop it and let the .get below keep the template's
            # zero-initialized residuals — error feedback restarts its
            # telescope from zero, which is exactly a fresh-start step.
            meta = self.latest_metadata()
            if meta is not None and "grad_sync" not in meta:
                want.pop("grad_sync")
        restored = self._mgr.restore(
            label, args=ocp.args.StandardRestore(want))
        state = template.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
            # .get: checkpoints written before grad_sync existed restore
            # into non-EF templates (grad_sync={}) unchanged
            grad_sync=restored.get("grad_sync", template.grad_sync),
        )
        return state, int(restored["epoch"]), int(restored["step_in_epoch"])

    def latest_metadata(self) -> Optional[dict]:
        """Structure/shape metadata of the latest checkpoint WITHOUT reading
        array data (orbax item metadata). Lets callers diagnose a template
        mismatch precisely — e.g. a TP-vocab-padded (50304, d) embedding
        saved under a different --mesh than the resume run's."""
        label = self._mgr.latest_step()
        if label is None:
            return None
        try:
            return self._mgr.item_metadata(label)
        except Exception:
            return None

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
