"""Checkpoint / resume — absent from the reference (no torch.save/load
anywhere; SURVEY.md §5 "Checkpoint/resume: Absent") but required for usable
multi-host training on preemptible TPU pods.

Orbax-backed: sharded writes, multi-host-safe (every process participates;
no rank-0 funnel). Only the array pytrees are persisted
(step/params/batch_stats/opt_state/grad_sync); `apply_fn`/`tx` are code,
reconstructed by the caller — restoring requires a template TrainState with
matching structure, which `train.py` always has before resume.

Integrity (resilience/): every save writes a per-checkpoint MANIFEST
(step + a tree digest over the finalized files: path, size, sha256) into
``<dir>/.manifests/<label>.json``, and ``restore_latest`` verifies the
manifest before trusting a checkpoint — a torn/truncated checkpoint (disk
truncation, a partial copy, an injected ``torn_ckpt`` chaos fault) is
SKIPPED with a loud log and the previous valid one restores instead of the
run crashing on it. Orbax's own atomic-rename commit already excludes
interrupted writes from ``all_steps``; the manifest covers the post-commit
corruption class orbax cannot see. Legacy checkpoints (written before
manifests existed) have no manifest and restore unverified, exactly as
before.

Async saves (snapshot-then-write): ``save`` used to finalize synchronously
so the manifest could hash final files — the measured step-time stall this
design kills. Now only the device→host SNAPSHOT happens on the caller's
thread (it must: the train step donates the state buffers, so deferring the
copy would read freed memory), and the orbax write + chunked-sha256 manifest
run on ONE background writer while training continues. Barriers:

* the next ``save`` joins the previous write first (at most one write in
  flight — also where a failed async write surfaces, as the raised error);
* ``wait()`` / ``close()`` at shutdown, and every restore/metadata read,
  join the writer before touching the directory.

The async window does NOT widen the torn-checkpoint window silently: a
PENDING marker (``.manifests/<label>.pending``) is written before the
background write starts and removed only after the manifest finalizes, so a
crash between the orbax commit and the manifest leaves a checkpoint that
``verify`` reports as torn ("never finalized") instead of one that
masquerades as a trusted legacy checkpoint. What async changes is *when*
bytes hit disk, never *what*: the written files and manifest digests are
those of a synchronous save of the same state (PARITY.md).

Blocked-time accounting (the bench instrument): ``save_blocked_ms`` sums
every millisecond the calling thread spent inside ``save``/``wait`` —
under async saves it collapses to ~``snapshot_ms`` (the device→host copy),
which is the whole point.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Callable, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..utils.logging import log_main
from .. import telemetry
from .train_state import TrainState

_MANIFEST_DIRNAME = ".manifests"
_MANIFEST_FORMAT = 1


class CheckpointWorldSizeMismatch(RuntimeError):
    """A checkpoint written at one DP world size was restored against a
    template built for another — the flat-padded layouts (zero1 moments,
    fsdp params+moments, EF residuals) change shape with the shard count,
    so orbax's opaque tree-mismatch dump is really THIS error. Raised with
    both sizes in the message and the chosen candidate on the instance
    (``label`` / ``world_size`` — train.py's elastic-resume fallback
    restores exactly that label raw instead of re-scanning the
    directory); resolve by restoring through
    ``restore_latest(template_factory=...)`` (build the template at the
    checkpoint's recorded world size and reshard — resilience/elastic.py)
    or by resuming at the original world size."""

    label: Optional[int] = None
    world_size: Optional[int] = None


def _file_sha256(path: Path) -> str:
    # chunked: checkpoint data files are model-sized, and a whole-file
    # read_bytes() would spike host RAM by the checkpoint size on every
    # save/verify — on a host already holding params + optimizer state
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 22), b""):
            h.update(block)
    return h.hexdigest()


def _arrays(state: TrainState, epoch: int = 0, step_in_epoch: int = 0) -> dict:
    arrays = {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        # step-granular resume coordinates: the sampler is deterministic in
        # (seed, epoch), so (epoch, step_in_epoch) fully locates the
        # trajectory — a preemption at minute 50 no longer replays the
        # epoch. 0-d ndarrays, NOT numpy scalars: orbax's restore-template
        # validation rejects np.int32(0) (not in its supported leaf types).
        "epoch": np.asarray(epoch, np.int32),
        "step_in_epoch": np.asarray(step_in_epoch, np.int32),
    }
    # int8-wire error-feedback residuals (parallel/grad_sync.py): the
    # carried quantization remainder IS trajectory state — dropping it at
    # resume re-introduces the bias EF exists to cancel. Included only
    # when non-empty so every other mode's checkpoints keep the legacy
    # structure (resumable across this feature's introduction, both ways).
    import jax

    if jax.tree_util.tree_leaves(state.grad_sync):
        arrays["grad_sync"] = state.grad_sync
    return arrays


class CheckpointManager:
    """Step-granular save/restore-latest (the resume story the reference's
    append-only CSV hints at but never implements, ref :349-354).

    `label` orders checkpoints (use epoch * steps_per_epoch + step so
    mid-epoch preemption saves sort between epoch boundaries); the restored
    (epoch, step_in_epoch) pair tells the caller exactly where to resume.

    Layout-agnostic: restore lands every array in the TEMPLATE's sharding,
    so the flat-padded-sharded layouts (zero1's moments; fsdp_explicit's
    params + moments + per-group EF residuals) round-trip exactly as the
    replicated layout does — provided the template was built under the
    same mesh and mode flags (train.py's resume hint names them).

    ``async_save=True`` (the default) makes ``save`` snapshot-then-write:
    device→host copy on the caller's thread, orbax write + manifest on a
    background writer (``save(..., wait=True)`` forces one save back to
    synchronous — the preemption-drain saves use it: the process is about
    to exit, overlap buys nothing). A failed background write re-raises
    from the NEXT ``save``/``wait`` call — inside the supervisor's
    recovery scope, so "on a step/save failure, restore the latest valid
    checkpoint" covers async saves too.

    ``post_save_hook(label, step_dir)`` fires after a save (and its
    manifest) finalized — the chaos harness's torn-checkpoint injection
    point (resilience/faults.py). ``pre_finalize_hook(label)`` fires
    between the orbax commit and the manifest write — the
    ``crash_during_save`` injection point (a raise there aborts the save
    exactly inside the async window the pending marker guards).
    ``last_skipped`` lists the labels the most recent ``restore_latest``
    rejected on integrity (the supervisor's recovery report reads it)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 post_save_hook: Optional[Callable[[int, Path], None]]
                 = None,
                 async_save: bool = True,
                 pre_finalize_hook: Optional[Callable[[int], None]] = None):
        self._dir = Path(directory).resolve()
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )
        self._post_save_hook = post_save_hook
        self._pre_finalize_hook = pre_finalize_hook
        self._async = bool(async_save)
        self.last_skipped: List[int] = []
        # label the most recent restore_latest actually restored (None
        # before any restore) — serving reads it to provenance the weights
        # it serves (which label, which manifest digest)
        self.last_restored: Optional[int] = None
        # labels already proven torn (label -> problem): a torn checkpoint
        # stays torn, so later restores must not re-hash its files to
        # rediscover it. Cleared per label on re-save.
        self._known_bad: dict = {}
        # the one in-flight background write (at most one: the next save
        # joins it first, so orbax manager state is never touched from two
        # threads at once) and its failure, surfaced at the next barrier.
        # Lock-free by protocol, not by accident: the writer thread writes
        # _writer_label/_writer_error, the caller reads them only AFTER
        # _join_writer's t.join() — the join IS the happens-before edge,
        # and the at-most-one-writer invariant means there is never a
        # second thread to race
        self._writer: Optional[threading.Thread] = None
        self._writer_label: Optional[int] = None
        self._writer_error: Optional[BaseException] = None
        # blocked-time accounting (bench: the save_blocked_ms instrument)
        self.save_blocked_ms = 0.0   # caller-thread ms inside save()/wait()
        self.snapshot_ms = 0.0       # of which: the device→host snapshot
        self.saves_started = 0

    # -- manifest plumbing -------------------------------------------------

    def _step_dir(self, label: int) -> Path:
        return self._dir / str(label)

    def _manifest_path(self, label: int) -> Path:
        return self._dir / _MANIFEST_DIRNAME / f"{label}.json"

    def _pending_path(self, label: int) -> Path:
        return self._dir / _MANIFEST_DIRNAME / f"{label}.pending"

    @staticmethod
    def _shape_summary(snapshot: dict) -> dict:
        """Sorted per-subtree shape multisets of the state being saved —
        recorded in the manifest so a cross-world restore can detect a
        layout mismatch BEFORE orbax touches the arrays (orbax's own item
        metadata is not reliably readable across versions, and its
        StandardRestore silently TRUNCATES a flat-padded leaf into a
        smaller template instead of failing)."""
        out = {}
        for key in ("params", "opt_state", "grad_sync"):
            if key in snapshot:
                out[key] = sorted(
                    list(np.shape(leaf))
                    for leaf in jax.tree_util.tree_leaves(snapshot[key]))
        return out

    def _write_manifest(self, label: int, step: int,
                        world_size: Optional[int] = None,
                        shapes: Optional[dict] = None) -> None:
        step_dir = self._step_dir(label)
        files = {}
        tree = hashlib.sha256()
        for p in sorted(step_dir.rglob("*")):
            if not p.is_file():
                continue
            rel = p.relative_to(step_dir).as_posix()
            digest = _file_sha256(p)
            size = p.stat().st_size
            files[rel] = {"size": size, "sha256": digest}
            tree.update(f"{rel}\0{size}\0{digest}\0".encode())
        manifest = {"format": _MANIFEST_FORMAT, "label": label,
                    "step": int(step), "n_files": len(files),
                    "tree_digest": tree.hexdigest(), "files": files}
        if world_size is not None:
            # the DP world size (batch shards) the state was laid out for:
            # the per-label probe elastic restores / template factories use
            # to build a matching template (legacy manifests lack it)
            manifest["world_size"] = int(world_size)
        if shapes:
            manifest["shapes"] = shapes
        path = self._manifest_path(label)
        path.parent.mkdir(parents=True, exist_ok=True)
        # atomic: a manifest torn by a crash mid-write must read as invalid
        # (skip), never as a half-truth that validates a half-checkpoint
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(manifest, sort_keys=True))
        os.replace(tmp, path)
        # prune manifests (and pending markers) of steps orbax's
        # max_to_keep already deleted
        live = {str(s) for s in self._mgr.all_steps()}
        for stale in list(path.parent.glob("*.json")) \
                + list(path.parent.glob("*.pending")):
            if stale.stem not in live:
                stale.unlink(missing_ok=True)

    def verify(self, label: int) -> Optional[str]:
        """None = intact (or legacy: no manifest to check — restores
        unverified, exactly as before manifests existed); otherwise a
        human-readable description of the corruption. An orbax-committed
        step whose PENDING marker survives without a manifest is an async
        save that died before finalizing — torn, never legacy. Failures
        are cached per label (torn stays torn) so repeated restores under
        the restart supervisor don't re-hash the same dead checkpoint."""
        if label in self._known_bad:
            return self._known_bad[label]
        problem = self._verify_uncached(label)
        if problem is not None:
            self._known_bad[label] = problem
        return problem

    def _verify_uncached(self, label: int) -> Optional[str]:
        path = self._manifest_path(label)
        if not path.exists():
            if self._pending_path(label).exists():
                # the async writer started this save and never finalized it
                # (crash between the orbax commit and the manifest write) —
                # the files may even be complete, but nothing vouches for
                # them; treating it as legacy would silently WIDEN the
                # torn-checkpoint window by exactly the async interval
                return ("async save never finalized (pending marker "
                        "present, no manifest — the writer died between "
                        "the orbax commit and the manifest)")
            return None  # legacy checkpoint
        try:
            manifest = json.loads(path.read_text())
            files = manifest["files"]
        except Exception as e:
            return f"unreadable manifest ({e})"
        step_dir = self._step_dir(label)
        for rel, info in files.items():
            p = step_dir / rel
            if not p.is_file():
                return f"file {rel} missing"
            size = p.stat().st_size
            if size != info["size"]:
                return (f"file {rel} truncated ({size} bytes, manifest "
                        f"says {info['size']})")
            if _file_sha256(p) != info["sha256"]:
                return f"file {rel} corrupt (digest mismatch)"
        return None

    # -- the background writer ---------------------------------------------

    def _join_writer(self, reraise: bool = True) -> None:
        """Barrier on the in-flight write. ``reraise=True`` (save/wait)
        surfaces a failed write as the raised error — inside the
        supervisor's recovery scope; ``reraise=False`` (restore/metadata/
        close paths) logs it instead: a failed save is a torn/absent
        checkpoint, which the integrity verification already handles."""
        t = self._writer
        if t is not None:
            t.join()
            self._writer = None
        err, label = self._writer_error, self._writer_label
        if err is None:
            return
        self._writer_error = None
        self._writer_label = None
        if reraise:
            raise err
        log_main(f"CHECKPOINT: async save of checkpoint {label} failed "
                 f"({type(err).__name__}: {err}) — it will be skipped by "
                 "integrity verification")

    def _write_job(self, label: int, snapshot: dict, step_value: int,
                   world_size: Optional[int] = None) -> None:
        """Everything after the snapshot: orbax write + finalize, the
        manifest, the pending-marker removal, and the hooks. Runs on the
        writer thread (async) or inline (sync / ``wait=True``)."""
        self._mgr.save(label, args=ocp.args.StandardSave(snapshot))
        self._mgr.wait_until_finished()
        if self._pre_finalize_hook is not None:
            # the crash_during_save window: orbax has committed, the
            # manifest does not exist yet — a raise here must leave a
            # checkpoint restore_latest skips loudly (the pending marker)
            self._pre_finalize_hook(label)
        # manifest writes are process-0-only: every process hashing and
        # racing the same .manifests/<label>.json.tmp on shared storage
        # could publish interleaved JSON — an "unreadable manifest" that
        # makes a GOOD checkpoint skip forever. Verification stays on
        # every process (read-only; all reach the same verdict).
        if jax.process_index() == 0:
            self._write_manifest(label, step=step_value,
                                 world_size=world_size,
                                 shapes=self._shape_summary(snapshot))
            self._pending_path(label).unlink(missing_ok=True)
        if self._post_save_hook is not None:
            self._post_save_hook(label, self._step_dir(label))

    def _writer_main(self, label: int, snapshot: dict, step_value: int,
                     world_size: Optional[int] = None) -> None:
        try:
            self._write_job(label, snapshot, step_value,
                            world_size=world_size)
        except BaseException as e:  # surfaced at the next barrier
            self._writer_error = e
            self._writer_label = label

    # -- save / restore ----------------------------------------------------

    def save(self, label: int, state: TrainState, wait: bool = False,
             epoch: Optional[int] = None, step_in_epoch: int = 0,
             world_size: Optional[int] = None) -> None:
        """`epoch` defaults to `label` (the legacy epoch-granular callers
        label saves by completed-epoch count). Snapshot-then-write: the
        device→host copy happens HERE (the train step donates these
        buffers — deferring the read would race the donation), then the
        orbax write + manifest run on the background writer unless
        ``wait=True`` or the manager was built ``async_save=False``.
        Joins (and surfaces the failure of) any previous in-flight write
        first. Re-saving an existing label (the supervisor replaying over
        a torn save) replaces the whole step. ``world_size`` (the DP batch
        shard count the state is laid out for) is recorded in the manifest
        so cross-world restores — elastic resizes — can probe it per label
        (`checkpoint_world_size`) and build a matching template."""
        t0 = time.perf_counter()
        self._join_writer()
        if label in self._mgr.all_steps():
            # never mix a fresh save into a stale (possibly torn) step dir
            self._mgr.delete(label)
            self._manifest_path(label).unlink(missing_ok=True)
        self._known_bad.pop(label, None)
        t_snap = time.perf_counter()
        # the only device work of a save: one host copy of the arrays.
        # numpy leaves land in orbax exactly like device arrays do, so the
        # written bytes (and manifest digests) match a synchronous save.
        snapshot = jax.device_get(_arrays(
            state, label if epoch is None else epoch, step_in_epoch))
        step_value = int(snapshot["step"])
        self.snapshot_ms += (time.perf_counter() - t_snap) * 1e3
        self.saves_started += 1
        if jax.process_index() == 0:
            pending = self._pending_path(label)
            pending.parent.mkdir(parents=True, exist_ok=True)
            pending.write_text(json.dumps(
                {"label": label, "step": step_value}))
        if self._async and not wait:
            t = threading.Thread(
                target=self._writer_main,
                args=(label, snapshot, step_value, world_size),
                name=f"ckpt-writer-{label}", daemon=True)
            self._writer = t
            t.start()
        else:
            self._write_job(label, snapshot, step_value,
                            world_size=world_size)
        blocked_s = time.perf_counter() - t0
        self.save_blocked_ms += blocked_s * 1e3
        # the save_blocked telemetry span: exactly the caller-thread stall
        # this save cost the train loop (under async ≈ the snapshot copy)
        telemetry.span_event("save_blocked", blocked_s, label=label,
                             phase="save",
                             async_save=bool(self._async and not wait))

    def _template_shapes_differ(self, label: int,
                                template: TrainState) -> bool:
        """Whether the checkpoint's saved array shapes differ from the
        template's — compared as per-subtree shape MULTISETS, so the
        replicated layout (whose shapes are world-size independent)
        restores across worlds unharassed while a flat-padded layout's
        changed padding is caught. Shapes come from OUR manifest (the
        `shapes` field `_write_manifest` records) — orbax's item metadata
        is not reliably readable across versions, and this check is what
        stands between a cross-world restore and StandardRestore's silent
        truncation. False when no shape record exists (legacy manifest:
        the restore then proceeds on its own merits)."""
        manifest = self.manifest(label)
        saved = (manifest or {}).get("shapes")
        if not saved:
            return False

        def shapes(tree) -> List[list]:
            return sorted(
                list(np.shape(leaf))
                for leaf in jax.tree_util.tree_leaves(tree))

        try:
            # grad_sync is compared too: the replicated+int8 layout's
            # params/opt_state are world-independent — ONLY its (n, R)
            # EF residual rows change with the world, and orbax would
            # truncate them just as silently. (A cross-world restore that
            # ALSO toggles compression trips this check as well — that
            # combination has no supported restore path, and the named
            # error beats orbax's structure dump.)
            for key, want in saved.items():
                if shapes(getattr(template, key)) != sorted(
                        list(s) for s in want):
                    return True
        except Exception:
            return False
        return False

    def checkpoint_world_size(self, label: Optional[int]) -> Optional[int]:
        """The DP world size (batch shards) checkpoint ``label`` was saved
        under, from its manifest — None for legacy manifests (written
        before the field existed), manifest-less checkpoints, or a None
        label. The per-label probe elastic restores key their template
        (and reshard decision) on."""
        if label is None:
            return None
        manifest = self.manifest(label)
        if manifest is None:
            return None
        w = manifest.get("world_size")
        return int(w) if w is not None else None

    def _verified_labels(self, among=None):
        """Candidate labels, newest first, that PASS integrity
        verification — the shared front half of every restore: joins the
        writer, resets + records ``last_skipped``, logs each torn skip
        loudly. A generator so callers stop at the first hit."""
        self._join_writer(reraise=False)
        self.last_skipped = []
        labels = sorted((label for label in self._mgr.all_steps()
                         if among is None or label in among), reverse=True)
        for label in labels:
            problem = self.verify(label)
            if problem is not None:
                log_main(f"CHECKPOINT INTEGRITY: checkpoint {label} is "
                         f"torn ({problem}) — skipping it and trying the "
                         "previous one")
                telemetry.emit("event", "torn_checkpoint_skipped",
                               label=label, problem=problem)
                self.last_skipped.append(label)
                continue
            yield label

    def restore_latest_raw(
        self, among=None,
    ) -> Optional[Tuple[dict, int, Optional[int], int, int]]:
        """Newest VALID checkpoint as HOST numpy arrays in their SAVED
        shapes — no template. Returns ``(arrays, label, world_size,
        epoch, step_in_epoch)`` or None; torn checkpoints are skipped
        exactly as in :meth:`restore_latest`.

        The cross-PROCESS elastic restore (ISSUE 12): a fleet relaunch at
        a different world size cannot build the old world's device
        templates (that mesh no longer exists in this process), so the
        checkpoint's own saved shapes stand in for the template and the
        caller reshards the host arrays into its current layout
        (``resilience.elastic.reshard_raw_state``). Orbax reconstructs
        the saved pytree as plain nested containers whose flattened leaf
        order mirrors the saved TrainState's (both sides flatten the same
        structure), so positional re-unflattening onto a matching
        template treedef is exact — the reshard's per-leaf shape checks
        catch a structural drift loudly."""
        for label in self._verified_labels(among):
            with telemetry.span("restore", label=label, raw=True):
                restored = self._mgr.restore(
                    label, args=ocp.args.StandardRestore())
            self.last_restored = label
            return (restored, label, self.checkpoint_world_size(label),
                    int(restored["epoch"]), int(restored["step_in_epoch"]))
        if self.last_skipped:
            log_main(f"CHECKPOINT INTEGRITY: every checkpoint "
                     f"({self.last_skipped}) failed verification — "
                     "nothing to restore")
        return None

    def restore_latest(
        self, template: Optional[TrainState] = None, among=None,
        template_factory=None, template_world_size: Optional[int] = None,
    ) -> Optional[Tuple[TrainState, int, int]]:
        """Returns (state, epoch, step_in_epoch) from the newest checkpoint
        that PASSES integrity verification, or None if none exists (torn
        ones are skipped with a loud log — recorded in ``last_skipped``).
        `template` supplies structure/sharding for every restored array.
        step_in_epoch > 0 means the save was a mid-epoch preemption:
        resume epoch `epoch` AT that step (the loaders' start_step).
        ``among`` (a collection of labels) restricts the candidates — the
        restart supervisor of a NON-resume run passes the labels it wrote
        itself, so a stale checkpoint a previous run left in the same
        directory can never leak into a fresh trajectory. Any in-flight
        async write is joined first (a restore must never race the
        writer); its failure, if any, is logged, not raised — a failed
        save is exactly a torn checkpoint, handled below.

        World sizes: ``template_factory(world)`` (instead of ``template``)
        builds the template PER CANDIDATE from the manifest's recorded
        world size (None for legacy manifests) — the elastic-restore path:
        a checkpoint written at 8 replicas restores into an 8-world
        template even when the run now holds 4 (the caller reshards,
        resilience/elastic.py). With a plain ``template``,
        ``template_world_size`` turns orbax's opaque structure-mismatch
        dump into :class:`CheckpointWorldSizeMismatch` naming both sizes
        whenever the manifest proves the worlds really differ."""
        if (template is None) == (template_factory is None):
            raise ValueError("restore_latest needs exactly one of "
                             "`template` or `template_factory`")
        for label in self._verified_labels(among):
            saved_world = self.checkpoint_world_size(label)
            if template_factory is not None:
                tmpl = template_factory(saved_world)
            else:
                tmpl = template
                if (saved_world is not None
                        and template_world_size is not None
                        and saved_world != template_world_size
                        and self._template_shapes_differ(label, tmpl)):
                    # MUST be checked before the restore: orbax does not
                    # reliably reject a shape mismatch — StandardRestore
                    # can silently truncate a flat-padded leaf into the
                    # smaller-world template, which corrupts the state
                    # instead of failing
                    err = CheckpointWorldSizeMismatch(
                        f"checkpoint {label} was written at world size "
                        f"{saved_world} (DP batch shards), but the "
                        "restore template was built for world size "
                        f"{template_world_size} — flat-padded layouts "
                        "(zero1 moments, fsdp params, EF residuals) "
                        "change shape with the DP degree. Restore with a "
                        f"template built at world size {saved_world} "
                        "(restore_latest(template_factory=...)) and "
                        "reshard via resilience.elastic, or resume at "
                        "the original world size")
                    # the already-verified, already-chosen candidate: an
                    # elastic-resume fallback restores exactly this label
                    # (among={err.label}) instead of re-scanning — and
                    # re-hashing — every candidate from scratch
                    err.label = label
                    err.world_size = saved_world
                    raise err
            return self._restore(label, tmpl)
        if self.last_skipped:
            log_main(f"CHECKPOINT INTEGRITY: every checkpoint "
                     f"({self.last_skipped}) failed verification — "
                     "nothing to restore")
        return None

    def manifest(self, label: int) -> Optional[dict]:
        """The integrity manifest of one checkpoint (``tree_digest``,
        per-file sizes/sha256), or None for a legacy (pre-manifest)
        checkpoint / unreadable manifest. The serving engine embeds the
        ``tree_digest`` in its provenance record: a served model names the
        exact bytes it serves."""
        self._join_writer(reraise=False)
        path = self._manifest_path(label)
        if not path.exists():
            return None
        try:
            return json.loads(path.read_text())
        except Exception:
            return None

    def _restore(self, label: int,
                 template: TrainState) -> Tuple[TrainState, int, int]:
        with telemetry.span("restore", label=label):
            out = self._restore_inner(label, template)
        # only a restore that SUCCEEDED may claim the label (a template
        # mismatch raises above — provenance must not name it)
        self.last_restored = label
        return out

    def _restore_inner(self, label: int,
                       template: TrainState) -> Tuple[TrainState, int, int]:
        want = _arrays(template)
        if "grad_sync" in want:
            # An int8-wire template resuming a checkpoint written WITHOUT
            # EF residuals (pre-feature, or the flag was just turned on):
            # orbax rejects a template key the checkpoint lacks outright,
            # so drop it and let the .get below keep the template's
            # zero-initialized residuals — error feedback restarts its
            # telescope from zero, which is exactly a fresh-start step.
            meta = self.metadata(label)
            if meta is not None and "grad_sync" not in meta:
                want.pop("grad_sync")
        restored = self._mgr.restore(
            label, args=ocp.args.StandardRestore(want))
        state = template.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
            # .get: checkpoints written before grad_sync existed restore
            # into non-EF templates (grad_sync={}) unchanged
            grad_sync=restored.get("grad_sync", template.grad_sync),
        )
        return state, int(restored["epoch"]), int(restored["step_in_epoch"])

    def metadata(self, label: Optional[int] = None) -> Optional[dict]:
        """Structure/shape metadata of one checkpoint (default: latest)
        WITHOUT reading array data (orbax item metadata). Lets callers
        diagnose a template mismatch precisely — e.g. a TP-vocab-padded
        (50304, d) embedding saved under a different --mesh than the
        resume run's."""
        self._join_writer(reraise=False)
        if label is None:
            label = self._mgr.latest_step()
        if label is None:
            return None
        try:
            return self._mgr.item_metadata(label)
        except Exception:
            return None

    def latest_metadata(self) -> Optional[dict]:
        return self.metadata()

    def wait(self) -> None:
        """Barrier: join the background writer (re-raising its failure —
        a shutdown must not silently drop a lost save) and drain orbax."""
        t0 = time.perf_counter()
        try:
            self._join_writer()
            self._mgr.wait_until_finished()
        finally:
            blocked_s = time.perf_counter() - t0
            self.save_blocked_ms += blocked_s * 1e3
            telemetry.span_event("save_blocked", blocked_s, phase="wait")

    def close(self) -> None:
        self._join_writer(reraise=False)
        self._mgr.close()
