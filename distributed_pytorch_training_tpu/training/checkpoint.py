"""Checkpoint / resume — absent from the reference (no torch.save/load
anywhere; SURVEY.md §5 "Checkpoint/resume: Absent") but required for usable
multi-host training on preemptible TPU pods.

Orbax-backed: sharded async-capable writes, multi-host-safe (every process
participates; no rank-0 funnel). Only the array pytrees are persisted
(step/params/batch_stats/opt_state); `apply_fn`/`tx` are code, reconstructed
by the caller — restoring requires a template TrainState with matching
structure, which `train.py` always has before resume.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple

import orbax.checkpoint as ocp

from .train_state import TrainState


def _arrays(state: TrainState) -> dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


class CheckpointManager:
    """Epoch-granular save/restore-latest (the resume story the reference's
    append-only CSV hints at but never implements, ref :349-354)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            Path(directory).resolve(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, epoch: int, state: TrainState, wait: bool = False) -> None:
        self._mgr.save(epoch, args=ocp.args.StandardSave(_arrays(state)))
        if wait:
            self._mgr.wait_until_finished()

    def restore_latest(self, template: TrainState) -> Optional[Tuple[TrainState, int]]:
        """Returns (state, epoch) or None if no checkpoint exists. `template`
        supplies structure/sharding for every restored array."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(_arrays(template)))
        state = template.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )
        return state, step

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
