"""Tasks: what a batch means and how loss/metrics are computed.

The reference hardcodes one task — image classification with
CrossEntropyLoss and top-1 accuracy (/root/reference/train_ddp.py:217-222,
:338). Here the task is a pluggable object so the same Trainer drives the
vision configs and the BERT/GPT-2 language configs (BASELINE.json:6-12).

Contract: ``loss_and_metrics`` returns ``(loss, (metrics, new_batch_stats))``
where metrics are *weighted sums* (not means) so they accumulate across steps
and reduce across hosts exactly like the reference's sample-weighted sums
(ref :217-222, :246-253):
  - "loss_sum":  sum(per_sample_loss * weight)
  - "correct":   sum(is_correct * weight)   (task-defined notion of correct)
  - "weight":    sum(weight)
All three stay on device until a print boundary (avoiding the reference's
per-step ``.item()`` sync anti-pattern, ref :217/:220; SURVEY.md §3.2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from ..data.augment import normalize_images, random_crop_flip
from ..parallel.collectives import TpShardedLogits, tp_parallel_cross_entropy

Metrics = Dict[str, jnp.ndarray]


class Task:
    """Interface; see module docstring for the metrics contract."""

    def loss_and_metrics(
        self,
        state,
        params,
        batch: Dict[str, jnp.ndarray],
        rng: jax.Array,
        train: bool,
    ) -> Tuple[jnp.ndarray, Tuple[Metrics, Any]]:
        raise NotImplementedError


@dataclasses.dataclass
class ImageClassificationTask(Task):
    """CIFAR/ImageNet classification (ref :217-222, :338).

    Augmentation (RandomCrop+Flip, ref :91-96) and normalization (ref :86-89)
    run on device as part of the compiled step — uint8 in, logits out.
    """

    mean: Sequence[float]
    std: Sequence[float]
    augment: bool = True
    crop_padding: int = 4
    compute_dtype: Any = jnp.float32

    def loss_and_metrics(self, state, params, batch, rng, train):
        images = batch["image"]
        if train and self.augment:
            images = random_crop_flip(images, rng, padding=self.crop_padding)
        x = normalize_images(images, self.mean, self.std, dtype=self.compute_dtype)

        variables = {"params": params}
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))
        if has_stats:
            variables["batch_stats"] = state.batch_stats

        if train and has_stats:
            logits, mutated = state.apply_fn(
                variables, x, train=True, mutable=["batch_stats"])
            new_stats = mutated["batch_stats"]
        else:
            logits = state.apply_fn(variables, x, train=train)
            new_stats = state.batch_stats

        labels = batch["label"]
        w = batch["weight"]
        per_sample = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels)
        weight_sum = w.sum()
        loss = (per_sample * w).sum() / jnp.maximum(weight_sum, 1.0)

        correct = ((jnp.argmax(logits, axis=-1) == labels) * w).sum()
        metrics = {
            "loss_sum": (per_sample * w).sum(),
            "correct": correct,
            "weight": weight_sum,
        }
        return loss, (metrics, new_stats)


@dataclasses.dataclass
class LanguageModelingTask(Task):
    """Causal next-token prediction (the GPT-2 355M config, BASELINE.json:12).

    Batch: {"input_ids": (B, S) int32, "weight": (B,)}. Loss = CE of token
    t+1 given tokens <=t, averaged over real (weighted) positions, plus
    `aux_loss_weight` x any auxiliary losses the model sows into its
    ``"losses"`` collection (0 for dense models — sowing is a no-op there).
    "correct" is next-token top-1 — so summarize() reports token accuracy.
    """

    compute_dtype: Any = jnp.float32
    aux_loss_weight: float = 0.0

    def loss_and_metrics(self, state, params, batch, rng, train):
        ids = batch["input_ids"]
        # Thread the step rng into apply so stochastic model internals
        # (dropout, MoE router jitter — models/moe.py router_noise) have the
        # "dropout" stream available at train time.
        rngs = {"dropout": rng} if train else None
        logits, mutated = state.apply_fn(
            {"params": params}, ids, train=train, mutable=["losses"],
            rngs=rngs)
        # shift: predict ids[:, 1:] from logits[:, :-1]
        tgt = ids[:, 1:]
        if isinstance(logits, TpShardedLogits):
            # vocab-parallel head (explicit TP): Megatron parallel-vocab
            # CE over the local logit columns — two (B, S, 2)-sized
            # model-axis stats instead of a vocab-scale logits gather
            # (parallel/collectives.tp_parallel_cross_entropy). Same
            # train and eval path.
            per_tok, predicted = tp_parallel_cross_entropy(
                logits.map_local(lambda x: x[:, :-1]), tgt)
        else:
            lg = logits[:, :-1].astype(jnp.float32)
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                lg, tgt)
            predicted = jnp.argmax(lg, axis=-1) == tgt
        w = batch["weight"][:, None] * jnp.ones_like(per_tok)
        wsum = w.sum()
        loss = (per_tok * w).sum() / jnp.maximum(wsum, 1.0)
        if self.aux_loss_weight:
            aux_leaves = jax.tree_util.tree_leaves(mutated.get("losses", {}))
            if aux_leaves:
                aux = (sum(jnp.asarray(a).mean() for a in aux_leaves)
                       / len(aux_leaves))
                loss = loss + self.aux_loss_weight * aux
        correct = (predicted * w).sum()
        metrics = {"loss_sum": (per_tok * w).sum(), "correct": correct,
                   "weight": wsum}
        return loss, (metrics, state.batch_stats)


@dataclasses.dataclass
class MoeLanguageModelingTask(LanguageModelingTask):
    """Causal LM over an MoE model (models/moe.py): the base CE loss plus the
    Switch-style router load-balancing loss the model sows (weight 0.01)."""

    aux_loss_weight: float = 0.01


@dataclasses.dataclass
class MaskedLMTask(Task):
    """BERT masked-LM (BASELINE.json:11, seq-len 512).

    Standard BERT recipe, applied ON DEVICE inside the compiled step: select
    15% of positions; of those 80% -> [MASK], 10% -> random token, 10% ->
    unchanged; loss only on selected positions. "correct" is masked-token
    top-1. Batch: {"input_ids": (B, S), "weight": (B,)}.
    """

    mask_token_id: int = 103  # BERT-base [MASK]
    vocab_size: int = 30522
    mask_prob: float = 0.15
    compute_dtype: Any = jnp.float32

    def loss_and_metrics(self, state, params, batch, rng, train):
        ids = batch["input_ids"]
        k_sel, k_act, k_rand = jax.random.split(rng, 3)
        selected = jax.random.bernoulli(k_sel, self.mask_prob, ids.shape)
        action = jax.random.uniform(k_act, ids.shape)
        masked = jnp.where(action < 0.8, self.mask_token_id,
                           jnp.where(action < 0.9,
                                     jax.random.randint(k_rand, ids.shape, 0,
                                                        self.vocab_size),
                                     ids))
        inputs = jnp.where(selected, masked, ids)

        rngs = {"dropout": jax.random.fold_in(rng, 1)} if train else None
        logits = state.apply_fn({"params": params}, inputs, train=train,
                                rngs=rngs)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), ids)
        w = selected.astype(jnp.float32) * batch["weight"][:, None]
        wsum = w.sum()
        loss = (per_tok * w).sum() / jnp.maximum(wsum, 1.0)
        correct = ((jnp.argmax(logits, axis=-1) == ids) * w).sum()
        metrics = {"loss_sum": (per_tok * w).sum(), "correct": correct,
                   "weight": wsum}
        return loss, (metrics, state.batch_stats)


def zero_metrics() -> Metrics:
    return {"loss_sum": jnp.zeros(()), "correct": jnp.zeros(()),
            "weight": jnp.zeros(())}


def add_metrics(a: Metrics, b: Metrics) -> Metrics:
    return jax.tree_util.tree_map(jnp.add, a, b)


def summarize(metrics: Metrics) -> Tuple[float, float]:
    """(mean loss, accuracy %) from weighted sums — the reference's
    global_loss/global_acc math (ref :258-259)."""
    total = float(metrics["weight"])
    if total == 0:
        return float("nan"), float("nan")
    return (float(metrics["loss_sum"]) / total,
            100.0 * float(metrics["correct"]) / total)
