"""TrainState — the pytree that replaces the reference's mutable
(model, optimizer, scaler) triple (/root/reference/train_ddp.py:335-346).

Functional: every train step maps state -> state. No GradScaler field exists
because bf16 needs no loss scaling (fp32-range exponent; SURVEY.md §2b row 4).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array  # int32 scalar
    params: Any
    batch_stats: Any  # BatchNorm EMAs ({} for stat-free models)
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    # Explicit-reducer side state (parallel/grad_sync.py): error-feedback
    # residuals for the int8 gradient wire ({"ef": ...}, per-replica rows
    # sharded over the batch axes — keyed per bucket/leaf for the bucketed
    # and zero1 scatters, per LAYER GROUP name for fsdp_explicit's
    # per-layer scatter). {} (no leaves) for every other mode — the
    # pytree/checkpoint shape is unchanged unless int8 is engaged.
    grad_sync: Any = dataclasses.field(default_factory=dict)

    @classmethod
    def create(cls, apply_fn: Callable, params: Any, tx: optax.GradientTransformation,
               batch_stats: Any = None, opt_state: Any = None) -> "TrainState":
        """``opt_state`` overrides the default ``tx.init(params)`` — the
        ZeRO-1 path (training/loop.py) constructs its optimizer state in the
        flat-padded-sharded layout (optim.zero1_opt_state), where every
        moment leaf is a 1-D chunk of the flattened parameter partitioned
        across the data-parallel replicas rather than a replicated copy.
        Checkpointing is layout-agnostic either way: orbax restores into
        whatever sharded template the run constructs (checkpoint.py)."""
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats if batch_stats is not None else {},
            opt_state=tx.init(params) if opt_state is None else opt_state,
            apply_fn=apply_fn,
            tx=tx,
        )

    def apply_gradients(self, grads: Any, batch_stats: Any = None) -> "TrainState":
        """optimizer.step() equivalent (ref :214 / :208)."""
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=self.batch_stats if batch_stats is None else batch_stats,
        )

    def param_count(self) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self.params))
