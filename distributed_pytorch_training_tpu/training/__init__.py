"""Training loops, optimizers, state — TPU-native equivalent of the reference's
L4 layer (/root/reference/train_ddp.py:170-300) plus the optimizer/scaler setup
(:339-346). The whole per-batch body (ref :198-222) compiles to ONE XLA program
per step; gradient synchronization is a layout consequence, not code.
"""

from .optim import make_optimizer, make_schedule  # noqa: F401
from .train_state import TrainState  # noqa: F401
from .loop import Trainer, TrainConfig  # noqa: F401
