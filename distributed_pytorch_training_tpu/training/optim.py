"""Optimizers — TPU-native equivalent of ``optim.SGD(lr, momentum,
weight_decay)`` (/root/reference/train_ddp.py:339-344) plus AdamW for the
transformer configs (BASELINE.json:11-12).

Built as optax transformation chains with torch-exact semantics:
torch SGD applies weight decay by adding ``wd * param`` to the gradient
*before* the momentum buffer update (decoupled-from-loss, coupled-to-momentum)
— the chain below reproduces that ordering, so parameter trajectories match
the reference step-for-step in fp32.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import optax

Schedule = Union[float, optax.Schedule]


def clip_by_global_norm_dp(
    max_norm: float, axis_names: Optional[Sequence[str]] = None,
    leaf_weights: Optional[dict] = None,
) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` whose norm is psum'd over mesh axes.

    The ZeRO-1 sharded update (training/loop.py) feeds the optimizer
    per-replica SHARDS of the global gradient; the stock clip would then
    clip each replica by its own shard's norm — a different (and per-replica
    inconsistent) trajectory. Summing the squared norms across `axis_names`
    first recovers the exact global norm, so zero1 and replicated runs clip
    identically. With ``axis_names=None`` this IS the stock transform (the
    single-device passthrough convention of parallel/collectives.py).
    Usable only inside a context that binds the axis names (shard_map).

    ``leaf_weights`` (explicit TP x FSDP, ISSUE 13): {'/'-joined leaf
    path: weight} multiplying each leaf's SQUARED contribution before the
    psum. The TP at-rest layout stores model-replicated leaves once per
    model shard, so a psum over (model,) + batch axes counts them M times;
    `parallel.sharding.tp_clip_weights` assigns those leaves 1/M (and
    TP-split leaves 1) so the recovered norm is the exact global one.
    Every leaf path must be present — a missing path is a loud KeyError,
    never a silently mis-weighted norm.
    """
    if not axis_names:
        return optax.clip_by_global_norm(max_norm)

    import jax
    import jax.numpy as jnp

    def update_fn(updates, state, params=None):
        del params
        if leaf_weights is None:
            sq = sum(jnp.sum(jnp.square(u))
                     for u in jax.tree_util.tree_leaves(updates))
        else:
            from ..parallel.sharding import _path_str

            sq = sum(
                leaf_weights[_path_str(path)] * jnp.sum(jnp.square(u))
                for path, u in jax.tree_util.tree_leaves_with_path(updates))
        g_norm = jnp.sqrt(jax.lax.psum(sq, tuple(axis_names)))
        # mirror optax.clip_by_global_norm exactly (select, not clamp) so
        # the parity with the replicated path is bit-for-bit in fp32
        trigger = jnp.squeeze(g_norm < max_norm)

        def clip_fn(t):
            return jax.lax.select(
                trigger, t, (t / g_norm.astype(t.dtype)) * max_norm)

        return jax.tree_util.tree_map(clip_fn, updates), state

    return optax.GradientTransformation(
        lambda params: optax.EmptyState(), update_fn)


def make_schedule(
    name: str,
    base_lr: float,
    total_steps: Optional[int] = None,
    warmup_steps: int = 0,
    final_lr_ratio: float = 0.0,
) -> optax.Schedule:
    """LR schedules. The reference uses a constant LR (no scheduler anywhere in
    train_ddp.py); cosine/warmup are provided for the transformer configs."""
    if name == "constant":
        return optax.constant_schedule(base_lr)
    if name == "cosine":
        if total_steps is None:
            raise ValueError("cosine schedule needs total_steps")
        warm = optax.linear_schedule(0.0, base_lr, max(warmup_steps, 1))
        cos = optax.cosine_decay_schedule(
            base_lr, max(total_steps - warmup_steps, 1), alpha=final_lr_ratio)
        return optax.join_schedules([warm, cos], [warmup_steps])
    if name == "linear_warmup":
        warm = optax.linear_schedule(0.0, base_lr, max(warmup_steps, 1))
        return optax.join_schedules(
            [warm, optax.constant_schedule(base_lr)], [warmup_steps])
    raise ValueError(f"unknown schedule {name!r} (constant, cosine, linear_warmup)")


def sgd(
    learning_rate: Schedule,
    momentum: float = 0.9,
    weight_decay: float = 5e-4,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """torch.optim.SGD parity (ref :339-344): g += wd*p, then momentum, then
    -lr step. Defaults match the reference CLI defaults (ref :30-35)."""
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


def adamw(
    learning_rate: Schedule,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.01,
    grad_clip_norm: Optional[float] = 1.0,
    shard_axes: Optional[Sequence[str]] = None,
    clip_leaf_weights: Optional[dict] = None,
) -> optax.GradientTransformation:
    """AdamW for BERT/GPT-2 (BASELINE.json:11-12); decoupled weight decay,
    optional global-norm clipping (standard for LM training).

    ``shard_axes``: mesh axis names the ZeRO-1 update shards gradients over
    — the clip's global norm is then psum'd across them (every other part of
    the chain is elementwise and shard-oblivious). Leave None for the
    replicated path. ``clip_leaf_weights`` — the explicit-TP duplication
    weights (see `clip_by_global_norm_dp`).
    """
    parts = []
    if grad_clip_norm:
        parts.append(clip_by_global_norm_dp(grad_clip_norm, shard_axes,
                                            leaf_weights=clip_leaf_weights))
    parts.append(optax.scale_by_adam(b1=b1, b2=b2, eps=eps))
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


def make_optimizer(
    name: str,
    learning_rate: Schedule,
    momentum: float = 0.9,
    weight_decay: float = 5e-4,
    grad_clip_norm: Optional[float] = None,
    shard_axes: Optional[Sequence[str]] = None,
    clip_leaf_weights: Optional[dict] = None,
) -> optax.GradientTransformation:
    """Optimizer factory keyed by CLI name (the reference hardcodes SGD,
    ref :339; transformers need AdamW). ``shard_axes`` /
    ``clip_leaf_weights`` — see `adamw`; SGD's chain is fully elementwise,
    so it needs no shard awareness."""
    if name == "sgd":
        return sgd(learning_rate, momentum=momentum, weight_decay=weight_decay)
    if name == "adamw":
        return adamw(learning_rate, weight_decay=weight_decay,
                     grad_clip_norm=grad_clip_norm, shard_axes=shard_axes,
                     clip_leaf_weights=clip_leaf_weights)
    raise ValueError(f"unknown optimizer {name!r} (sgd, adamw)")


def zero1_opt_state(tx: optax.GradientTransformation, params,
                    mesh, flatten_tree_fn=None, axes=None) -> "tuple":
    """Optimizer state for the sharded weight update: moments are born in
    the flat-padded-sharded layout (parallel/sharding.py `flatten_pad`),
    each replica materializing ONLY its 1/N chunk — the optimizer-memory
    division that motivates cross-replica weight-update sharding (Xu et
    al., PAPERS.md). Scalar state (step counts) stays replicated.

    Used by every mode that updates 1/N of the weights per replica: the
    manual zero1 shard_map path, the zero1 x TP GSPMD composition, and
    explicit FSDP (`fsdp_explicit`, which additionally stores the PARAMS
    in the same flat layout — parallel/sharding.py `fsdp_flat_params`).

    ``flatten_tree_fn``/``axes`` override the flat layout and the dim-0
    sharding axes — explicit TP x FSDP passes the model-major
    `tp_flat_leaf` layout and (model,) + batch axes, so moments are born
    1/(N*M) for every TP-split leaf.
    """
    import jax
    from jax.sharding import NamedSharding

    from ..parallel.mesh import batch_shard_count
    from ..parallel.sharding import dp_flat_specs, flatten_pad

    n = batch_shard_count(mesh)
    if flatten_tree_fn is None:
        def flatten_tree_fn(p):
            return jax.tree_util.tree_map(
                lambda leaf: flatten_pad(leaf, n), p)

    def init(params):
        return tx.init(flatten_tree_fn(params))

    specs = dp_flat_specs(jax.eval_shape(init, params),
                          *(() if axes is None else (tuple(axes),)))
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs)
    return jax.jit(init, out_shardings=shardings)(params)
