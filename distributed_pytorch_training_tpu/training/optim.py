"""Optimizers — TPU-native equivalent of ``optim.SGD(lr, momentum,
weight_decay)`` (/root/reference/train_ddp.py:339-344) plus AdamW for the
transformer configs (BASELINE.json:11-12).

Built as optax transformation chains with torch-exact semantics:
torch SGD applies weight decay by adding ``wd * param`` to the gradient
*before* the momentum buffer update (decoupled-from-loss, coupled-to-momentum)
— the chain below reproduces that ordering, so parameter trajectories match
the reference step-for-step in fp32.
"""

from __future__ import annotations

from typing import Optional, Union

import optax

Schedule = Union[float, optax.Schedule]


def make_schedule(
    name: str,
    base_lr: float,
    total_steps: Optional[int] = None,
    warmup_steps: int = 0,
    final_lr_ratio: float = 0.0,
) -> optax.Schedule:
    """LR schedules. The reference uses a constant LR (no scheduler anywhere in
    train_ddp.py); cosine/warmup are provided for the transformer configs."""
    if name == "constant":
        return optax.constant_schedule(base_lr)
    if name == "cosine":
        if total_steps is None:
            raise ValueError("cosine schedule needs total_steps")
        warm = optax.linear_schedule(0.0, base_lr, max(warmup_steps, 1))
        cos = optax.cosine_decay_schedule(
            base_lr, max(total_steps - warmup_steps, 1), alpha=final_lr_ratio)
        return optax.join_schedules([warm, cos], [warmup_steps])
    if name == "linear_warmup":
        warm = optax.linear_schedule(0.0, base_lr, max(warmup_steps, 1))
        return optax.join_schedules(
            [warm, optax.constant_schedule(base_lr)], [warmup_steps])
    raise ValueError(f"unknown schedule {name!r} (constant, cosine, linear_warmup)")


def sgd(
    learning_rate: Schedule,
    momentum: float = 0.9,
    weight_decay: float = 5e-4,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """torch.optim.SGD parity (ref :339-344): g += wd*p, then momentum, then
    -lr step. Defaults match the reference CLI defaults (ref :30-35)."""
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


def adamw(
    learning_rate: Schedule,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.01,
    grad_clip_norm: Optional[float] = 1.0,
) -> optax.GradientTransformation:
    """AdamW for BERT/GPT-2 (BASELINE.json:11-12); decoupled weight decay,
    optional global-norm clipping (standard for LM training)."""
    parts = []
    if grad_clip_norm:
        parts.append(optax.clip_by_global_norm(grad_clip_norm))
    parts.append(optax.scale_by_adam(b1=b1, b2=b2, eps=eps))
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


def make_optimizer(
    name: str,
    learning_rate: Schedule,
    momentum: float = 0.9,
    weight_decay: float = 5e-4,
    grad_clip_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    """Optimizer factory keyed by CLI name (the reference hardcodes SGD,
    ref :339; transformers need AdamW)."""
    if name == "sgd":
        return sgd(learning_rate, momentum=momentum, weight_decay=weight_decay)
    if name == "adamw":
        return adamw(learning_rate, weight_decay=weight_decay,
                     grad_clip_norm=grad_clip_norm)
    raise ValueError(f"unknown optimizer {name!r} (sgd, adamw)")
