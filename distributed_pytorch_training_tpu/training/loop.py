"""Trainer: compiled train/eval steps + epoch loops.

TPU-native re-design of train_one_epoch/validate
(/root/reference/train_ddp.py:170-300). The reference's per-batch body —
H2D copy, zero_grad, autocast forward, backward with DDP bucketed all-reduce,
scaler step (ref :198-214) — becomes ONE jitted function ``state, batch ->
state, metrics``; gradient sync is implied by the batch being sharded over the
mesh's data axes, and bf16 replaces autocast+GradScaler (no loss scaling
needed; SURVEY.md §2b).

Improvements over the reference, by design:
* metrics accumulate on device; the host fetches only at print boundaries
  (the ref's per-step ``.item()`` is a sync bottleneck, ref :217/:220);
* validation is sharded over the mesh instead of replicated per rank
  (ref :266-300 evaluates the full set on every rank; SURVEY.md §3.3);
* the last partial batch is padded+masked, so one XLA program serves every
  step (ref's drop_last=False short batch would recompile, SURVEY.md §7).

The parallelism promises the step modes make here (zero1's
scatter/update/gather signature, the bucketed reducer's collective bound,
compressed wires really off fp32, donation aliasing, no host transfers in
the compiled step, no per-step ``.item()`` syncs) are ENFORCED by the
contract checker — ``analysis check`` lowers the canonical config matrix
and lints this file's step paths (analysis/hlo_rules.py,
analysis/ast_rules.py ``no-host-sync-in-step``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import all_gather, psum, psum_scatter, shard_map
from ..parallel.grad_sync import (
    EF_WIRE_DTYPES, WIRE_DTYPES, HierSpec, build_bucket_plan,
    build_layer_plan, compressed_psum_scatter, ef_state_bucketed,
    ef_state_fsdp, ef_state_zero1, flatten_tree, hier_delta_all_gather,
    hier_psum_scatter, hier_shard_all_gather, padded_total_size,
    quantized_delta_all_gather, quantized_shard_all_gather, reduce_flat,
    unflatten_tree,
)
from ..parallel.mesh import BATCH_AXES, MODEL, batch_shard_count
from ..parallel.sharding import (
    PartitionRules, batch_spec, dp_flat_specs, feasible_spec,
    flatten_pad, fsdp_flat_params, fsdp_tp_flat_params, shard_pytree,
    tp_flat_leaf, tp_local_struct, tp_split_dims, tp_unflatten_leaf,
    tree_specs,
)
from ..utils.logging import log_main
from ..utils.metrics import ThroughputMeter
from .. import telemetry
from .tasks import Task, add_metrics, summarize, zero_metrics
from .train_state import TrainState


@dataclasses.dataclass
class TrainConfig:
    """Loop knobs (CLI-facing subset mirrors ref defaults, train_ddp.py:19-46)."""

    per_device_batch: int = 128
    print_freq: int = 50
    seed: int = 42
    bf16: bool = False  # the --amp equivalent (ref :36-37)
    donate_state: bool = True
    # Gradient accumulation: split each global batch into this many
    # microbatches inside the jitted step (lax.scan), summing weighted
    # gradients — reference-scale global batches on few chips at
    # 1/grad_accum the activation memory. 1 = off.
    grad_accum: int = 1
    # ZeRO-1 cross-replica weight-update sharding (Xu et al., PAPERS.md):
    # gradients reduce-scatter over the data-parallel axes instead of
    # all-reducing, each replica updates 1/N of the (flattened) parameters
    # with 1/N of the optimizer state, and the new parameters all-gather
    # back to replicated — optimizer compute and moment memory divided by
    # the DP degree. Off = the replicated (DDP-equivalent) update. No-op on
    # a single batch shard (the collectives' passthrough convention).
    zero1: bool = False
    # -- explicit gradient synchronization (parallel/grad_sync.py) --------
    # bucket_cap_mb > 0 engages the bucketed reducer (the DDP bucket_cap_mb
    # analog): gradients flatten into ceil(total_bytes / cap) contiguous
    # fp32 buckets, each synced by ONE collective — O(buckets) large
    # transfers instead of XLA's O(leaves) small ones. 0 = the implicit
    # path (gradient sync left to XLA layout propagation). Incompatible
    # with zero1 (whose per-leaf flat-shard layout IS its optimizer-state
    # checkpoint format).
    bucket_cap_mb: float = 0.0
    # Gradient wire dtype: "fp32" (exact), "bf16" (half the wire bytes,
    # bf16 accumulation on the wire — bounded error), "int8" (per-bucket
    # max-abs scales + error feedback carrying the quantization residual
    # to the next step; the bucketed form is gather-based, a byte win at
    # small DP degrees), or "int8_multihop" (DynamiQ's two-hop form: s8
    # all-to-all reduce-scatter with hop-1 error feedback, requantize the
    # partial sums, s8 all-gather — 2 collectives/bucket, ~2 B/element
    # regardless of the DP degree; see grad_sync.py's accounting). Master
    # accumulation and the optimizer always run fp32. Any non-fp32 value
    # engages the explicit reducer; "bf16"/"int8" compose with zero1 (the
    # reduce-scatter half compresses via s8 all-to-all, n-independently).
    # zero1 + "int8_multihop" is the FULLY compressed zero1 wire: the
    # scatter half is the s8 all-to-all (already n-independent — same as
    # "int8", with error feedback), and the param all-gather compresses
    # too — each replica gathers s8 codes of its shard's UPDATE (new
    # params - old params) plus one fp32 scale per chunk and adds the
    # identical dequantized delta to the replicated old params (bounded
    # per-step error, exactly replica-identical, not fed back;
    # grad_sync.quantized_delta_all_gather documents the model).
    # "int8_hier" is the two-tier topology-aware form on a tiered mesh
    # (a `slice` axis times the intra-slice batch axes): per bucket, an
    # EXACT fp32 reduce-scatter inside the slice (the fast ICI tier),
    # the DynamiQ s8 two-hop exchange ACROSS slices (the slow DCN tier —
    # the only compressed, error-fed-back stage; ~2 B/element per slice
    # independent of the slice count), and an exact intra-slice
    # all-gather back (grad_sync._int8_hier_sum). On a mesh without a
    # multi-sized slice axis it resolves to the flat fp32 path
    # (bit-identical passthrough, logged). Composes with grad-accum
    # overlap, zero1 (hier scatter + s8-over-slice param gather), and
    # fsdp_explicit's per-layer cut; rejected with explicit TP (the
    # model axis owns its own wire).
    wire_dtype: str = "fp32"
    # The mesh axis named as the slow-tier/outer axis for "int8_hier"
    # (mesh.SLICE by default — `--slices N` populates it). Must be one of
    # the mesh's batch axes; axes of size 1 (or absent) make int8_hier a
    # flat-fp32 passthrough.
    slice_axis: str = "slice"
    # Explicit full-parameter FSDP (SimpleFSDP, PAPERS.md): params AND
    # optimizer moments live flat-sharded 1/N per replica AT REST (the
    # zero1 flat padded layout applied to the parameters themselves), each
    # layer's params are all-gathered just-in-time inside the shard_map'd
    # step — gathers chained one layer ahead so layer i+1's gather can
    # overlap layer i's compute — and gradients reduce-scatter directly
    # back into the shard layout (compressed_psum_scatter, per layer).
    # Parameter memory at rest divides by the batch-shard count; the
    # transient in-step working set still peaks at full params (the
    # gathered copies live through the backward), like zero1. Composes
    # with wire_dtype: bf16/int8 compress the gradient scatter
    # (int8 with error feedback, per layer group); "int8_multihop"
    # additionally compresses the param gathers as s8 codes + per-chunk
    # scales (grad_sync.quantized_shard_all_gather — bounded,
    # replica-identical per-step perturbation of the gathered WORKING copy
    # only; at-rest shards stay exact fp32). Incompatible with zero1 (this
    # IS zero1 plus sharded params) and bucket_cap_mb (the per-layer cut
    # owns the wire layout). Off = params replicated (DDP layout).
    fsdp_explicit: bool = False
    # In grad-accum mode, reduce microbatch i's buckets INSIDE the scan
    # body (no data dependency on microbatch i+1's compute, so XLA can
    # overlap comm with compute — DDP's backward-hook overlap). False =
    # accumulate locally and reduce once after the scan (exposes the comm;
    # exists to measure the overlap win).
    overlap_grad_sync: bool = True
    # Fused int8 codec kernels (ops/quantize.py): route the int8 wires'
    # quantize (absmax-scale + round/clip) and receive-side dequant-
    # accumulate through Pallas kernels instead of the XLA-composed op
    # chain — one VMEM pass per codec stage, bit-identical by contract
    # (PARITY.md). None = auto (TPU only, DPT_FUSED_QUANTIZE env
    # override); True forces the kernels (interpreter mode on CPU — the
    # parity-test configuration); False forces the XLA-composed reference.
    # A no-op unless wire_dtype is an int8 mode on a multi-shard mesh.
    fused_quantize: Optional[bool] = None


def split_microbatches(tree: Any, accum: int,
                       scope: str = "per-shard batch") -> Any:
    """Interleaved microbatch split of a batch pytree for the grad-accum
    scan: leading dim B -> (accum, B/accum, ...), microbatch i = rows
    i::accum. INTERLEAVED, not contiguous blocks: the batch is sharded
    over the data axes by contiguous row ranges, so a contiguous
    microbatch would live on 1/accum of the devices and every scan step
    would reshard; strided microbatches stay evenly spread over all
    shards. Scalars broadcast to (accum,). One splitter for every step
    mode (replicated / grad_sync / zero1 / fsdp — the four scan bodies
    must agree on the interleaving or their parity tests lie); ``scope``
    names the batch in the divisibility error ("global batch" on the
    replicated path, the per-shard default inside shard_map bodies)."""

    def split(x):
        if x.ndim == 0:
            return jnp.broadcast_to(x, (accum,))
        if x.shape[0] % accum:
            raise ValueError(
                f"{scope} {x.shape[0]} not divisible by "
                f"grad_accum={accum}")
        return x.reshape(x.shape[0] // accum, accum,
                         *x.shape[1:]).swapaxes(0, 1)

    return jax.tree_util.tree_map(split, tree)


class Trainer:
    """Owns the compiled steps for one (model task, mesh) pair."""

    def __init__(
        self,
        task: Task,
        mesh: Mesh,
        config: TrainConfig,
        rules: Optional[PartitionRules] = None,
    ):
        self.task = task
        self.mesh = mesh
        self.config = config
        self.rules = rules
        # optional MFU reference (set_mfu_reference): when present, the
        # throughput print lines also report model-FLOPs utilization
        self._flops_per_sample: Optional[float] = None
        self._peak_flops_total: Optional[float] = None
        # optional telemetry.AnomalyWatchdog fed per-step host timings and
        # print-boundary losses by train_epoch (train.py installs it; None
        # everywhere else — the hot path pays two perf_counter reads)
        self.watchdog = None

        if config.wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype {config.wire_dtype!r} is not one of "
                f"{WIRE_DTYPES}")
        if config.bucket_cap_mb < 0:
            raise ValueError(
                f"bucket_cap_mb must be >= 0, got {config.bucket_cap_mb}")
        if config.zero1 and config.bucket_cap_mb > 0:
            raise ValueError(
                "bucket_cap_mb is the bucketed reducer of the replicated "
                "update path; zero1's per-leaf flat-shard layout IS its "
                "optimizer-state (and checkpoint) format — use zero1 with "
                "wire_dtype compression, or the bucketed reducer without "
                "zero1, not both")
        if config.fsdp_explicit and config.zero1:
            raise ValueError(
                "fsdp_explicit IS zero1 plus flat-sharded parameters (the "
                "sharded update with per-layer just-in-time gathers) — "
                "pick one update mode, not both")
        if config.fsdp_explicit and config.bucket_cap_mb > 0:
            raise ValueError(
                "bucket_cap_mb cuts the replicated reducer's flat "
                "gradient; fsdp_explicit's wire layout is the per-layer "
                "cut of the parameter tree (grad_sync.build_layer_plan) — "
                "use fsdp_explicit with wire_dtype compression instead")
        explicit_sync = (config.bucket_cap_mb > 0
                         or config.wire_dtype != "fp32")
        self._zero1_n = batch_shard_count(mesh)
        multi = self._zero1_n > 1
        model_n = mesh.shape.get(MODEL, 1)
        # Explicit TP x FSDP (ISSUE 13): on a 2-D ("data","model") mesh the
        # fsdp step runs megatron column/row-split blocks inside the SAME
        # shard_map (one psum over `model` per residual join); the
        # per-layer gathers/scatters ride the data axes only, over the
        # TP-LOCAL parameter slices — wire bytes drop 1/M per replica.
        # Params + both AdamW moments live flat-sharded 1/(N*M) at rest
        # (model-major flat layout, parallel/sharding.py tp_flat_leaf).
        self._fsdp = bool(config.fsdp_explicit) and (multi or model_n > 1)
        self._tp_n = model_n if (self._fsdp and model_n > 1) else 1
        # zero1 x TP (the per-leaf composition): on meshes with a model
        # axis the manual shard_map path cannot run (the TP layers need
        # GSPMD inside the body, and jax 0.4.x partial-auto shard_map
        # rejects the collectives) — the update shards via per-leaf
        # flat-padded sharding CONSTRAINTS instead: gradients/params are
        # annotated P(batch axes) per leaf and GSPMD partitions the
        # optimizer update + inserts the scatter/gather movement.
        self._zero1_gspmd = bool(config.zero1) and multi and model_n > 1
        self._zero1 = (bool(config.zero1) and multi
                       and not self._zero1_gspmd)
        self._grad_sync = (explicit_sync and not config.zero1
                           and not config.fsdp_explicit and multi)
        # -- two-tier topology-aware wire (int8_hier) ---------------------
        # Resolve the EFFECTIVE wire dtype and the hierarchy spec ONCE;
        # every step path and init_state read self._wire / self._hier
        # (engagement above keys off the REQUESTED dtype, so a resolved
        # passthrough still runs the explicit reducer — at fp32).
        self._wire = config.wire_dtype
        self._hier: Optional[HierSpec] = None
        if config.wire_dtype == "int8_hier":
            slice_axis = config.slice_axis
            if slice_axis not in BATCH_AXES:
                raise ValueError(
                    f"int8_hier syncs over the batch axes {BATCH_AXES}; "
                    f"slice_axis={slice_axis!r} is not one of them — the "
                    "slow tier must be a data-parallel mesh axis "
                    "(mesh.SLICE by default, populated by --slices)")
            n_slices = mesh.shape.get(slice_axis, 1)
            if n_slices > 0 and self._zero1_n % n_slices:
                # unreachable when slice_axis is a real batch axis (the
                # world IS the product of the batch axes) — a loud guard
                # for hand-built meshes
                raise ValueError(
                    f"int8_hier: {self._zero1_n} batch shards do not "
                    f"factor into {n_slices} slices (world % slices != 0)")
            if self._tp_n > 1:
                raise ValueError(
                    "int8_hier does not compose with explicit TP: the "
                    "model axis runs megatron psums with their own wire "
                    "accounting, and the hier codec's fast-tier "
                    "reduce-scatter would have to thread through them — "
                    "use int8_multihop under fsdp_explicit x TP, or "
                    "int8_hier on a model-free mesh")
            if n_slices > 1:
                fast = tuple(a for a in BATCH_AXES
                             if a != slice_axis
                             and mesh.shape.get(a, 1) > 1)
                self._hier = HierSpec(
                    slice_axis=slice_axis, fast_axes=fast,
                    n_slices=n_slices,
                    n_inner=self._zero1_n // n_slices)
            else:
                # slices=1 passthrough: nothing crosses a slow link, the
                # hierarchy collapses to the flat EXACT path — bit-for-bit
                # the fp32 wire (pinned in tests/test_hier.py)
                self._wire = "fp32"
                log_main("NOTE: int8_hier requested without a multi-slice "
                         f"mesh (axis {slice_axis!r} size {n_slices}) — "
                         "running the flat fp32 wire (bit-identical "
                         "passthrough)")
        # the per-layer gather plan + unflatten template; built by
        # init_state for fsdp_explicit states (the step needs the original
        # shapes — flat leaves alone cannot be unflattened)
        self._fsdp_plan = None
        self._fsdp_template = None
        self._fsdp_sizes = None
        # explicit-TP state (built by init_state when _tp_n > 1): the
        # per-leaf model-axis split dims (tp_fsdp_rules read as layout),
        # the TP-local model clone whose apply the step body runs, and the
        # TP-local ShapeDtypeStruct template the per-layer gather
        # unflattens against
        self._tp_split_dims = None
        self._tp_model = None
        self._fsdp_local_template = None
        if config.zero1 or config.fsdp_explicit or explicit_sync:
            # These modes run the step in a shard_map over the batch axes
            # (zero1/grad_sync with replicated parameters, fsdp_explicit
            # with flat-sharded ones) — same mesh constraints, except
            # zero1 composes with a `model` axis via the GSPMD path above.
            mode = ("fsdp_explicit" if config.fsdp_explicit
                    else "zero1" if config.zero1
                    else "grad_sync (bucket_cap_mb/wire_dtype)")
            allowed = ({MODEL} if (config.zero1 or config.fsdp_explicit)
                       else set())
            bad = sorted(a for a, s in mesh.shape.items()
                         if s > 1 and a not in BATCH_AXES
                         and a not in allowed)
            if bad:
                raise ValueError(
                    f"{mode} runs gradient sync over the data-parallel "
                    f"axes {BATCH_AXES}; mesh axes {bad} > 1 need the "
                    "implicit path (SP/PP/EP collectives are per-layer, "
                    "not per-update; only zero1 and fsdp_explicit compose "
                    "with a model axis — zero1 via the per-leaf GSPMD "
                    "update, fsdp_explicit via explicit megatron TP)")
            if self._zero1_gspmd and config.wire_dtype != "fp32":
                raise ValueError(
                    "zero1 on a model-axis mesh runs the GSPMD sharded "
                    "update, where the scatter/gather are layout "
                    "constraints, not explicit collectives the codecs "
                    "could wrap — a compressed wire on a model-axis mesh "
                    "is --fsdp-explicit's job (explicit TP x FSDP owns "
                    "its wire layout end to end; PARITY.md records this "
                    "path as subsumed); use wire_dtype='fp32' here")
            if rules is not None:
                conflict = sorted(
                    rules.axes_used()
                    & {a for a in BATCH_AXES if mesh.shape[a] > 1})
                if conflict and config.fsdp_explicit:
                    raise ValueError(
                        "fsdp_explicit owns the parameter layout "
                        "(flat-sharded 1/N over the batch axes) and would "
                        f"silently drop the partition rules sharding "
                        f"params over {conflict} — use GSPMD rules with "
                        "the implicit path, or fsdp_explicit without "
                        "param-sharding rules, not both")
                if conflict:
                    raise ValueError(
                        f"{mode} assumes replicated parameters, but the "
                        f"partition rules shard params over {conflict} — "
                        "explicitly sharded params + explicit sync is "
                        "fsdp_explicit's job (TrainConfig.fsdp_explicit / "
                        "--fsdp-explicit); GSPMD fsdp rules need the "
                        "implicit path")
            if config.zero1 and not multi:
                log_main("NOTE: zero1 requested on a single batch shard — "
                         "running the replicated update (identity "
                         "passthrough, like single-process DDP)")
            if config.fsdp_explicit and not multi and model_n <= 1:
                log_main("NOTE: fsdp_explicit requested on a single batch "
                         "shard — nothing to shard; running the "
                         "replicated update (identity passthrough)")
            if (not config.zero1 and not config.fsdp_explicit
                    and explicit_sync and not self._grad_sync):
                log_main("NOTE: explicit gradient sync requested on a "
                         "single batch shard — nothing to synchronize; "
                         "running the implicit path (identity passthrough, "
                         "like single-process DDP)")

        donate = (0,) if config.donate_state else ()
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=donate)
        self._eval_step = jax.jit(self._eval_step_impl)

    @property
    def batch_shards(self) -> int:
        """The DP world size this trainer's step was built for (product of
        the mesh's batch axes). The restart Supervisor records it in every
        checkpoint manifest and re-plans against it on an elastic resize —
        the per-step RNG (folded from ``state.step``) and the sampler
        (seeded by seed+epoch at a FIXED global batch) are world-size-
        independent, so a resharded restore replays the same trajectory
        behind the same step fence."""
        return self._zero1_n

    def tp_expected_model_collectives(self) -> Tuple[int, int]:
        """(model-axis psums, model-axis gathers) one explicit-TP train
        step legitimately spends on STRUCTURAL (hidden-activation-sized)
        collectives — the `tp-psum-signature` rule's budget
        (analysis/hlo_rules.py), derived from the TP model: per block, one
        psum per residual join in the forward (attention out + MLP out)
        and one backward psum per parallel-region input — 4 per block —
        plus the vocab-parallel embedding's lookup psum + head-input
        backward psum when engaged. Gathers are 0: the parallel-vocab
        cross-entropy (collectives.tp_parallel_cross_entropy) replaced
        the vocab-scale logits gather; its two (B, S, 2)-sized stat
        collectives are batch-shaped, not hidden-shaped, and are budgeted
        separately by `tp_expected_ce_stat_elements` so the rule can
        floor-filter them. (0, 0) when explicit TP is not engaged."""
        if self._tp_n <= 1 or self._tp_model is None:
            return (0, 0)
        depth = getattr(self._tp_model, "depth", None)
        if depth is None:
            return (0, 0)
        tp_vocab = bool(getattr(self._tp_model, "tp_vocab", False))
        return (4 * depth + (2 if tp_vocab else 0), 0)

    def tp_expected_ce_stat_elements(self, local_rows: int,
                                     seq_len: int) -> int:
        """Per-shard element count of EACH of the parallel-vocab CE's two
        model-axis stat collectives (the stop-gradient pmax and the
        stacked [sumexp, target-logit] psum — both deliberately
        (local_rows, seq-1, 2)-shaped so they share one census size
        class; collectives.tp_parallel_cross_entropy). The
        `tp-psum-signature` rule adds 2 to the psum budget iff this
        clears its census floor — the stats are batch-shaped, so whether
        a given artifact SEES them depends on batch x floor, unlike the
        hidden-sized structural psums. 0 when the vocab-parallel head is
        not engaged."""
        if self._tp_n <= 1 or self._tp_model is None:
            return 0
        if not bool(getattr(self._tp_model, "tp_vocab", False)):
            return 0
        return 2 * int(local_rows) * max(int(seq_len) - 1, 1)

    def tp_wire_bytes(self, local_batch: int, seq_len: int) -> int:
        """Per-replica model-axis wire bytes of one explicit-TP step
        (`grad_sync.tp_psum_bytes_per_step` fed from the TP model) — the
        TP tier term train.py and the bench harness emit. 0 when explicit
        TP is not engaged."""
        from ..parallel.grad_sync import tp_psum_bytes_per_step

        if self._tp_n <= 1 or self._tp_model is None:
            return 0
        m = self._tp_model
        if getattr(m, "depth", None) is None:
            return 0
        return tp_psum_bytes_per_step(
            m.hidden_dim, m.depth, local_batch, seq_len, self._tp_n,
            tp_vocab=bool(getattr(m, "tp_vocab", False)),
            padded_vocab=getattr(m, "padded_vocab", 0))

    def wire_accounting_inputs(self, state: TrainState, base_cfg: dict,
                               global_batch: int, seq_len: int):
        """(params, cfg) for `grad_sync.emit_wire_accounting` — THE one
        assembly both train.py and the bench harness use, so their rows
        cannot drift. Under explicit TP the data-axis terms come from the
        TP-LOCAL template (each model shard gathers/scatters only its 1/M
        slice) and the model-axis activation bytes ride ``tp_psum_bytes``
        (their own telemetry tier row); 1-D configs pass through
        unchanged."""
        cfg = dict(base_cfg)
        params = state.params
        if self._tp_n > 1:
            params = self._fsdp_local_template
            cfg["model_shards"] = self._tp_n
            cfg["tp_psum_bytes"] = self.tp_wire_bytes(
                global_batch // self._zero1_n, seq_len)
        if self._hier is not None:
            # the slice factorization lives in the MESH, not the config
            # dict callers hold — inject the resolved count so the
            # accounting records the tiered split (and a resolved
            # passthrough records the flat fp32 wire it actually runs)
            cfg["slices"] = self._hier.n_slices
        elif cfg.get("wire_dtype") == "int8_hier":
            cfg["wire_dtype"] = self._wire  # slices=1 passthrough: fp32
        return params, cfg

    def set_mfu_reference(self, flops_per_sample: float,
                          peak_flops_total: float) -> None:
        """Enable MFU in the step log: `flops_per_sample` is the analytic
        train-step cost of ONE sample (experiments/flops.py),
        `peak_flops_total` the summed peak FLOP/s of the mesh's devices.
        The reference's meter stops at samples/s (train_ddp.py:224-243);
        MFU is the same number made comparable across hardware."""
        self._flops_per_sample = flops_per_sample
        self._peak_flops_total = peak_flops_total

    # -- compiled bodies ---------------------------------------------------

    def _train_step_impl(self, state: TrainState, batch, epoch_key):
        rng = jax.random.fold_in(epoch_key, state.step)
        accum = self.config.grad_accum

        if self._fsdp:
            return self._fsdp_step(state, batch, rng)
        if self._zero1:
            return self._zero1_step(state, batch, rng)
        if self._grad_sync:
            return self._grad_sync_step(state, batch, rng)

        if accum <= 1:
            def loss_fn(params):
                return self.task.loss_and_metrics(state, params, batch, rng,
                                                  train=True)

            grads, (metrics, new_stats) = jax.grad(
                loss_fn, has_aux=True)(state.params)
            # No explicit all-reduce: grads of a loss over the data-sharded
            # global batch are already the synchronized gradients (the DDP
            # reducer's job, ref :305-310, done by XLA layout propagation).
            if self._zero1_gspmd:
                return self._zero1_gspmd_apply(state, grads,
                                               new_stats), metrics
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics

        # -- gradient accumulation ----------------------------------------
        # The task loss is the weighted MEAN over its (micro)batch, so the
        # global-batch gradient is the weight-proportional combination:
        #   d(global mean)/dθ = Σ_i (w_i / W) · d(mean_i)/dθ.
        # We accumulate w_i-scaled microbatch grads in the scan carry and
        # divide by W once.
        #
        # Equivalence scope (vs the unaccumulated step on the same batch):
        # EXACT (up to fp reassociation) for deterministic per-sample losses
        # (causal LM with dropout 0 — the parity test). NOT bit-equal for:
        # * stochastic tasks (MLM masking, dropout, augmentation): each
        #   microbatch gets its own fold of the step RNG, so different
        #   positions mask — still an unbiased step, just a different draw;
        # * batch-statistic auxiliary losses (MoE load balancing): the
        #   accumulated objective is the w_i/W-weighted combination of
        #   per-microbatch aux losses, whereas grad_accum=1 computes routing
        #   statistics over the full batch. Inherent to accumulation, not a
        #   bug — per-microbatch balancing is itself a valid regularizer.
        # * BatchNorm models (ResNets): each microbatch normalizes by ITS
        #   OWN statistics (exactly torch's behavior under accumulation), so
        #   grads differ from the full-batch step by the (small, O(1/|mb|))
        #   between-microbatch variance. Running stats stay unbiased: every
        #   microbatch EMA starts from the SAME pre-step stats (state is
        #   closed over, not carried), so the weighted mean of the per-
        #   microbatch EMAs equals ONE EMA update with the weighted-mean
        #   batch statistics — not `accum` compounding updates.
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))

        micro_batches = split_microbatches(batch, accum,
                                           scope="global batch")

        def micro_grads(mb, key):
            def loss_fn(params):
                return self.task.loss_and_metrics(state, params, mb, key,
                                                  train=True)

            return jax.grad(loss_fn, has_aux=True)(state.params)

        def body(carry, xs):
            g_sum, s_sum, m_sum = carry
            mb, key = xs
            g, (m, new_stats) = micro_grads(mb, key)
            w = m["weight"]
            g_sum = jax.tree_util.tree_map(
                lambda a, b: a + w * b.astype(a.dtype), g_sum, g)
            if has_stats:
                s_sum = jax.tree_util.tree_map(
                    lambda a, b: a + w * b.astype(a.dtype), s_sum, new_stats)
            m_sum = add_metrics(m_sum, m)
            return (g_sum, s_sum, m_sum), None

        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
        s0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, jnp.float32), state.batch_stats)
        keys = jax.random.split(rng, accum)
        (g_sum, s_sum, metrics), _ = jax.lax.scan(
            body, (g0, s0, zero_metrics()), (micro_batches, keys))
        total_w = jnp.maximum(metrics["weight"], 1.0)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / total_w).astype(p.dtype), g_sum, state.params)
        if has_stats:
            # A fully-padded global batch (weight 0) must keep the old
            # stats, not zero them (grads are already a no-op then).
            new_stats = jax.tree_util.tree_map(
                lambda s, old: jnp.where(metrics["weight"] > 0, s / total_w,
                                         old.astype(jnp.float32)
                                         ).astype(old.dtype),
                s_sum, state.batch_stats)
        else:
            new_stats = state.batch_stats
        if self._zero1_gspmd:
            return self._zero1_gspmd_apply(state, grads, new_stats), metrics
        new_state = state.apply_gradients(grads, batch_stats=new_stats)
        return new_state, metrics

    # -- ZeRO-1 x TP: GSPMD-sharded weight update ----------------------------

    def _zero1_gspmd_apply(self, state: TrainState, grads, new_stats
                           ) -> TrainState:
        """The zero1 update on meshes with a `model` axis (the per-leaf
        composition): gradients arrive fully synchronized from the
        replicated path's implicit sync (TP params carry TP-sharded grads,
        DP sync is XLA's), and the UPDATE shards over the batch axes by
        layout constraint — each leaf's gradient, parameter view, and
        moments are flat-padded and annotated P(batch axes), so GSPMD
        partitions the elementwise optimizer chain 1/N per replica and
        inserts the scatter/gather data movement itself. Moments live
        flat-sharded from init (`optim.zero1_opt_state`), exactly like the
        manual zero1 path — same checkpoint layout, same memory division.

        Trade-offs vs the manual shard_map path (pure-DP meshes), stated
        honestly: the collective schedule is XLA's choice (no
        reduce-scatter signature contract), wire compression is
        unavailable (the scatter/gather are constraints, not explicit
        collectives the codecs could wrap), and the global-norm clip runs
        on GLOBAL flat arrays (stock optax — build the optimizer with
        shard_axes=None). Parity vs the replicated update is pinned at
        reassociation tolerance in tests/test_zero1.py."""
        from jax.sharding import NamedSharding

        mesh, n = self.mesh, self._zero1_n
        dp = NamedSharding(mesh, P(BATCH_AXES))

        def flat_dp(x):
            return lax.with_sharding_constraint(
                flatten_pad(x.astype(jnp.float32), n), dp)

        flat_g = jax.tree_util.tree_map(flat_dp, grads)
        p_flat = jax.tree_util.tree_map(flat_dp, state.params)
        updates, new_opt = state.tx.update(flat_g, state.opt_state, p_flat)
        new_flat = optax.apply_updates(p_flat, updates)
        # back to model shapes, re-constrained to the rules' layout so the
        # updated params keep their TP sharding instead of whatever the
        # flat->full reshape propagates
        specs = tree_specs(state.params, self.rules)

        def unflatten(f, p, spec):
            full = f[:p.size].reshape(p.shape).astype(p.dtype)
            return lax.with_sharding_constraint(
                full, NamedSharding(
                    mesh, feasible_spec(spec, p.shape, mesh)))

        new_params = jax.tree_util.tree_map(unflatten, new_flat,
                                            state.params, specs)
        return state.replace(step=state.step + 1, params=new_params,
                             batch_stats=new_stats, opt_state=new_opt)

    # -- explicit bucketed / compressed gradient sync ------------------------

    def _grad_sync_step(self, state: TrainState, batch, rng):
        """The native DDP reducer (parallel/grad_sync.py): the step runs in
        a shard_map over the batch axes, each replica computes its LOCAL
        weight-scaled gradient sum, flattens it into the bucket plan's flat
        vector, and syncs bucket-by-bucket at the configured wire dtype;
        the (replicated) optimizer update consumes the fp32 global mean.
        In grad-accum mode with overlap on, each microbatch's buckets are
        reduced INSIDE the scan body — microbatch i's collectives have no
        data dependency on microbatch i+1's compute, so XLA's latency-
        hiding scheduler can run them concurrently (DDP's backward-hook
        overlap, done by dependence structure instead of hooks).

        Equivalence scope vs the implicit path, same batch:
        * The REASSOCIATION ORDER changes: the implicit path lets XLA
          contract the loss mean over the global batch; here each replica
          sums its local batch first and the psum combines replicas (and,
          under accumulation with overlap, per-microbatch psums sum
          instead of one psum of sums). Within a bucket, leaves keep
          `jax.tree_util.tree_leaves` order. Same real-number gradient,
          fp-rounding-level differences — the parity contract
          tests/test_grad_sync.py pins with tolerances and documents.
          Bucket BOUNDARIES never change math: per-element reductions are
          independent, so different bucket_cap_mb values produce
          bit-identical trajectories (also pinned).
        * bf16 wire: the cross-replica sum accumulates in bf16 — a bounded
          per-step perturbation, convergence pinned on the tiny-LM task.
        * int8 wire: per-bucket max-abs quantization with error feedback —
          biased per step, telescoping across steps; convergence pinned.
        * int8_multihop wire: TWO quantizations per bucket — hop 1
          per-destination-chunk with error feedback (telescoping, like
          int8), hop 2 on the requantized partial sum (a bounded per-step
          perturbation, identical on every replica, NOT fed back —
          grad_sync.py documents the bound); convergence pinned.
        * int8_hier wire: the intra-slice reduce-scatter and all-gather
          are EXACT fp32 (only reassociation changes vs flat fp32); all
          compression error comes from the cross-slice s8 multihop stage
          (hop-1 EF telescoping + hop-2 bounded, the int8_multihop model
          applied over the slice axis alone — PARITY.md "Exactness
          model: two-tier sync"); convergence pinned.
        * stochastic tasks / BatchNorm: the zero1 caveats verbatim (each
          shard folds its index into the step RNG; BN normalizes by
          per-shard statistics, torch DDP's per-GPU BN semantics).
        """
        mesh, accum, n = self.mesh, self.config.grad_accum, self._zero1_n
        axes = BATCH_AXES
        task, cfg = self.task, self.config
        wire, overlap = self._wire, cfg.overlap_grad_sync
        hier = self._hier if wire == "int8_hier" else None
        fusedq = cfg.fused_quantize  # tri-state; codecs resolve at trace
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))
        outer = state
        plan = build_bucket_plan(state.params, cfg.bucket_cap_mb)
        use_ef = wire in EF_WIRE_DTYPES
        if use_ef and not state.grad_sync:
            raise ValueError(
                f"wire_dtype={wire!r} needs error-feedback buffers — build "
                "the state via Trainer.init_state (TrainState.grad_sync is "
                "empty)")
        if use_ef:
            # The residual layout is plan-dependent for the multihop wire
            # (padded_bucket_bounds of THIS bucket_cap_mb): a checkpoint
            # resumed under a different cap would silently re-inject stale
            # error at the wrong elements — fail loudly on the size
            # mismatch instead. (Same-size different-layout collisions are
            # possible in principle; changing the cap across a multihop
            # resume is unsupported, documented at ef_state_bucketed.)
            if wire == "int8_multihop":
                expect = padded_total_size(plan, n)
            elif wire == "int8_hier":
                # one slow-tier residual slice per replica: the padded
                # layout divided by the intra-slice degree (the fast
                # reduce-scatter's output IS the compressed stage's input)
                expect = padded_total_size(plan, n) // hier.n_inner
            else:
                expect = plan.total_size
            got = state.grad_sync["ef"].shape[-1]
            if got != expect:
                raise ValueError(
                    f"error-feedback residual length {got} does not match "
                    f"the {wire!r} wire's layout for bucket_cap_mb="
                    f"{cfg.bucket_cap_mb} ({expect} elements) — the state "
                    "was built (or checkpointed) under a different bucket "
                    "plan; rebuild via Trainer.init_state or restore with "
                    "the original bucket_cap_mb")

        rep = P()
        batch_specs = jax.tree_util.tree_map(
            lambda x: batch_spec(jnp.ndim(x)), batch)
        ef_spec = P(axes)

        def body(params, opt_state, stats, lbatch, key, step, *maybe_ef):
            inner = outer.replace(step=step, params=params,
                                  batch_stats=stats, opt_state=opt_state)
            idx = lax.axis_index(axes)
            # local residual: (S,) for int8, (S_padded,) for int8_multihop
            ef_l = maybe_ef[0][0] if use_ef else None

            def micro_grads(mb, k):
                def loss_fn(p):
                    return task.loss_and_metrics(inner, p, mb, k, train=True)

                return jax.grad(loss_fn, has_aux=True)(params)

            if accum <= 1:
                key = jax.random.fold_in(key, idx)
                g, (m, stats_l) = micro_grads(lbatch, key)
                w = m["weight"]
                flat = flatten_tree(jax.tree_util.tree_map(
                    lambda a: w * a.astype(jnp.float32), g))
                flat, ef_l = reduce_flat(flat, plan, axes, n, wire, ef_l,
                                         fused=fusedq, hier=hier)
                s_sum = (jax.tree_util.tree_map(
                    lambda s: w * s.astype(jnp.float32), stats_l)
                    if has_stats else stats)
                m_local = m
            else:
                # the replicated path's interleaved LOCAL split (zero1's
                # argument verbatim: local rows i::accum are the shard's
                # part of global microbatch i)
                micro_batches = split_microbatches(lbatch, accum)
                keys = jax.random.split(key, accum)

                def mb_body(carry, xs):
                    acc, s_sum, m_sum, ef_c = carry
                    mb, k = xs
                    g, (m, stats_mb) = micro_grads(
                        mb, jax.random.fold_in(k, idx))
                    w = m["weight"]
                    flat = flatten_tree(jax.tree_util.tree_map(
                        lambda a: w * a.astype(jnp.float32), g))
                    if overlap:
                        # sync THIS microbatch's buckets now — the carry
                        # holds already-global sums, and the collective
                        # overlaps the next microbatch's compute
                        flat, ef_c = reduce_flat(flat, plan, axes, n,
                                                 wire, ef_c, fused=fusedq,
                                                 hier=hier)
                    acc = acc + flat
                    if has_stats:
                        s_sum = jax.tree_util.tree_map(
                            lambda a, b: a + w * b.astype(a.dtype),
                            s_sum, stats_mb)
                    m_sum = add_metrics(m_sum, m)
                    return (acc, s_sum, m_sum, ef_c), None

                acc0 = jnp.zeros((plan.total_size,), jnp.float32)
                s0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), stats)
                (flat, s_sum, m_local, ef_l), _ = lax.scan(
                    mb_body, (acc0, s0, zero_metrics(), ef_l),
                    (micro_batches, keys))
                if not overlap:
                    flat, ef_l = reduce_flat(flat, plan, axes, n, wire,
                                             ef_l, fused=fusedq, hier=hier)

            # metric fan-in (the zero1 comment verbatim: 3 scalar psums)
            metrics = jax.tree_util.tree_map(
                lambda v: psum(v, axes), m_local)
            total_w = jnp.maximum(metrics["weight"], 1.0)
            grads = unflatten_tree(flat / total_w, params)

            # replicated update from the synced global-mean gradient — the
            # optimizer must NOT carry shard_axes here (grads are already
            # global; a psum'd clip norm would count every replica n times)
            updates, new_opt = outer.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)

            if has_stats:
                new_stats = jax.tree_util.tree_map(
                    lambda s, old: jnp.where(
                        metrics["weight"] > 0,
                        psum(s, axes) / total_w,
                        old.astype(jnp.float32)).astype(old.dtype),
                    s_sum, stats)
            else:
                new_stats = stats
            out = (new_params, new_opt, new_stats, metrics)
            if use_ef:
                out += (ef_l[None],)
            return out

        in_specs = (rep, rep, rep, batch_specs, rep, rep)
        out_specs = (rep, rep, rep, rep)
        args = [state.params, state.opt_state, state.batch_stats, batch,
                rng, state.step]
        if use_ef:
            in_specs += (ef_spec,)
            out_specs += (ef_spec,)
            args.append(state.grad_sync["ef"])
        stepped = shard_map(body, mesh, in_specs=in_specs,
                            out_specs=out_specs)
        res = stepped(*args)
        new_params, new_opt, new_stats, metrics = res[:4]
        new_gs = {"ef": res[4]} if use_ef else state.grad_sync
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  batch_stats=new_stats, opt_state=new_opt,
                                  grad_sync=new_gs)
        return new_state, metrics

    # -- ZeRO-1 sharded weight update ---------------------------------------

    def _zero1_step(self, state: TrainState, batch, rng):
        """Cross-replica sharded update (Xu et al., PAPERS.md): the whole
        step runs in a shard_map over the batch axes, so the gradient sync
        is an explicit `psum_scatter` (a true reduce-scatter in the compiled
        HLO — half an all-reduce), the optimizer update touches only this
        replica's 1/N flat chunk of params + moments, and one `all_gather`
        rebuilds the replicated parameters. Collective payload per step
        stays ~2x params (all-reduce = reduce-scatter + all-gather), but
        the update compute and moment memory divide by N, and XLA can
        overlap the gather with the next step's forward.

        Semantics vs the replicated path, same batch:
        * deterministic tasks (causal LM, dropout 0): identical up to fp
          reassociation — the parity contract tests/test_zero1.py pins;
        * stochastic tasks: each shard folds its linear shard index into
          the step RNG, so draws are independent across shards but differ
          from the replicated path's single global stream (the grad-accum
          caveat, verbatim);
        * BatchNorm models: each shard normalizes by ITS OWN statistics —
          exactly torch DDP's per-GPU BatchNorm (ref train_ddp.py:305-310
          never syncs BN), where the replicated GSPMD path computes
          global-batch statistics. EMAs stay unbiased: the weighted mean
          of per-shard EMAs equals one EMA update with the weighted-mean
          batch statistics (the grad-accum argument, across space instead
          of time).

        Wire compression (TrainConfig.wire_dtype) composes here: the
        reduce-scatter half runs at bf16 or int8+error-feedback (one
        residual per leaf per replica, parallel/grad_sync.py) — the grads
        compress, the parameter all-gather stays exact. The residual is in
        weight-scaled-gradient units (scatter operands are w-scaled sums).
        "int8_multihop" compresses BOTH halves: the scatter is the same s8
        all-to-all as "int8" (with error feedback), and the param gather
        rides s8 too — each replica quantizes its shard's UPDATE (new
        shard - old shard) per chunk and all replicas add the identical
        dequantized delta to the replicated old params
        (grad_sync.quantized_delta_all_gather: bounded per-step error,
        replica-identical, not fed back — the hop-2 error model).
        "int8_hier" tiers both halves over the slice factorization: the
        scatter is an exact fp32 intra-slice reduce-scatter followed by
        the s8 cross-slice exchange with error feedback
        (grad_sync.hier_psum_scatter), and the param gather rides s8
        UPDATE codes across slices + an exact fp32 intra-slice gather
        (grad_sync.hier_delta_all_gather) — only the slow tier ever
        carries compressed bytes. Shard ownership is FAST-MAJOR
        (HierSpec.hier_axes): chunk j*n_slices+s belongs to (fast j,
        slice s), so the at-rest flat layout shards over
        fast_axes+(slice,) instead of the batch axes.
        """
        mesh, accum, n = self.mesh, self.config.grad_accum, self._zero1_n
        axes = BATCH_AXES
        task = self.task
        wire = self._wire
        hier = self._hier if wire == "int8_hier" else None
        fusedq = self.config.fused_quantize  # tri-state, resolved at trace
        # multihop's scatter half IS the int8 s8 all-to-all (already
        # n-independent); what multihop adds over "int8" here is the
        # compressed param gather below.
        scatter_wire = "int8" if wire == "int8_multihop" else wire
        use_ef = wire in EF_WIRE_DTYPES
        if use_ef and not state.grad_sync:
            raise ValueError(
                f"wire_dtype={wire!r} needs error-feedback buffers — build "
                "the state via Trainer.init_state (TrainState.grad_sync is "
                "empty)")
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))
        outer = state  # static fields (apply_fn/tx) for the inner rebuild

        rep = P()
        batch_specs = jax.tree_util.tree_map(
            lambda x: batch_spec(jnp.ndim(x)), batch)
        opt_specs = dp_flat_specs(
            state.opt_state,
            axes=hier.hier_axes if hier is not None else BATCH_AXES)

        def body(params, opt_state, stats, lbatch, key, step, *maybe_ef):
            inner = outer.replace(step=step, params=params,
                                  batch_stats=stats, opt_state=opt_state)
            idx = lax.axis_index(axes)  # linear replica index over the axes
            # chunk OWNERSHIP index: fast-major under the hier wire (the
            # fast psum_scatter hands fast-rank j chunk j, the slice
            # exchange hands slice s sub-chunk s), batch-linear otherwise
            own = (lax.axis_index(hier.hier_axes) if hier is not None
                   else idx)
            # per-leaf local residuals, (1, padded) -> (padded,)
            ef_l = (jax.tree_util.tree_map(lambda r: r[0], maybe_ef[0])
                    if use_ef else None)
            treedef = jax.tree_util.tree_structure(params)

            def micro_grads(mb, k):
                def loss_fn(p):
                    return task.loss_and_metrics(inner, p, mb, k, train=True)

                return jax.grad(loss_fn, has_aux=True)(params)

            def scatter_tree(gtree, ef_tree, combine=None, into=None):
                """Per-leaf compressed reduce-scatter of the w-scaled grad
                tree: returns (shard tree [combined into `into` via
                `combine` when given], new ef tree)."""
                g_leaves = treedef.flatten_up_to(gtree)
                ef_leaves = (treedef.flatten_up_to(ef_tree) if use_ef
                             else [None] * len(g_leaves))
                into_leaves = (treedef.flatten_up_to(into)
                               if into is not None else [None] * len(g_leaves))
                outs, new_efs = [], []
                for a, r, acc in zip(g_leaves, ef_leaves, into_leaves):
                    if hier is not None:
                        s, nr = hier_psum_scatter(
                            flatten_pad(a.astype(jnp.float32), n), hier,
                            r, fused=fusedq)
                    else:
                        s, nr = compressed_psum_scatter(
                            flatten_pad(a.astype(jnp.float32), n), axes, n,
                            scatter_wire, r, fused=fusedq)
                    outs.append(acc + s if combine else s)
                    new_efs.append(nr)
                return (jax.tree_util.tree_unflatten(treedef, outs),
                        (jax.tree_util.tree_unflatten(treedef, new_efs)
                         if use_ef else None))

            if accum <= 1:
                key = jax.random.fold_in(key, idx)
                g, (m, stats_l) = micro_grads(lbatch, key)
                w = m["weight"]
                g_sum, ef_l = scatter_tree(
                    jax.tree_util.tree_map(lambda a: w * a, g), ef_l)
                s_sum = (jax.tree_util.tree_map(
                    lambda s: w * s.astype(jnp.float32), stats_l)
                    if has_stats else stats)
                m_local = m
            else:
                # grad accumulation INSIDE the sharded step: the scan carry
                # holds w-scaled gradient *shards* ((padded/N,) fp32), so
                # the accumulation buffer is 1/N the replicated path's.
                # Split is over the LOCAL rows; with the local batch
                # divisible by accum, local rows i::accum are exactly the
                # shard's part of global microbatch i (the interleaved
                # global split of the replicated path).
                micro_batches = split_microbatches(lbatch, accum)
                keys = jax.random.split(key, accum)

                def mb_body(carry, xs):
                    g_sum, s_sum, m_sum, ef_c = carry
                    mb, k = xs
                    g, (m, stats_mb) = micro_grads(
                        mb, jax.random.fold_in(k, idx))
                    w = m["weight"]
                    g_sum, ef_c = scatter_tree(
                        jax.tree_util.tree_map(lambda b: w * b, g), ef_c,
                        combine=True, into=g_sum)
                    if has_stats:
                        s_sum = jax.tree_util.tree_map(
                            lambda a, b: a + w * b.astype(a.dtype),
                            s_sum, stats_mb)
                    m_sum = add_metrics(m_sum, m)
                    return (g_sum, s_sum, m_sum, ef_c), None

                g0 = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(
                        (flatten_pad(p, n).size // n,), jnp.float32),
                    params)
                s0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), stats)
                (g_sum, s_sum, m_local, ef_l), _ = lax.scan(
                    mb_body, (g0, s0, zero_metrics(), ef_l),
                    (micro_batches, keys))

            # fan the per-shard metric sums in (the reference's 3 epoch
            # all-reduces, ref :251-253, here 3 scalar psums per step)
            metrics = jax.tree_util.tree_map(
                lambda v: psum(v, axes), m_local)
            total_w = jnp.maximum(metrics["weight"], 1.0)

            def pshard(p):
                flat = flatten_pad(p, n)
                k = flat.size // n
                return lax.dynamic_slice_in_dim(flat, own * k, k)

            p_shards = jax.tree_util.tree_map(pshard, params)
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / total_w).astype(p.dtype), g_sum, p_shards)

            # 1/N of the optimizer update — the whole point of zero1
            updates, new_opt = outer.tx.update(grads, opt_state, p_shards)
            new_p_shards = optax.apply_updates(p_shards, updates)
            if wire == "int8_multihop":
                # compressed param gather: s8 UPDATE codes + one fp32 scale
                # per chunk; every replica adds the identical dequantized
                # delta to the replicated old params, so exact replication
                # is preserved (grad_sync.quantized_delta_all_gather)
                new_params = jax.tree_util.tree_map(
                    lambda s, old, p: quantized_delta_all_gather(
                        s, old, flatten_pad(p, n), axes, fused=fusedq,
                    )[:p.size].reshape(p.shape).astype(p.dtype),
                    new_p_shards, p_shards, params)
            elif hier is not None:
                # two-tier param gather: s8 UPDATE codes + per-chunk fp32
                # scales cross the slices (bounded, replica-identical, not
                # fed back — the multihop hop-2 model), then an EXACT fp32
                # all-gather inside the slice; slice first, fast second,
                # inverting the fast-major chunk ownership
                new_params = jax.tree_util.tree_map(
                    lambda s, old, p: hier_delta_all_gather(
                        s, old, flatten_pad(p, n), hier, fused=fusedq,
                    )[:p.size].reshape(p.shape).astype(p.dtype),
                    new_p_shards, p_shards, params)
            else:
                new_params = jax.tree_util.tree_map(
                    lambda s, p: all_gather(s, axes)[:p.size].reshape(p.shape),
                    new_p_shards, params)

            if has_stats:
                # A fully-padded global batch (weight 0) keeps old stats
                # (grads are a no-op then), mirroring the accum path.
                new_stats = jax.tree_util.tree_map(
                    lambda s, old: jnp.where(
                        metrics["weight"] > 0,
                        psum(s, axes) / total_w,
                        old.astype(jnp.float32)).astype(old.dtype),
                    s_sum, stats)
            else:
                new_stats = stats
            out = (new_params, new_opt, new_stats, metrics)
            if use_ef:
                out += (jax.tree_util.tree_map(lambda r: r[None], ef_l),)
            return out

        in_specs = (rep, opt_specs, rep, batch_specs, rep, rep)
        out_specs = (rep, opt_specs, rep, rep)
        args = [state.params, state.opt_state, state.batch_stats, batch,
                rng, state.step]
        if use_ef:
            in_specs += (P(axes),)
            out_specs += (P(axes),)
            args.append(state.grad_sync["ef"])
        stepped = shard_map(body, mesh, in_specs=in_specs,
                            out_specs=out_specs)
        res = stepped(*args)
        new_params, new_opt, new_stats, metrics = res[:4]
        new_gs = {"ef": res[4]} if use_ef else state.grad_sync
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  batch_stats=new_stats, opt_state=new_opt,
                                  grad_sync=new_gs)
        return new_state, metrics

    # -- explicit full-parameter FSDP ---------------------------------------

    def _fsdp_unflatten(self, flat_params):
        """Model-shaped params from the flat-sharded at-rest layout via
        plain reshape/slice ops — OUTSIDE shard_map (eval, diagnostics)
        GSPMD inserts the gathers; inside the step the per-layer gather
        does it explicitly. Under explicit TP the at-rest layout is
        model-major (per-shard slices concatenated): split leaves
        re-concatenate along their split dim, replicated leaves take
        copy 0 (all copies bit-identical by construction)."""
        if self._fsdp_template is None:
            raise ValueError(
                "fsdp_explicit state has no unflatten template — build "
                "the state via Trainer.init_state (the flat leaves alone "
                "cannot recover the model shapes)")
        if self._tp_n > 1:
            from jax.sharding import NamedSharding

            # Replicate each flat leaf FIRST: jax 0.4.x GSPMD miscompiles
            # the reshape/slice/concat chain on an input whose dim 0 is
            # sharded over a multi-name axis tuple (wrong data movement,
            # found empirically) — an explicit resharding to replicated is
            # handled correctly and is work the unflatten forces anyway.
            rep = NamedSharding(self.mesh, P())
            return jax.tree_util.tree_map(
                lambda f, t, d: tp_unflatten_leaf(
                    lax.with_sharding_constraint(f, rep), t.shape, t.dtype,
                    d, self._tp_n),
                flat_params, self._fsdp_template, self._tp_split_dims)
        return jax.tree_util.tree_map(
            lambda f, t: f[:int(np.prod(t.shape) or 1)]
            .reshape(t.shape).astype(t.dtype),
            flat_params, self._fsdp_template)

    def _fsdp_step(self, state: TrainState, batch, rng):
        """Explicit full-parameter FSDP (SimpleFSDP, PAPERS.md): params and
        moments live flat-sharded 1/N at rest; the step, inside one
        shard_map over the batch axes, (1) rebuilds the full parameters
        with ONE all-gather per layer group — gathers chained one layer
        ahead via `lax.optimization_barrier`, so gather i+1 waits only on
        gather i (not on any compute) and the scheduler can run it under
        layer i's consumption — (2) computes this replica's local
        gradients against the gathered working copy, (3) reduce-scatters
        each layer's gradient straight into the shard layout
        (`compressed_psum_scatter` on the destination-major group row
        stacking), and (4) updates 1/N of params+moments per replica. The
        new param SHARDS are the step's output — nothing gathers back to
        replicated; the next step's forward re-gathers just-in-time.

        Equivalence scope vs the replicated path, same batch: the zero1
        semantics verbatim (the update pipeline is zero1's with the gather
        moved from epilogue to prologue) — fp32 parity at reassociation
        tolerance, per-shard RNG folds, per-shard BatchNorm statistics.
        Wire modes: bf16/int8 compress the scatter only (int8 with
        per-group error feedback; gathers stay exact fp32, like zero1's);
        "int8_multihop" also compresses the param gathers
        (`quantized_shard_all_gather`: bounded, replica-identical
        perturbation of the gathered WORKING copy — the at-rest shards
        stay exact, so the error does not accumulate into the stored
        parameters; convergence pinned, not parity). "int8_hier" tiers
        both wires over the slice factorization: per-layer scatters run
        the exact fp32 intra-slice reduce-scatter + s8 cross-slice
        exchange with error feedback (`grad_sync.hier_psum_scatter`),
        per-layer gathers ride s8 across slices + exact fp32 inside the
        slice (`grad_sync.hier_shard_all_gather`) — the zero1 hier
        composition applied per layer group, with FAST-MAJOR at-rest rows
        (`HierSpec.hier_axes`). Rejected with explicit TP.
        """
        mesh, accum, n = self.mesh, self.config.grad_accum, self._zero1_n
        axes = BATCH_AXES  # the FSDP wire: gathers/scatters ride data only
        tp = self._tp_n
        # explicit TP: the model axis joins the shard_map (megatron psums
        # bind it); the at-rest dim-0 layout is model-major
        axes_all = ((MODEL,) + BATCH_AXES) if tp > 1 else BATCH_AXES
        task, cfg = self.task, self.config
        wire = self._wire
        hier = self._hier if wire == "int8_hier" else None
        fusedq = cfg.fused_quantize  # tri-state, resolved at trace
        scatter_wire = "int8" if wire == "int8_multihop" else wire
        use_ef = wire in EF_WIRE_DTYPES
        plan = self._fsdp_plan
        if plan is None:
            raise ValueError(
                "fsdp_explicit needs the per-layer plan and unflatten "
                "template — build the state via Trainer.init_state")
        if use_ef and not state.grad_sync:
            raise ValueError(
                f"wire_dtype={wire!r} needs error-feedback buffers — build "
                "the state via Trainer.init_state (TrainState.grad_sync is "
                "empty)")
        if use_ef:
            for g in plan.groups:
                got = state.grad_sync["ef"][g.name].shape[-1]
                # hier: one slow-tier residual per replica per group —
                # the padded group row divided by the intra-slice degree
                expect = (n * g.row_size
                          // (hier.n_inner if hier is not None else 1))
                if got != expect:
                    raise ValueError(
                        f"error-feedback residual for layer group "
                        f"{g.name!r} has {got} elements, expected {expect} "
                        "— the state was built for a different model/mesh; "
                        "rebuild via Trainer.init_state")
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))
        if tp > 1:
            # the body computes with the TP-local model (megatron
            # column/row split, model-axis psums via the custom_vjp f/g
            # operators in parallel/collectives.py)
            outer = state.replace(apply_fn=self._tp_model.apply)
        else:
            outer = state  # static fields (apply_fn/tx) for inner rebuild
        local_template = self._fsdp_local_template
        template_leaves = jax.tree_util.tree_leaves(local_template)
        treedef = jax.tree_util.tree_structure(local_template)
        leaf_sizes = self._fsdp_sizes  # host-precomputed (init_state)

        rep = P()
        batch_specs = jax.tree_util.tree_map(
            lambda x: batch_spec(jnp.ndim(x)), batch)
        # hier wire: at-rest rows bind FAST-MAJOR (the scatter's chunk
        # ownership — see _zero1_step), so dim 0 shards over
        # fast_axes+(slice,) instead of the batch-axis order
        rest_axes = hier.hier_axes if hier is not None else axes_all
        param_specs = dp_flat_specs(state.params, axes=rest_axes)
        opt_specs = dp_flat_specs(state.opt_state, axes=rest_axes)

        def body(p_shards, opt_state, stats, lbatch, key, step, *maybe_ef):
            idx = lax.axis_index(axes)
            # per-group residuals, (1, G) local row -> (G,)
            ef_l = ({name: r[0] for name, r in maybe_ef[0].items()}
                    if use_ef else None)
            shard_leaves = treedef.flatten_up_to(p_shards)

            # -- per-layer just-in-time gather (the prologue) -------------
            full = [None] * len(template_leaves)
            prev = None
            for g in plan.groups:
                row = (jnp.concatenate([shard_leaves[s].astype(jnp.float32)
                                        for s in g.leaf_slots])
                       if len(g.leaf_slots) > 1
                       else shard_leaves[g.leaf_slots[0]]
                       .astype(jnp.float32))
                if prev is not None:
                    # prefetch chain: gather i+1 depends on gather i's
                    # COMPLETION only — never on layer i's compute — so
                    # the latency-hiding scheduler can issue it while
                    # layer i is being consumed, one layer ahead
                    row = lax.optimization_barrier((row, prev))[0]
                if wire == "int8_multihop":
                    flatg = quantized_shard_all_gather(row, axes,
                                                       fused=fusedq)
                elif hier is not None:
                    # s8 across slices, exact fp32 inside — slice first,
                    # fast second, inverting fast-major row ownership
                    flatg = hier_shard_all_gather(row, hier, fused=fusedq)
                else:
                    flatg = all_gather(row, axes)
                prev = flatg
                mat = flatg.reshape(n, g.row_size)
                off = 0
                for s, c in zip(g.leaf_slots, g.chunk_sizes):
                    t = template_leaves[s]
                    full[s] = (mat[:, off:off + c].reshape(-1)
                               [:leaf_sizes[s]]
                               .reshape(t.shape).astype(t.dtype))
                    off += c
            params = jax.tree_util.tree_unflatten(treedef, full)
            inner = outer.replace(step=step, params=params,
                                  batch_stats=stats, opt_state=opt_state)

            def micro_grads(mb, k):
                def loss_fn(p):
                    return task.loss_and_metrics(inner, p, mb, k, train=True)

                return jax.grad(loss_fn, has_aux=True)(params)

            def scatter_layers(gtree, ef_tree, into=None):
                """Per-layer compressed reduce-scatter of the w-scaled
                grad tree straight into the shard layout: returns
                (per-leaf chunk tree [+= into], new per-group ef dict)."""
                g_leaves = treedef.flatten_up_to(gtree)
                into_leaves = (treedef.flatten_up_to(into)
                               if into is not None else None)
                outs = [None] * len(g_leaves)
                new_ef = {}
                for g in plan.groups:
                    # destination-major stacking: row j = concat of every
                    # member leaf's chunk j, so the scatter lands each
                    # leaf's chunk on its owner in one collective
                    parts = [
                        flatten_pad(g_leaves[s].astype(jnp.float32), n)
                        .reshape(n, -1)
                        for s in g.leaf_slots]
                    v = (jnp.concatenate(parts, axis=1)
                         if len(parts) > 1 else parts[0]).reshape(-1)
                    r = ef_tree[g.name] if use_ef else None
                    if hier is not None:
                        s_out, nr = hier_psum_scatter(v, hier, r,
                                                      fused=fusedq)
                    else:
                        s_out, nr = compressed_psum_scatter(
                            v, axes, n, scatter_wire, r, fused=fusedq)
                    off = 0
                    for s, c in zip(g.leaf_slots, g.chunk_sizes):
                        chunk = lax.slice_in_dim(s_out, off, off + c)
                        outs[s] = (into_leaves[s] + chunk
                                   if into is not None else chunk)
                        off += c
                    if use_ef:
                        new_ef[g.name] = nr
                return (jax.tree_util.tree_unflatten(treedef, outs),
                        new_ef if use_ef else None)

            if accum <= 1:
                key = jax.random.fold_in(key, idx)
                g, (m, stats_l) = micro_grads(lbatch, key)
                w = m["weight"]
                g_sum, ef_l = scatter_layers(
                    jax.tree_util.tree_map(lambda a: w * a, g), ef_l)
                s_sum = (jax.tree_util.tree_map(
                    lambda s: w * s.astype(jnp.float32), stats_l)
                    if has_stats else stats)
                m_local = m
            else:
                # zero1's in-scan accumulation verbatim: the carry holds
                # per-leaf gradient SHARDS (1/N the replicated buffer),
                # and each microbatch's scatter overlaps the next
                # microbatch's compute
                micro_batches = split_microbatches(lbatch, accum)
                keys = jax.random.split(key, accum)

                def mb_body(carry, xs):
                    g_sum, s_sum, m_sum, ef_c = carry
                    mb, k = xs
                    g, (m, stats_mb) = micro_grads(
                        mb, jax.random.fold_in(k, idx))
                    w = m["weight"]
                    g_sum, ef_c = scatter_layers(
                        jax.tree_util.tree_map(lambda b: w * b, g), ef_c,
                        into=g_sum)
                    if has_stats:
                        s_sum = jax.tree_util.tree_map(
                            lambda a, b: a + w * b.astype(a.dtype),
                            s_sum, stats_mb)
                    m_sum = add_metrics(m_sum, m)
                    return (g_sum, s_sum, m_sum, ef_c), None

                g0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), p_shards)
                s0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), stats)
                (g_sum, s_sum, m_local, ef_l), _ = lax.scan(
                    mb_body, (g0, s0, zero_metrics(), ef_l),
                    (micro_batches, keys))

            metrics = jax.tree_util.tree_map(
                lambda v: psum(v, axes), m_local)
            total_w = jnp.maximum(metrics["weight"], 1.0)
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / total_w).astype(p.dtype), g_sum, p_shards)

            # 1/N of the optimizer update, on the at-rest shards — the
            # zero1 core, minus its epilogue gather: the new shards ARE
            # the output layout
            updates, new_opt = outer.tx.update(grads, opt_state, p_shards)
            new_p_shards = optax.apply_updates(p_shards, updates)

            if has_stats:
                new_stats = jax.tree_util.tree_map(
                    lambda s, old: jnp.where(
                        metrics["weight"] > 0,
                        psum(s, axes) / total_w,
                        old.astype(jnp.float32)).astype(old.dtype),
                    s_sum, stats)
            else:
                new_stats = stats
            out = (new_p_shards, new_opt, new_stats, metrics)
            if use_ef:
                out += ({name: r[None] for name, r in ef_l.items()},)
            return out

        in_specs = (param_specs, opt_specs, rep, batch_specs, rep, rep)
        out_specs = (param_specs, opt_specs, rep, rep)
        args = [state.params, state.opt_state, state.batch_stats, batch,
                rng, state.step]
        if use_ef:
            ef_specs = jax.tree_util.tree_map(lambda _: P(axes_all),
                                              state.grad_sync["ef"])
            in_specs += (ef_specs,)
            out_specs += (ef_specs,)
            args.append(state.grad_sync["ef"])
        stepped = shard_map(body, mesh, in_specs=in_specs,
                            out_specs=out_specs)
        res = stepped(*args)
        new_params, new_opt, new_stats, metrics = res[:4]
        new_gs = {"ef": res[4]} if use_ef else state.grad_sync
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  batch_stats=new_stats, opt_state=new_opt,
                                  grad_sync=new_gs)
        return new_state, metrics

    def _eval_step_impl(self, state: TrainState, batch):
        rng = jax.random.PRNGKey(0)  # unused: eval has no augmentation (ref :98-101)
        params = (self._fsdp_unflatten(state.params) if self._fsdp
                  else state.params)
        _, (metrics, _) = self.task.loss_and_metrics(
            state, params, batch, rng, train=False)
        return metrics

    # -- state construction ------------------------------------------------

    def init_state(self, model, sample_input, tx, init_rng: jax.Array) -> TrainState:
        """Initialize params, then place them on the mesh per the partition
        rules (replicated by default — the DDP broadcast moment, ref :305-310).
        `sample_input` is a (1, ...) array of the model's input shape/dtype
        (float images or int32 token ids)."""
        from ..parallel.mesh import batch_shard_count

        x = jnp.asarray(sample_input)
        # Models containing shard_map'd ops (ring attention) need the traced
        # batch dim divisible by the mesh batch axes; tile the sample up.
        n_shards = batch_shard_count(self.mesh)
        if x.shape[0] % n_shards:
            x = jnp.tile(x, (n_shards,) + (1,) * (x.ndim - 1))
        variables = model.init(init_rng, x, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        # int8 gradient wires: zero-initialized error-feedback residuals,
        # attached AFTER mesh placement (they carry their own per-replica
        # sharding; the rules would replicate them). zero1 feeds back on
        # its scatter half under both int8 forms ("int8_multihop" scatters
        # via the same s8 all-to-all; only its param gather differs).
        use_ef = (self._wire in EF_WIRE_DTYPES
                  and (self._zero1 or self._grad_sync or self._fsdp))
        hier = self._hier if self._wire == "int8_hier" else None
        n_inner = hier.n_inner if hier is not None else 1
        if self._fsdp:
            # Explicit FSDP: params AND moments are born in the zero1 flat
            # padded layout, 1/N per replica at rest — the at-rest memory
            # division that is the mode's point. The model-shaped template
            # (shapes/dtypes only, host-side) is what the step's per-layer
            # gather unflattens against. With a model axis (explicit TP,
            # ISSUE 13) the layout is model-major: each leaf's TP-local
            # slice (or full copy, for model-replicated leaves) flat-padded
            # per model shard — 1/(N*M) at rest for every TP-split tensor.
            from .optim import zero1_opt_state

            n, tp = self._zero1_n, self._tp_n
            self._fsdp_template = jax.tree_util.tree_map(
                lambda p: jax.ShapeDtypeStruct(jnp.shape(p),
                                               jnp.result_type(p)), params)
            if tp > 1:
                import dataclasses as _dc

                field_names = {f.name for f in _dc.fields(type(model))}
                if not {"tp_size", "tp_axis"} <= field_names:
                    raise ValueError(
                        f"mesh has model={tp} under fsdp_explicit, but "
                        f"{type(model).__name__} has no explicit-TP form "
                        "(tp_size/tp_axis fields) — gpt2_* models support "
                        "explicit TP; others need a 1-D mesh or the "
                        "implicit GSPMD path")
                heads = getattr(model, "num_heads", None)
                if heads is not None and heads % tp:
                    # the TP module raises the same at trace time; failing
                    # here keeps the error at state construction
                    raise ValueError(
                        f"num_heads={heads} not divisible by the mesh's "
                        f"model={tp} — explicit TP splits attention by "
                        "whole heads")
                rules = self.rules
                if rules is None and hasattr(type(model), "partition_rules"):
                    rules = type(model).partition_rules()
                if rules is None:
                    raise ValueError(
                        "explicit TP derives its layout from the model's "
                        "partition rules (tp_fsdp_rules) — pass rules= or "
                        "give the model a partition_rules() classmethod")
                self._tp_split_dims = tp_split_dims(self._fsdp_template,
                                                    rules, tp)
                self._tp_model = model.clone(tp_size=tp, tp_axis=MODEL)
                local_template = tp_local_struct(self._fsdp_template,
                                                 self._tp_split_dims, tp)
            else:
                local_template = self._fsdp_template
            self._fsdp_local_template = local_template
            # host-side leaf sizes (tree_leaves order) for the in-step
            # unflatten slicing — precomputed here so the traced step does
            # no int() shape math (the no-host-sync-in-step lint's scope)
            self._fsdp_sizes = tuple(
                int(np.prod(t.shape) or 1) for t in
                jax.tree_util.tree_leaves(local_template))
            self._fsdp_plan = build_layer_plan(local_template, n)
            if tp > 1:
                axes_all = (MODEL,) + BATCH_AXES
                split_dims = self._tp_split_dims
                opt_state = zero1_opt_state(
                    tx, params, self.mesh,
                    flatten_tree_fn=lambda p: jax.tree_util.tree_map(
                        lambda x, d: tp_flat_leaf(x, d, tp, n),
                        p, split_dims),
                    axes=axes_all)
                flat_params = fsdp_tp_flat_params(
                    params, self.mesh, n, tp, split_dims, axes_all)
            else:
                # hier wire: moments born in the fast-major row binding
                # the step's specs use (params reshard once, first step)
                opt_state = zero1_opt_state(
                    tx, params, self.mesh,
                    axes=hier.hier_axes if hier is not None else None)
                flat_params = fsdp_flat_params(params, self.mesh, n)
            state = TrainState.create(
                apply_fn=model.apply, params=params, tx=tx,
                batch_stats=batch_stats, opt_state=opt_state)
            placed = shard_pytree(state.replace(params={}, opt_state={}),
                                  self.mesh, None)
            placed = placed.replace(params=flat_params, opt_state=opt_state)
            if use_ef:
                placed = placed.replace(grad_sync=ef_state_fsdp(
                    local_template, self.mesh, n, model_n=tp,
                    n_inner=n_inner))
            return placed
        if self._zero1 or self._zero1_gspmd:
            # Params stay replicated (the DDP layout — zero1 shards only
            # the UPDATE); the optimizer state is born flat-padded-sharded
            # over the batch axes, 1/N per replica.
            from .optim import zero1_opt_state

            opt_state = zero1_opt_state(
                tx, params, self.mesh,
                axes=hier.hier_axes if (hier is not None and self._zero1)
                else None)
            state = TrainState.create(
                apply_fn=model.apply, params=params, tx=tx,
                batch_stats=batch_stats, opt_state=opt_state)
            placed = shard_pytree(state.replace(opt_state={}), self.mesh,
                                  self.rules)
            placed = placed.replace(opt_state=opt_state)
            if use_ef:
                placed = placed.replace(grad_sync=ef_state_zero1(
                    params, self.mesh, self._zero1_n, n_inner=n_inner))
            return placed
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, batch_stats=batch_stats)
        placed = shard_pytree(state, self.mesh, self.rules)
        if use_ef:
            placed = placed.replace(grad_sync=ef_state_bucketed(
                params, self.mesh, self._zero1_n,
                bucket_cap_mb=self.config.bucket_cap_mb,
                wire_dtype=self._wire,
                n_slices=hier.n_slices if hier is not None else 1))
        return placed

    # -- epoch loops -------------------------------------------------------

    def train_epoch(
        self,
        state: TrainState,
        batches: Iterable,
        epoch: int,
        steps_per_epoch: int,
        samples_per_step: Optional[Sequence[int]] = None,
        step_hook: Optional[Any] = None,
        start_step: int = 0,
        stop_fn: Optional[Any] = None,
        fault_hook: Optional[Any] = None,
    ) -> Tuple[TrainState, float, float, float, int]:
        """One epoch (maps train_one_epoch, ref :170-263). Returns
        (state, global mean loss, global top-1 %, epoch wall seconds,
        steps executed). `step_hook(step_index)` fires before each step
        (profiler windows). `start_step` labels a mid-epoch resume (the
        caller hands an already-offset batch iterator; the per-step RNG is
        folded from state.step, so the restored trajectory is identical).
        `stop_fn()` checked after every step: True breaks the loop — the
        step-granular preemption point (steps executed < full epoch).
        `fault_hook(step_index)` is the resilience/ step fence: it fires
        BEFORE the step executes (so a raise there means the optimizer
        never applied the step — the restart supervisor's restore point)
        and is None on every un-supervised run (the hot path pays
        nothing).

        Telemetry (host-side only — nothing here touches traced code, and
        the ``telemetry-emit-outside-traced`` AST rule keeps it that way):
        per-step ``data_wait`` (time blocked on the loader iterator) and
        ``step_dispatch`` (time inside the jitted-call dispatch — with
        donation backpressure this tracks device step time once the
        pipeline fills) spans, a ``device_sync`` span around the epoch's
        one block_until_ready, and epoch counters (``epoch_time_s``,
        ``steps``, ``samples``) — the totals ``telemetry summary`` checks
        its split against. ``self.watchdog`` (an AnomalyWatchdog) is fed
        the same timings plus print-boundary losses; with its abort hook
        on, a detection raises AnomalyAbort — under the Supervisor, a
        restartable step failure like any other."""
        cfg = self.config
        epoch_key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), epoch)

        epoch_metrics = zero_metrics()
        # perf_counter, not time.time(): an NTP step mid-epoch would
        # corrupt the CSV's epoch_time_seconds (the ThroughputMeter got
        # the same fix)
        t_epoch = time.perf_counter()
        meter = ThroughputMeter()
        steps_done = 0
        epoch_samples = 0
        watchdog = self.watchdog

        it = iter(batches)
        i = 0
        while True:
            t_wait = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            data_wait_s = time.perf_counter() - t_wait
            telemetry.span_event("data_wait", data_wait_s,
                                 step=start_step + i, epoch=epoch)
            if fault_hook is not None:
                fault_hook(i)
            if step_hook is not None:
                # the GLOBAL step label (start_step + i) — the same
                # numbering the spans, the watchdog, and the straggler
                # table use, so an armed capture window's step range can
                # be lined up against a flagged step on a mid-epoch
                # resume (the profiler's static window triggers on its
                # own call count, not this label)
                step_hook(start_step + i)
            t_disp = time.perf_counter()
            state, metrics = self._train_step(state, batch, epoch_key)
            dispatch_s = time.perf_counter() - t_disp
            telemetry.span_event("step_dispatch", dispatch_s,
                                 step=start_step + i, epoch=epoch)
            if watchdog is not None:
                watchdog.observe_step(start_step + i,
                                      data_wait_s + dispatch_s,
                                      data_wait_s=data_wait_s)
            epoch_metrics = add_metrics(epoch_metrics, metrics)
            steps_done = i + 1
            # sample count is host-known (sampler math), no device fetch:
            if samples_per_step is not None:
                n = samples_per_step[min(i, len(samples_per_step) - 1)]
                meter.update(n)
                epoch_samples += n

            if (i + 1) % cfg.print_freq == 0:
                # Host fetch happens only here (print boundary), mirroring the
                # reference cadence (ref :229-243) without its per-step syncs.
                # Like the reference, the printed loss/acc are the epoch
                # running averages (ref :230-231).
                avg_loss, avg_acc = summarize(epoch_metrics)
                if watchdog is not None:
                    # the loop's only host fetch — the non-finite-loss
                    # detector rides it instead of adding a sync
                    watchdog.observe_loss(start_step + i, avg_loss)
                rate = meter.rate()
                mfu = ""
                if self._flops_per_sample and self._peak_flops_total:
                    mfu_pct = (100.0 * rate * self._flops_per_sample
                               / self._peak_flops_total)
                    mfu = f"  MFU: {mfu_pct:.1f}%"
                log_main(
                    f"Epoch [{epoch + 1}] "
                    f"Step [{start_step + i + 1}/{steps_per_epoch}] "
                    f"Loss: {avg_loss:.4f}  "
                    f"Acc: {avg_acc:.2f}%  "
                    f"Throughput: {rate:.2f} samples/s (global)" + mfu
                )
                meter.reset()

            if stop_fn is not None and stop_fn():
                break
            i += 1

        # Epoch totals: weighted sums are already global (the batch was the
        # global batch) — the reference needs 3 all-reduces here (ref :251-253);
        # we need none.
        with telemetry.span("device_sync", epoch=epoch):
            jax.block_until_ready(epoch_metrics["weight"])
        epoch_time = time.perf_counter() - t_epoch
        telemetry.counter("epoch_time_s", epoch_time, epoch=epoch)
        telemetry.counter("steps", steps_done, epoch=epoch)
        if epoch_samples:
            telemetry.counter("samples", epoch_samples, epoch=epoch)
        loss, acc = summarize(epoch_metrics)
        return state, loss, acc, epoch_time, steps_done

    def evaluate(self, state: TrainState, batches: Iterable) -> Tuple[float, float]:
        """Sharded validation (maps validate, ref :266-300)."""
        with telemetry.span("eval"):
            totals = zero_metrics()
            for batch in batches:
                totals = add_metrics(totals, self._eval_step(state, batch))
            return summarize(totals)
