"""Trainer: compiled train/eval steps + epoch loops.

TPU-native re-design of train_one_epoch/validate
(/root/reference/train_ddp.py:170-300). The reference's per-batch body —
H2D copy, zero_grad, autocast forward, backward with DDP bucketed all-reduce,
scaler step (ref :198-214) — becomes ONE jitted function ``state, batch ->
state, metrics``; gradient sync is implied by the batch being sharded over the
mesh's data axes, and bf16 replaces autocast+GradScaler (no loss scaling
needed; SURVEY.md §2b).

Improvements over the reference, by design:
* metrics accumulate on device; the host fetches only at print boundaries
  (the ref's per-step ``.item()`` is a sync bottleneck, ref :217/:220);
* validation is sharded over the mesh instead of replicated per rank
  (ref :266-300 evaluates the full set on every rank; SURVEY.md §3.3);
* the last partial batch is padded+masked, so one XLA program serves every
  step (ref's drop_last=False short batch would recompile, SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import all_gather, psum, psum_scatter, shard_map
from ..parallel.mesh import BATCH_AXES, batch_shard_count
from ..parallel.sharding import (
    PartitionRules, batch_spec, dp_flat_specs, flatten_pad, shard_pytree,
)
from ..utils.logging import log_main
from ..utils.metrics import ThroughputMeter
from .tasks import Task, add_metrics, summarize, zero_metrics
from .train_state import TrainState


@dataclasses.dataclass
class TrainConfig:
    """Loop knobs (CLI-facing subset mirrors ref defaults, train_ddp.py:19-46)."""

    per_device_batch: int = 128
    print_freq: int = 50
    seed: int = 42
    bf16: bool = False  # the --amp equivalent (ref :36-37)
    donate_state: bool = True
    # Gradient accumulation: split each global batch into this many
    # microbatches inside the jitted step (lax.scan), summing weighted
    # gradients — reference-scale global batches on few chips at
    # 1/grad_accum the activation memory. 1 = off.
    grad_accum: int = 1
    # ZeRO-1 cross-replica weight-update sharding (Xu et al., PAPERS.md):
    # gradients reduce-scatter over the data-parallel axes instead of
    # all-reducing, each replica updates 1/N of the (flattened) parameters
    # with 1/N of the optimizer state, and the new parameters all-gather
    # back to replicated — optimizer compute and moment memory divided by
    # the DP degree. Off = the replicated (DDP-equivalent) update. No-op on
    # a single batch shard (the collectives' passthrough convention).
    zero1: bool = False


class Trainer:
    """Owns the compiled steps for one (model task, mesh) pair."""

    def __init__(
        self,
        task: Task,
        mesh: Mesh,
        config: TrainConfig,
        rules: Optional[PartitionRules] = None,
    ):
        self.task = task
        self.mesh = mesh
        self.config = config
        self.rules = rules
        # optional MFU reference (set_mfu_reference): when present, the
        # throughput print lines also report model-FLOPs utilization
        self._flops_per_sample: Optional[float] = None
        self._peak_flops_total: Optional[float] = None

        self._zero1_n = batch_shard_count(mesh)
        self._zero1 = bool(config.zero1) and self._zero1_n > 1
        if config.zero1:
            bad = sorted(a for a, s in mesh.shape.items()
                         if s > 1 and a not in BATCH_AXES)
            if bad:
                raise ValueError(
                    f"zero1 shards the weight update over the data-parallel "
                    f"axes {BATCH_AXES}; mesh axes {bad} > 1 need the "
                    "replicated update path (TP/SP/PP/EP collectives are "
                    "per-layer, not per-update)")
            if rules is not None:
                conflict = sorted(
                    rules.axes_used()
                    & {a for a in BATCH_AXES if mesh.shape[a] > 1})
                if conflict:
                    raise ValueError(
                        f"zero1 assumes replicated parameters, but the "
                        f"partition rules shard params over {conflict} — "
                        "use either zero1 (optimizer-state sharding) or "
                        "fsdp parameter sharding on this mesh, not both")
            if not self._zero1:
                log_main("NOTE: zero1 requested on a single batch shard — "
                         "running the replicated update (identity "
                         "passthrough, like single-process DDP)")

        donate = (0,) if config.donate_state else ()
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=donate)
        self._eval_step = jax.jit(self._eval_step_impl)

    def set_mfu_reference(self, flops_per_sample: float,
                          peak_flops_total: float) -> None:
        """Enable MFU in the step log: `flops_per_sample` is the analytic
        train-step cost of ONE sample (experiments/flops.py),
        `peak_flops_total` the summed peak FLOP/s of the mesh's devices.
        The reference's meter stops at samples/s (train_ddp.py:224-243);
        MFU is the same number made comparable across hardware."""
        self._flops_per_sample = flops_per_sample
        self._peak_flops_total = peak_flops_total

    # -- compiled bodies ---------------------------------------------------

    def _train_step_impl(self, state: TrainState, batch, epoch_key):
        rng = jax.random.fold_in(epoch_key, state.step)
        accum = self.config.grad_accum

        if self._zero1:
            return self._zero1_step(state, batch, rng)

        if accum <= 1:
            def loss_fn(params):
                return self.task.loss_and_metrics(state, params, batch, rng,
                                                  train=True)

            grads, (metrics, new_stats) = jax.grad(
                loss_fn, has_aux=True)(state.params)
            # No explicit all-reduce: grads of a loss over the data-sharded
            # global batch are already the synchronized gradients (the DDP
            # reducer's job, ref :305-310, done by XLA layout propagation).
            new_state = state.apply_gradients(grads, batch_stats=new_stats)
            return new_state, metrics

        # -- gradient accumulation ----------------------------------------
        # The task loss is the weighted MEAN over its (micro)batch, so the
        # global-batch gradient is the weight-proportional combination:
        #   d(global mean)/dθ = Σ_i (w_i / W) · d(mean_i)/dθ.
        # We accumulate w_i-scaled microbatch grads in the scan carry and
        # divide by W once.
        #
        # Equivalence scope (vs the unaccumulated step on the same batch):
        # EXACT (up to fp reassociation) for deterministic per-sample losses
        # (causal LM with dropout 0 — the parity test). NOT bit-equal for:
        # * stochastic tasks (MLM masking, dropout, augmentation): each
        #   microbatch gets its own fold of the step RNG, so different
        #   positions mask — still an unbiased step, just a different draw;
        # * batch-statistic auxiliary losses (MoE load balancing): the
        #   accumulated objective is the w_i/W-weighted combination of
        #   per-microbatch aux losses, whereas grad_accum=1 computes routing
        #   statistics over the full batch. Inherent to accumulation, not a
        #   bug — per-microbatch balancing is itself a valid regularizer.
        # * BatchNorm models (ResNets): each microbatch normalizes by ITS
        #   OWN statistics (exactly torch's behavior under accumulation), so
        #   grads differ from the full-batch step by the (small, O(1/|mb|))
        #   between-microbatch variance. Running stats stay unbiased: every
        #   microbatch EMA starts from the SAME pre-step stats (state is
        #   closed over, not carried), so the weighted mean of the per-
        #   microbatch EMAs equals ONE EMA update with the weighted-mean
        #   batch statistics — not `accum` compounding updates.
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))

        def split(x):
            if x.ndim == 0:
                return jnp.broadcast_to(x, (accum,))
            if x.shape[0] % accum:
                raise ValueError(
                    f"global batch {x.shape[0]} not divisible by "
                    f"grad_accum={accum}")
            # INTERLEAVED split (microbatch i = rows i::accum), not
            # contiguous blocks: the batch is sharded over the data axes by
            # contiguous row ranges, so a contiguous microbatch would live
            # on 1/accum of the devices and every scan step would reshard.
            # Strided microbatches stay evenly spread over all shards.
            return x.reshape(x.shape[0] // accum, accum,
                             *x.shape[1:]).swapaxes(0, 1)

        micro_batches = jax.tree_util.tree_map(split, batch)

        def micro_grads(mb, key):
            def loss_fn(params):
                return self.task.loss_and_metrics(state, params, mb, key,
                                                  train=True)

            return jax.grad(loss_fn, has_aux=True)(state.params)

        def body(carry, xs):
            g_sum, s_sum, m_sum = carry
            mb, key = xs
            g, (m, new_stats) = micro_grads(mb, key)
            w = m["weight"]
            g_sum = jax.tree_util.tree_map(
                lambda a, b: a + w * b.astype(a.dtype), g_sum, g)
            if has_stats:
                s_sum = jax.tree_util.tree_map(
                    lambda a, b: a + w * b.astype(a.dtype), s_sum, new_stats)
            m_sum = add_metrics(m_sum, m)
            return (g_sum, s_sum, m_sum), None

        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
        s0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, jnp.float32), state.batch_stats)
        keys = jax.random.split(rng, accum)
        (g_sum, s_sum, metrics), _ = jax.lax.scan(
            body, (g0, s0, zero_metrics()), (micro_batches, keys))
        total_w = jnp.maximum(metrics["weight"], 1.0)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / total_w).astype(p.dtype), g_sum, state.params)
        if has_stats:
            # A fully-padded global batch (weight 0) must keep the old
            # stats, not zero them (grads are already a no-op then).
            new_stats = jax.tree_util.tree_map(
                lambda s, old: jnp.where(metrics["weight"] > 0, s / total_w,
                                         old.astype(jnp.float32)
                                         ).astype(old.dtype),
                s_sum, state.batch_stats)
        else:
            new_stats = state.batch_stats
        new_state = state.apply_gradients(grads, batch_stats=new_stats)
        return new_state, metrics

    # -- ZeRO-1 sharded weight update ---------------------------------------

    def _zero1_step(self, state: TrainState, batch, rng):
        """Cross-replica sharded update (Xu et al., PAPERS.md): the whole
        step runs in a shard_map over the batch axes, so the gradient sync
        is an explicit `psum_scatter` (a true reduce-scatter in the compiled
        HLO — half an all-reduce), the optimizer update touches only this
        replica's 1/N flat chunk of params + moments, and one `all_gather`
        rebuilds the replicated parameters. Collective payload per step
        stays ~2x params (all-reduce = reduce-scatter + all-gather), but
        the update compute and moment memory divide by N, and XLA can
        overlap the gather with the next step's forward.

        Semantics vs the replicated path, same batch:
        * deterministic tasks (causal LM, dropout 0): identical up to fp
          reassociation — the parity contract tests/test_zero1.py pins;
        * stochastic tasks: each shard folds its linear shard index into
          the step RNG, so draws are independent across shards but differ
          from the replicated path's single global stream (the grad-accum
          caveat, verbatim);
        * BatchNorm models: each shard normalizes by ITS OWN statistics —
          exactly torch DDP's per-GPU BatchNorm (ref train_ddp.py:305-310
          never syncs BN), where the replicated GSPMD path computes
          global-batch statistics. EMAs stay unbiased: the weighted mean
          of per-shard EMAs equals one EMA update with the weighted-mean
          batch statistics (the grad-accum argument, across space instead
          of time).
        """
        mesh, accum, n = self.mesh, self.config.grad_accum, self._zero1_n
        axes = BATCH_AXES
        task = self.task
        has_stats = bool(jax.tree_util.tree_leaves(state.batch_stats))
        outer = state  # static fields (apply_fn/tx) for the inner rebuild

        rep = P()
        batch_specs = jax.tree_util.tree_map(
            lambda x: batch_spec(jnp.ndim(x)), batch)
        opt_specs = dp_flat_specs(state.opt_state)

        def body(params, opt_state, stats, lbatch, key, step):
            inner = outer.replace(step=step, params=params,
                                  batch_stats=stats, opt_state=opt_state)
            idx = lax.axis_index(axes)  # linear replica index over the axes

            def micro_grads(mb, k):
                def loss_fn(p):
                    return task.loss_and_metrics(inner, p, mb, k, train=True)

                return jax.grad(loss_fn, has_aux=True)(params)

            def scatter(a):
                # this replica's 1/N chunk of the cross-replica gradient sum
                return psum_scatter(flatten_pad(a, n), axes)

            if accum <= 1:
                key = jax.random.fold_in(key, idx)
                g, (m, stats_l) = micro_grads(lbatch, key)
                w = m["weight"]
                g_sum = jax.tree_util.tree_map(
                    lambda a: scatter(w * a.astype(jnp.float32)), g)
                s_sum = (jax.tree_util.tree_map(
                    lambda s: w * s.astype(jnp.float32), stats_l)
                    if has_stats else stats)
                m_local = m
            else:
                # grad accumulation INSIDE the sharded step: the scan carry
                # holds w-scaled gradient *shards* ((padded/N,) fp32), so
                # the accumulation buffer is 1/N the replicated path's.
                # Split is over the LOCAL rows; with the local batch
                # divisible by accum, local rows i::accum are exactly the
                # shard's part of global microbatch i (the interleaved
                # global split of the replicated path).
                def split(x):
                    if x.ndim == 0:
                        return jnp.broadcast_to(x, (accum,))
                    if x.shape[0] % accum:
                        raise ValueError(
                            f"per-shard batch {x.shape[0]} not divisible "
                            f"by grad_accum={accum}")
                    return x.reshape(x.shape[0] // accum, accum,
                                     *x.shape[1:]).swapaxes(0, 1)

                micro_batches = jax.tree_util.tree_map(split, lbatch)
                keys = jax.random.split(key, accum)

                def mb_body(carry, xs):
                    g_sum, s_sum, m_sum = carry
                    mb, k = xs
                    g, (m, stats_mb) = micro_grads(
                        mb, jax.random.fold_in(k, idx))
                    w = m["weight"]
                    g_sum = jax.tree_util.tree_map(
                        lambda a, b: a + scatter(w * b.astype(a.dtype)),
                        g_sum, g)
                    if has_stats:
                        s_sum = jax.tree_util.tree_map(
                            lambda a, b: a + w * b.astype(a.dtype),
                            s_sum, stats_mb)
                    m_sum = add_metrics(m_sum, m)
                    return (g_sum, s_sum, m_sum), None

                g0 = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(
                        (flatten_pad(p, n).size // n,), jnp.float32),
                    params)
                s0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), stats)
                (g_sum, s_sum, m_local), _ = lax.scan(
                    mb_body, (g0, s0, zero_metrics()),
                    (micro_batches, keys))

            # fan the per-shard metric sums in (the reference's 3 epoch
            # all-reduces, ref :251-253, here 3 scalar psums per step)
            metrics = jax.tree_util.tree_map(
                lambda v: psum(v, axes), m_local)
            total_w = jnp.maximum(metrics["weight"], 1.0)

            def pshard(p):
                flat = flatten_pad(p, n)
                k = flat.size // n
                return lax.dynamic_slice_in_dim(flat, idx * k, k)

            p_shards = jax.tree_util.tree_map(pshard, params)
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / total_w).astype(p.dtype), g_sum, p_shards)

            # 1/N of the optimizer update — the whole point of zero1
            updates, new_opt = outer.tx.update(grads, opt_state, p_shards)
            new_p_shards = optax.apply_updates(p_shards, updates)
            new_params = jax.tree_util.tree_map(
                lambda s, p: all_gather(s, axes)[:p.size].reshape(p.shape),
                new_p_shards, params)

            if has_stats:
                # A fully-padded global batch (weight 0) keeps old stats
                # (grads are a no-op then), mirroring the accum path.
                new_stats = jax.tree_util.tree_map(
                    lambda s, old: jnp.where(
                        metrics["weight"] > 0,
                        psum(s, axes) / total_w,
                        old.astype(jnp.float32)).astype(old.dtype),
                    s_sum, stats)
            else:
                new_stats = stats
            return new_params, new_opt, new_stats, metrics

        stepped = shard_map(
            body, mesh,
            in_specs=(rep, opt_specs, rep, batch_specs, rep, rep),
            out_specs=(rep, opt_specs, rep, rep))
        new_params, new_opt, new_stats, metrics = stepped(
            state.params, state.opt_state, state.batch_stats, batch, rng,
            state.step)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  batch_stats=new_stats, opt_state=new_opt)
        return new_state, metrics

    def _eval_step_impl(self, state: TrainState, batch):
        rng = jax.random.PRNGKey(0)  # unused: eval has no augmentation (ref :98-101)
        _, (metrics, _) = self.task.loss_and_metrics(
            state, state.params, batch, rng, train=False)
        return metrics

    # -- state construction ------------------------------------------------

    def init_state(self, model, sample_input, tx, init_rng: jax.Array) -> TrainState:
        """Initialize params, then place them on the mesh per the partition
        rules (replicated by default — the DDP broadcast moment, ref :305-310).
        `sample_input` is a (1, ...) array of the model's input shape/dtype
        (float images or int32 token ids)."""
        from ..parallel.mesh import batch_shard_count

        x = jnp.asarray(sample_input)
        # Models containing shard_map'd ops (ring attention) need the traced
        # batch dim divisible by the mesh batch axes; tile the sample up.
        n_shards = batch_shard_count(self.mesh)
        if x.shape[0] % n_shards:
            x = jnp.tile(x, (n_shards,) + (1,) * (x.ndim - 1))
        variables = model.init(init_rng, x, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        if self._zero1:
            # Params stay replicated (the DDP layout — zero1 shards only
            # the UPDATE); the optimizer state is born flat-padded-sharded
            # over the batch axes, 1/N per replica.
            from .optim import zero1_opt_state

            opt_state = zero1_opt_state(tx, params, self.mesh)
            state = TrainState.create(
                apply_fn=model.apply, params=params, tx=tx,
                batch_stats=batch_stats, opt_state=opt_state)
            placed = shard_pytree(state.replace(opt_state={}), self.mesh,
                                  self.rules)
            return placed.replace(opt_state=opt_state)
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, batch_stats=batch_stats)
        return shard_pytree(state, self.mesh, self.rules)

    # -- epoch loops -------------------------------------------------------

    def train_epoch(
        self,
        state: TrainState,
        batches: Iterable,
        epoch: int,
        steps_per_epoch: int,
        samples_per_step: Optional[Sequence[int]] = None,
        step_hook: Optional[Any] = None,
        start_step: int = 0,
        stop_fn: Optional[Any] = None,
    ) -> Tuple[TrainState, float, float, float, int]:
        """One epoch (maps train_one_epoch, ref :170-263). Returns
        (state, global mean loss, global top-1 %, epoch wall seconds,
        steps executed). `step_hook(step_index)` fires before each step
        (profiler windows). `start_step` labels a mid-epoch resume (the
        caller hands an already-offset batch iterator; the per-step RNG is
        folded from state.step, so the restored trajectory is identical).
        `stop_fn()` checked after every step: True breaks the loop — the
        step-granular preemption point (steps executed < full epoch)."""
        cfg = self.config
        epoch_key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), epoch)

        epoch_metrics = zero_metrics()
        t_epoch = time.time()
        meter = ThroughputMeter()
        steps_done = 0

        for i, batch in enumerate(batches):
            if step_hook is not None:
                step_hook(i)
            state, metrics = self._train_step(state, batch, epoch_key)
            epoch_metrics = add_metrics(epoch_metrics, metrics)
            steps_done = i + 1
            # sample count is host-known (sampler math), no device fetch:
            if samples_per_step is not None:
                meter.update(samples_per_step[min(i, len(samples_per_step) - 1)])

            if (i + 1) % cfg.print_freq == 0:
                # Host fetch happens only here (print boundary), mirroring the
                # reference cadence (ref :229-243) without its per-step syncs.
                # Like the reference, the printed loss/acc are the epoch
                # running averages (ref :230-231).
                avg_loss, avg_acc = summarize(epoch_metrics)
                rate = meter.rate()
                mfu = ""
                if self._flops_per_sample and self._peak_flops_total:
                    mfu_pct = (100.0 * rate * self._flops_per_sample
                               / self._peak_flops_total)
                    mfu = f"  MFU: {mfu_pct:.1f}%"
                log_main(
                    f"Epoch [{epoch + 1}] "
                    f"Step [{start_step + i + 1}/{steps_per_epoch}] "
                    f"Loss: {avg_loss:.4f}  "
                    f"Acc: {avg_acc:.2f}%  "
                    f"Throughput: {rate:.2f} samples/s (global)" + mfu
                )
                meter.reset()

            if stop_fn is not None and stop_fn():
                break

        # Epoch totals: weighted sums are already global (the batch was the
        # global batch) — the reference needs 3 all-reduces here (ref :251-253);
        # we need none.
        jax.block_until_ready(epoch_metrics["weight"])
        epoch_time = time.time() - t_epoch
        loss, acc = summarize(epoch_metrics)
        return state, loss, acc, epoch_time, steps_done

    def evaluate(self, state: TrainState, batches: Iterable) -> Tuple[float, float]:
        """Sharded validation (maps validate, ref :266-300)."""
        totals = zero_metrics()
        for batch in batches:
            totals = add_metrics(totals, self._eval_step(state, batch))
        return summarize(totals)
