"""Heartbeat liveness: relay-port probing + the deathwatch, as a library.

Extracted from ``bench.py`` (where the ADVICE-r5 hardening landed) so bench
and train share ONE source of truth for the tunneled backend's relay-port
set and the abort behavior — the fixes (8087 in the defaults, the 1.5s/3-miss
lethal probe, the bounded PJRT close on partial death) can never drift
between two copies again.

Background (CHIP_STATUS.md, twice observed live): the tunneled single-chip
backend's device RPCs and remote compiles ride localhost relay ports
(8082/8083/8087). When the relay process dies — totally OR partially (just
the compile port) — the client sleep-retries UNAVAILABLE for tens of
minutes with no exception to catch; there is no client-side remedy, so
blocking is pure loss. The ``Deathwatch`` samples the armed ports and, once
any of them is dark for ``max_misses`` consecutive samples, aborts the
process promptly (``os._exit``, because a clean teardown through a dead
socket is exactly the hang being escaped) — after a BOUNDED best-effort
PJRT client close when some armed port is still alive, because an abrupt
exit while holding the TPU claim over a live device port is the
stuck-server-side-grant scenario that wedged the chip for hours.

This module never imports jax at module scope: arming a watch must not
initialize a backend (and linting must not need one).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

# The three ports CHIP_STATUS.md documents the relay listening on; omitting
# 8087 left the deathwatch blind to an 8087-only partial death (ADVICE r5 #1).
DEFAULT_RELAY_PORTS = "8082,8083,8087"
RELAY_PORTS_ENV = "DPT_RELAY_PORTS"
WATCH_INTERVAL_ENV = "DPT_RELAY_WATCH_INTERVAL"

# rc the deathwatch aborts with; parents (bench's watchdog) key their
# crash-salvage branch on it.
DEATHWATCH_EXIT_CODE = 70


def _stderr_log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def relay_ports() -> List[int]:
    """Configured local relay ports (``DPT_RELAY_PORTS``, default
    8082/8083/8087) — THE port registry. Every liveness view (bench's
    advisory ``_tunnel_status``, the lethal deathwatch, train's watch)
    reads this one function so the views can never diverge."""
    return [int(p) for p in
            os.environ.get(RELAY_PORTS_ENV, DEFAULT_RELAY_PORTS).split(",")
            if p.strip().isdigit()]


def port_listening(port: int, timeout: float = 0.2) -> bool:
    """TCP connect probe of one local relay port. The 200ms default suits
    advisory diagnosis; LETHAL probes pass ``LivenessPolicy.connect_timeout_s``
    (1.5s) so a relay that is alive but slow to accept (backlog full during
    a heavy compile/transfer) is not misread as dead (ADVICE r5 #2)."""
    import socket

    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except Exception:
        return False


def registry_snapshot(ports: Optional[Sequence[int]] = None,
                      timeout: float = 0.2) -> dict:
    """One liveness sample of the whole relay registry: ``{port: up}``.

    The control plane's capacity probe
    (control/probe.py ``heartbeat_capacity_probe``) reads fleet capacity
    off this snapshot — each registered port vouches for an equal share
    of the fleet — and the autopilot's decision evidence embeds it, so
    an eviction/grow decision records WHICH port was dark when it was
    taken."""
    return {int(p): port_listening(int(p), timeout=timeout)
            for p in (ports if ports is not None else relay_ports())}


def hard_exit(code: int) -> None:
    """The ONE sanctioned abrupt process exit (``os._exit``).

    An abrupt exit is legitimate only when a clean teardown is itself the
    hang being escaped (dead relay socket) or when a zombie would keep a
    device claim (preemption's hard deadline) — and even then the caller
    must have already attempted/bounded any cleanup it owes. Everywhere
    else, ``os._exit`` while holding the server-side TPU grant wedges the
    chip for every later process (observed live, hours to clear) — the
    ``no-bare-os-exit`` analysis rule flags any other call site."""
    os._exit(code)


def try_clean_pjrt_close(timeout_s: float = 5.0,
                         log: Callable[[str], None] = _stderr_log) -> None:
    """Best-effort, time-boxed release of the PJRT client (and with it the
    server-side TPU grant) before a deathwatch abort on PARTIAL relay death.

    Only meaningful when jax is already loaded and initialized in this
    process (otherwise there is no claim to release — importing jax here
    would CREATE one). The close itself can hang on the dead half of the
    relay, so it runs in a daemon thread that the abort abandons after
    ``timeout_s`` — a bounded attempt, never a new hang (ADVICE r5 #3)."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return
    done = threading.Event()

    def close():
        try:
            # clear_backends tears down the live PJRT client(s); the public
            # name moved across jax versions, so probe both homes.
            clear = getattr(jax_mod, "clear_backends", None)
            if clear is None:
                from jax.extend import backend as jex_backend
                clear = getattr(jex_backend, "clear_backends", None)
            if clear is not None:
                clear()
                log("PJRT client closed cleanly before abort")
        except Exception as e:
            log(f"clean PJRT close failed ({e}); aborting anyway")
        finally:
            done.set()

    # daemon + deliberately never joined (thread-lifecycle: daemon=True is
    # the sanctioned shape): a wedged PJRT close can block FOREVER on the
    # dead relay port, and the whole point is to abandon it and abort
    t = threading.Thread(target=close, daemon=True, name="pjrt-close")
    t.start()
    if not done.wait(timeout_s):
        log(f"clean PJRT close still blocked after {timeout_s:.0f}s "
            "— abandoning it (the dead relay port is unrecoverable)")


@dataclasses.dataclass(frozen=True)
class LivenessPolicy:
    """How a Deathwatch probes and what a death means.

    ``interval_s``: seconds between probe rounds (default from
    ``DPT_RELAY_WATCH_INTERVAL`` at arm time, 30 if unset).
    ``connect_timeout_s``: per-probe TCP connect timeout — 1.5s for lethal
    watches (the advisory 200ms misreads a saturated-but-alive relay,
    ADVICE r5 #2). ``max_misses``: the SAME port must be dark this many
    consecutive samples (per-port counters: transient blips on different
    ports must not add up to a kill). ``lethal``: True aborts the process
    with ``exit_code`` (after the bounded PJRT close on partial death);
    False is advisory — the watch sets ``Deathwatch.died`` and stops, and
    the owner (e.g. a supervisor loop) decides. ``escalate_after_s``
    (advisory watches only): the owner's checkpoint-then-abort depends on
    the current train step RETURNING, and a dead relay turns device RPCs
    into unbounded UNAVAILABLE retries — if the process is still alive
    this many seconds after ``died`` fired, the watch escalates to the
    lethal abort (bounded PJRT close + ``hard_exit``), so an advisory
    watch can never hang strictly longer than the lethal one it replaced.
    None disables escalation."""

    interval_s: float = 30.0
    connect_timeout_s: float = 1.5
    max_misses: int = 3
    lethal: bool = True
    exit_code: int = DEATHWATCH_EXIT_CODE
    escalate_after_s: Optional[float] = None


def default_policy(**overrides) -> LivenessPolicy:
    """The environment-resolved default policy (``WATCH_INTERVAL_ENV``
    honored), with field overrides — THE way an entry point customizes a
    watch (e.g. ``default_policy(lethal=False, escalate_after_s=600.0)``)
    without re-implementing the env resolution."""
    pol = LivenessPolicy(
        interval_s=float(os.environ.get(WATCH_INTERVAL_ENV, "30")))
    return dataclasses.replace(pol, **overrides) if overrides else pol


class Deathwatch:
    """Watch the armed relay ports; act when the tunnel dies mid-run.

    Use :meth:`arm` (the gated constructor) in entry points: it refuses to
    arm off default-port heuristics — an unrelated dev service on 8082 of a
    non-tunneled machine must never be able to kill a healthy run by
    restarting. Arming requires ``DPT_RELAY_PORTS`` to be explicitly set,
    OR ``assume_tunneled=True`` once a successful backend probe has
    CONFIRMED the tunnel (bench does this after seeing the ``axon``
    platform). Only ports LISTENING at arm time are watched: a port already
    dead means an already-degraded tunnel — tripping on it immediately
    would be wrong; but ANY armed port going dark counts (partial relay
    death hangs compiles just like total death — observed live).

    ``on_death(dead_ports, alive_ports)`` runs BEFORE the lethal abort —
    bench uses it to reap in-flight backend probes so no orphan keeps the
    TPU claim past the abort."""

    def __init__(self, ports: Sequence[int],
                 policy: LivenessPolicy = LivenessPolicy(),
                 on_death: Optional[Callable[[List[int], List[int]], None]]
                 = None,
                 log: Callable[[str], None] = _stderr_log):
        self.armed_ports = list(ports)
        self.policy = policy
        self.on_death = on_death
        self.log = log
        self.died = threading.Event()
        self.dead_ports: List[int] = []
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def arm(cls, *, require_env: bool = True, assume_tunneled: bool = False,
            policy: Optional[LivenessPolicy] = None,
            on_death: Optional[Callable[[List[int], List[int]], None]] = None,
            log: Callable[[str], None] = _stderr_log
            ) -> Optional["Deathwatch"]:
        """Gated arm-and-start. Returns the running watch, or None when the
        environment did not opt in (no ``DPT_RELAY_PORTS`` and not
        ``assume_tunneled``) or no armed port is listening (not a tunneled
        environment, or the tunnel is already dead at start)."""
        if require_env and RELAY_PORTS_ENV not in os.environ \
                and not assume_tunneled:
            return None
        if policy is None:
            policy = default_policy()
        armed = [p for p in relay_ports()
                 if port_listening(p, timeout=policy.connect_timeout_s)]
        if not armed:
            return None
        watch = cls(armed, policy=policy, on_death=on_death, log=log)
        watch.start()
        return watch

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self._watch, daemon=True,
                             name="relay-deathwatch")
        self._thread = t
        t.start()
        self.log(f"relay deathwatch armed on ports {self.armed_ports} "
                 f"(interval {self.policy.interval_s:g}s)")
        return t

    def _watch(self) -> None:
        # Per-port consecutive-miss counters: a lethal abort needs the SAME
        # port dark on `max_misses` samples in a row, each probed with the
        # policy's (long) connect timeout. A global counter would let
        # transient blips on different ports kill a healthy compile.
        pol = self.policy
        misses = {p: 0 for p in self.armed_ports}
        while True:
            time.sleep(pol.interval_s)
            for p in self.armed_ports:
                misses[p] = (misses[p] + 1 if not port_listening(
                    p, timeout=pol.connect_timeout_s) else 0)
            dead = [p for p in self.armed_ports
                    if misses[p] >= pol.max_misses]
            if dead:
                alive = [p for p in self.armed_ports if p not in dead
                         and port_listening(p,
                                            timeout=pol.connect_timeout_s)]
                self._fire(dead, alive)
                return

    def _fire(self, dead: List[int], alive: List[int]) -> None:
        pol = self.policy
        self.dead_ports = dead
        verb = ("exiting now instead of hanging in UNAVAILABLE retries "
                "until an outer watchdog SIGTERM" if pol.lethal
                else "signalling the owner")
        self.log(f"relay tunnel DIED mid-run (ports {dead} closed on "
                 f"{pol.max_misses} consecutive samples) — {verb}")
        if self.on_death is not None:
            try:
                self.on_death(dead, alive)
            except Exception as e:  # a broken callback must not mask death
                self.log(f"deathwatch on_death callback failed: {e}")
        self.died.set()
        if not pol.lethal:
            if pol.escalate_after_s is not None:
                self.log(
                    f"advisory deathwatch: hard exit rc={pol.exit_code} in "
                    f"{pol.escalate_after_s:g}s unless the owner's "
                    "checkpoint-then-abort finishes first")
                time.sleep(pol.escalate_after_s)
                # Still here: the owner never terminated — it is wedged in
                # the unbounded-UNAVAILABLE RPC retries the relay death
                # causes, and its drain/checkpoint will never run. Fall
                # through to the lethal abort so advisory mode cannot hang
                # strictly longer than the lethal watch it replaced.
                self.log(
                    f"advisory deathwatch ESCALATING: owner still alive "
                    f"{pol.escalate_after_s:g}s after relay death — the "
                    "checkpoint-then-abort is wedged; hard exit "
                    f"rc={pol.exit_code}")
            else:
                return
        if alive:
            # PARTIAL death: this process may still hold the TPU claim over
            # a live device port, and an abrupt exit can wedge the server-
            # side grant for hours (observed live). Attempt a clean PJRT
            # client close, bounded — the dead port can hang any teardown
            # RPC, so the attempt is abandoned at its deadline (r5 #3).
            try_clean_pjrt_close(timeout_s=5.0, log=self.log)
        # Flight recorder: the lethal abort is exactly the exit that loses
        # the JSONL tail — flush the ring + cause first (telemetry is
        # jax-free and flush_flight never raises/blocks unboundedly, so
        # this cannot re-create the hang being escaped).
        try:
            from ..telemetry import flush_flight
            flush_flight(cause=f"deathwatch: relay ports {dead} dead",
                         detail="lethal relay deathwatch abort",
                         rc=pol.exit_code)
        except Exception:  # a broken flight must never block the abort
            pass
        hard_exit(pol.exit_code)
