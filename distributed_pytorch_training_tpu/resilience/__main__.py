"""``python -m distributed_pytorch_training_tpu.resilience chaos`` — run a
scripted fault schedule against a short CPU-mesh training run and report
recovery stats. The demo AND the test harness: tier-1 drives this same
entry point (tests/test_resilience.py).

Also installed as the ``resilience`` console script (pyproject.toml).

The run is a tiny ResNet on synthetic data under the restart supervisor,
with the full recovery chain engaged: step-fence fault hooks in the train
loop, the torn-checkpoint hook on the save path, the stall hook in the
loader, manifest-verified restores, and preemption drain (the SIGTERM
fault goes through the real ``PreemptionGuard``). ``--verify-parity``
(default on) then re-runs the same seed WITHOUT faults and checks the
final params are BITWISE equal — recovery that changed the trajectory is a
failure, not a recovery.

``--elastic`` (ISSUEs 11 + 12) arms the Supervisor's mesh re-planner AND
the capacity watch: the default schedule kills a replica mid-epoch
(``replica_death@step=3`` — the run re-plans to the largest feasible
world <= survivors, reshards the checkpoint, continues at the shrunken
size) and then RETURNS the capacity (``capacity_return@step=4`` — the
supervisor grows back to the full world at the next segment boundary:
drain, checkpoint, re-plan UP, live reshard). Elasticity is proven
BIDIRECTIONAL in one run: 8 -> 4 -> 8. The parity control is the
post-LAST-resize one: restore the SAME resize-anchor checkpoint
independently (probing the manifest's OWN recorded world), reshard it
through the same helpers, run the remaining steps clean at the final
world — the post-resize segment must be BITWISE equal. ``--layout
{replicated,zero1,fsdp}`` and ``--wire-dtype`` pick the state layout the
resize must re-slice (int8 wires include the EF residuals, whose rows
fold M -> N zero-extended on a grow — the telescoping total is
preserved).

``fleet`` (ISSUE 12) is the cross-PROCESS story: an external orchestrator
(resilience/fleet.py) launches train.py children, watches exit codes,
and relaunches with a DIFFERENT world size over the shared checkpoint
directory — kill -> relaunch at half world -> capacity return -> relaunch
at full world, with cross-world restores riding train.py's elastic
--resume (raw restore + reshard; never a CheckpointWorldSizeMismatch
escape) and a control child verifying the final segment bitwise.

Exit codes: 0 recovered (and parity held), 1 not.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path
from typing import List, Optional

# What an injected fault's flight artifact must say: fault KIND -> the
# substring its flight cause carries. The injected-crash causes quote the
# fault label verbatim ("FaultError: injected crash@step=3"); sigterm
# surfaces as the preemption drain; torn checkpoints as the integrity
# skip. loader_stall is absent by design: a stall is not an exit (the
# anomaly watchdog covers it as an `anomaly` event / optional abort).
FLIGHT_SIGNATURES = {
    "crash": "crash@step",
    "crash_during_save": "crash_during_save",
    "sigterm": "sigterm",
    "torn_ckpt": "torn_checkpoint",
    "replica_death": "replica_death",
}


def check_flights(flight_dir, fired: List[str],
                  ignore: Optional[set] = None) -> dict:
    """Verify every fired fault with a flight signature left a parseable
    ``flight_*.json`` whose cause matches — the chaos acceptance bar for
    the flight recorder (ISSUE 8). ``ignore`` holds flight paths that
    existed BEFORE the run: a reused ``--ckpt-dir`` must not let a
    previous run's postmortems satisfy (or a stale unparseable one fail)
    THIS run's verification."""
    flights = []
    for p in sorted(Path(flight_dir).glob("flight_*.json")):
        if ignore and p in ignore:
            continue
        try:
            body = json.loads(p.read_text())
            flights.append({"path": str(p), "cause": body.get("cause", ""),
                            "n_events": body.get("n_events")})
        except ValueError:
            flights.append({"path": str(p), "cause": None,
                            "error": "unparseable"})
    causes = [f["cause"] or "" for f in flights]
    missing = []
    for label in fired:
        sig = FLIGHT_SIGNATURES.get(label.split("@")[0])
        if sig is not None and not any(sig in c for c in causes):
            missing.append(label)
    ok = not missing and all(f["cause"] is not None for f in flights)
    return {"flights": flights, "flights_missing": missing,
            "flights_ok": ok}


def read_control_decisions(stream_path) -> List[dict]:
    """The control-plane audit trail, read BACK from the stream JSONL —
    the autopilot verdict must prove the decisions were RECORDED (the
    operator-facing artifact), not merely taken in memory."""
    from ..telemetry.recorder import CONTROL_DECISION_KIND

    out: List[dict] = []
    path = Path(stream_path)
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if ev.get("kind") == CONTROL_DECISION_KIND:
            out.append(ev)
    return out


def _build_rig(mesh, seed: int, dataset_size: int, per_device_batch: int,
               fault_hook=None, layout: str = "replicated",
               wire_dtype: str = "fp32"):
    """(trainer, state_factory, loader) — the tiny-ResNet chaos workload
    (fp32 master, augmentation off: bitwise parity is the acceptance bar).
    ``layout`` picks the state layout a chaos/elastic run exercises:
    "replicated" (the DDP layout), "zero1" (flat-sharded moments) or
    "fsdp" (flat-sharded params + moments); an int8 ``wire_dtype`` adds
    the error-feedback residuals to the state (the elastic reshard must
    carry all of them)."""
    import jax
    import numpy as np

    from ..data.datasets import ArrayDataset
    from ..data.loader import ShardedLoader
    from ..models import get_model
    from ..training import TrainConfig, Trainer
    from ..training.optim import sgd
    from ..training.tasks import ImageClassificationTask

    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, (dataset_size, 8, 8, 3)).astype(np.uint8)
    labels = (images.astype(np.float32).mean(axis=(1, 2, 3)) > 127
              ).astype(np.int32)
    ds = ArrayDataset(images=images, labels=labels, num_classes=2,
                      name="chaos-synthetic", synthetic=True)
    task = ImageClassificationTask(mean=(0.5, 0.5, 0.5),
                                   std=(0.25, 0.25, 0.25), augment=False)
    if layout not in ("replicated", "zero1", "fsdp"):
        raise ValueError(f"unknown chaos layout {layout!r} "
                         "(replicated | zero1 | fsdp)")
    cfg = TrainConfig(seed=seed, print_freq=10_000, wire_dtype=wire_dtype,
                      zero1=layout == "zero1",
                      fsdp_explicit=layout == "fsdp")
    trainer = Trainer(task, mesh, cfg)
    # num_filters=8: a ~170k-param ResNet-18 — BatchNorm state and the full
    # recovery chain exercised, checkpoints small enough that the manifest
    # hashing and the several restores stay in tier-1 time
    model = get_model("resnet18", num_classes=2, cifar_stem=True,
                      num_filters=8)
    tx = sgd(0.05, momentum=0.9, weight_decay=5e-4)

    def state_factory():
        return trainer.init_state(model, np.zeros((1, 8, 8, 3), np.float32),
                                  tx, jax.random.PRNGKey(seed))

    loader = ShardedLoader(ds, mesh, per_device_batch, shuffle=True,
                           seed=seed, fault_hook=fault_hook)
    return trainer, state_factory, loader


def _elastic_control(args, ckpt_dir: str, report, rig_for):
    """The post-resize control trajectory: restore the LAST resize's
    checkpoint against its old-world template, reshard to the final world
    through the same helpers the supervisor used, and run the remaining
    steps clean (no faults fire — the injector's schedule is spent — and
    no supervisor segmentation). Returns the control state, or None when
    the resize restarted from scratch (nothing to pin a segment against).
    """
    from ..training.checkpoint import CheckpointManager
    from .elastic import reshard_train_state

    last = report.resizes[-1]
    label, to_w = last["label"], last["to_world"]
    if label is None:
        return None
    trainer_to, sf_to, loader_to = rig_for(to_w)
    ckpt = CheckpointManager(ckpt_dir, max_to_keep=64)
    try:
        # the checkpoint's OWN recorded world, not the resize record's
        # from_world: a second death before any post-resize save restores
        # a label still laid out for an earlier world
        saved_w = ckpt.checkpoint_world_size(label) or last["from_world"]
        _t, sf_from, _l = rig_for(saved_w)
        restored = ckpt.restore_latest(sf_from(), among={label})
    finally:
        ckpt.close()
    from_w = saved_w
    if restored is None:
        return None
    control, epoch_r, step_r = restored
    control = reshard_train_state(control, from_w, to_w, trainer_to,
                                  sf_to())
    spe = len(loader_to)
    for epoch in range(epoch_r, args.epochs):
        start = step_r if epoch == epoch_r else 0
        control, *_ = trainer_to.train_epoch(
            control, loader_to.epoch(epoch, start_step=start), epoch, spe,
            start_step=start)
    return control


def _add_fleet_args(p: argparse.ArgumentParser) -> None:
    """The `resilience fleet` scenario's own knobs (resilience/fleet.py);
    chaos ignores them. The shared knobs — --ckpt-dir, --seed, --layout,
    --wire-dtype, --epochs, --json, --no-verify-parity — apply to both
    commands."""
    p.add_argument("--global-batch", type=int, default=16,
                   help="fleet: the FIXED global batch every generation "
                        "splits over its world (the elastic invariant)")
    p.add_argument("--synthetic-size", type=int, default=64,
                   help="fleet: synthetic dataset rows (steps/epoch = "
                        "rows / global batch)")
    p.add_argument("--capacity", default="8,4,8",
                   help="fleet: available replicas per launch generation, "
                        "comma-separated (last value repeats) — the "
                        "scripted capacity feed: 8,4,8 is kill -> "
                        "half-world relaunch -> capacity-return relaunch")
    p.add_argument("--gen-chaos", default=None,
                   help="fleet: per-generation chaos specs "
                        "'GEN:SPEC[;GEN:SPEC...]' (default: generation 0 "
                        "crashes mid-epoch-1, generation 1 drains on "
                        "SIGTERM shortly before the end)")
    p.add_argument("--max-launches", type=int, default=8,
                   help="fleet: launch budget before giving up")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="fleet: stamp DPT_METRICS_PORT (+rank) into "
                        "every child so each serves live /metrics + "
                        "/healthz; the orchestrator smoke-scrapes it "
                        "while children run (telemetry/metrics_http.py). "
                        "Default off")
    p.add_argument("--federation-port", type=int, default=None,
                   help="fleet: additionally run ONE federated /metrics "
                        "fan-in on this port for the whole run "
                        "(telemetry/metrics_http.FederationServer): "
                        "every child series re-labelled with its "
                        "gen/rank (read from the child's own "
                        "dpt_build_info), exited generations kept in "
                        "the merge marked down. Requires --metrics-port")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="resilience", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=["chaos", "fleet"],
                   help="'chaos' runs the scripted in-process fault "
                        "schedule; 'fleet' runs the cross-process "
                        "relaunch scenario (resilience/fleet.py)")
    p.add_argument("--chaos", default=None,
                   help="fault plan (resilience/faults.py spec; default: "
                        "the full fixed-world schedule, or the "
                        "shrink-then-grow replica_death@step=3,"
                        "capacity_return@step=4 with --elastic)")
    p.add_argument("--elastic", action="store_true",
                   help="arm the Supervisor's mesh re-planner + capacity "
                        "watch: a replica_death fault restarts the run "
                        "resharded to the surviving replica count, a "
                        "capacity_return fault grows it back at the next "
                        "segment boundary, and the parity control "
                        "verifies the post-resize segment bitwise")
    p.add_argument("--autopilot", action="store_true",
                   help="close the control loop (ISSUE 20): attach the "
                        "control/ Autopilot to the telemetry stream and "
                        "let it evict a persistently slow rank at a "
                        "segment boundary (shrink via the elastic path; "
                        "implies --elastic). The default schedule stalls "
                        "the loader 3 consecutive steps on the same rank "
                        "and returns the capacity later — the verdict "
                        "requires the full detect -> evict -> grow "
                        "decision chain on the stream plus bitwise "
                        "post-resize parity")
    p.add_argument("--layout", default="replicated",
                   choices=["replicated", "zero1", "fsdp"],
                   help="state layout the run (and any reshard) exercises")
    p.add_argument("--wire-dtype", default="fp32",
                   help="gradient wire dtype (int8 wires add EF residuals "
                        "to the resharded state)")
    p.add_argument("--epochs", type=int, default=None,
                   help="training epochs (default: 2 for chaos; 3 for "
                        "fleet — one epoch per world phase)")
    p.add_argument("--per-device-batch", type=int, default=2)
    p.add_argument("--dataset-size", type=int, default=None,
                   help="synthetic dataset rows (default 64; 128 with "
                        "--autopilot — the eviction needs enough steps "
                        "per epoch for a 3-stall run plus the boundary "
                        "that convicts it)")
    p.add_argument("--checkpoint-every-steps", type=int, default=2)
    p.add_argument("--max-restarts", type=int, default=8)
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint directory (default: a fresh temp dir)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-verify-parity", action="store_true",
                   help="skip the no-fault same-seed control run")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable one-line report on stdout")
    _add_fleet_args(p)
    args = p.parse_args(argv)
    if args.command == "fleet":
        if args.epochs is None:
            args.epochs = 3
        from .fleet import fleet_main
        return fleet_main(args)
    if args.epochs is None:
        args.epochs = 2
    if args.autopilot:
        # the autopilot rides the elastic surface: eviction IS a shrink
        # re-plan, re-admission IS the boundary grow
        args.elastic = True
    if args.dataset_size is None:
        args.dataset_size = 128 if args.autopilot else 64
    if args.chaos is None and args.autopilot:
        # loop (1)'s proof schedule: the SAME rank stalls three
        # consecutive in-epoch steps (the policy's N) — no fault raises,
        # nothing crashes; the ONLY path to a resize is the autopilot
        # naming the straggler from data_wait spans and evicting it at
        # the boundary after the third stall. The capacity then returns
        # (absolute step 11, inside the shrunken world's epoch 1) and the
        # ordinary boundary grow re-admits it — detect -> evict -> grow.
        args.chaos = ("loader_stall@step=5:0.9s,loader_stall@step=6:0.9s,"
                      "loader_stall@step=7:0.9s,capacity_return@step=11")
    if args.chaos is None:
        # the default elastic schedule is BIDIRECTIONAL (ISSUE 12): kill
        # a replica at step 3 (8 -> 4 at the restart), return the
        # capacity at the step-4 fence (4 -> 8 at the next segment
        # boundary) — one run proves shrink, grow, and the EF fold both
        # ways
        args.chaos = ("replica_death@step=3,capacity_return@step=4"
                      if args.elastic else
                      "crash@step=3,torn_ckpt@save=2,"
                      "crash_during_save@save=2,sigterm@step=6")

    # The zero1/grad_sync trick reused: chaos runs on the 8-device virtual
    # CPU mesh unless a real accelerator is already up.
    from ..analysis.__main__ import _ensure_test_mesh
    _ensure_test_mesh()

    import jax
    import numpy as np

    from ..parallel import MeshSpec, build_mesh
    from ..training.checkpoint import CheckpointManager
    from ..training.preemption import PreemptionGuard
    from .faults import FaultInjector, FaultPlan
    from .supervisor import RetryPolicy, Supervisor, SupervisorError

    mesh = build_mesh(MeshSpec(), devices=jax.devices())
    world0 = len(jax.devices())
    # the capacity registry (elastic runs): replica deaths debit it via
    # the Supervisor, the capacity_return fault credits it via the
    # injector, and the Supervisor's segment-boundary poll grows on it
    capacity = None
    if args.elastic:
        from .capacity import CapacityWatch
        capacity = CapacityWatch(total=world0)
    injector = FaultInjector(FaultPlan.parse(args.chaos),
                             capacity_watch=capacity)
    global_batch = args.per_device_batch * world0
    # one rig per world this run has trained at — the replan builds them
    # lazily over device SUBSETS (the in-process stand-in for a relaunch
    # on the surviving fleet), and the parity control reuses them
    rigs = {}

    def rig_for(world: int):
        # every rig carries the fault hook — the parity control stays
        # clean anyway because a completed run's schedule is spent (the
        # injector's takes are empty membership checks by then)
        if world not in rigs:
            sub = (mesh if world == world0 else
                   build_mesh(MeshSpec(), devices=jax.devices()[:world]))
            if global_batch % world:
                raise ValueError(
                    f"global batch {global_batch} does not divide over "
                    f"{world} replicas")
            rigs[world] = _build_rig(
                sub, args.seed, args.dataset_size, global_batch // world,
                fault_hook=injector.on_loader_batch,
                layout=args.layout, wire_dtype=args.wire_dtype)
        return rigs[world]

    trainer, state_factory, loader = rig_for(world0)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="dpt-chaos-")
    # Telemetry + flight recorder (telemetry/): the supervisor flushes a
    # flight_<ts>.json per failure/drain into this stream's directory —
    # the chaos run then VERIFIES every injected fault left its
    # postmortem (check_flights), not just that training recovered.
    from .. import telemetry
    telemetry.configure(str(Path(ckpt_dir) / "telemetry_rank0.jsonl"),
                        meta={"entry": "resilience chaos",
                              "chaos": args.chaos})
    # Warm-restart compilation cache (DPT_COMPILE_CACHE tri-state): off by
    # default on the CPU harness ("auto" refuses XLA:CPU — unsafe reloads),
    # measurable on accelerators where an elastic resize otherwise pays a
    # full recompile of the resized step.
    from ..runtime import enable_persistent_compile_cache

    enable_persistent_compile_cache(Path(ckpt_dir) / ".jax_cache")
    # async saves ON (the production default): the schedule's
    # crash_during_save fault dies on the background writer and must
    # surface at the next save/wait barrier inside the recovery scope.
    # Elastic runs keep every label (max_to_keep): the parity control must
    # re-restore the exact resize-point checkpoint after the run.
    ckpt = CheckpointManager(ckpt_dir, post_save_hook=injector.on_save,
                             pre_finalize_hook=injector.on_save_finalize,
                             max_to_keep=(64 if args.elastic else 3))
    guard = PreemptionGuard.install()
    # flights already in the dir belong to a PREVIOUS run (user-supplied
    # --ckpt-dir reuse) — excluded from this run's verification
    pre_existing_flights = set(Path(ckpt_dir).glob("flight_*.json"))
    # fast, deterministic backoff: chaos is a harness, not a prod outage
    retry = RetryPolicy(max_restarts=args.max_restarts, backoff_base_s=0.01,
                        backoff_max_s=0.05, seed=args.seed)

    replan_cb = None
    if args.elastic:
        from .elastic import ElasticPlan, plan_elastic_world

        def replan_cb(survivors: int) -> "ElasticPlan":
            world = plan_elastic_world(survivors, global_batch)
            t, sf, ld = rig_for(world)
            return ElasticPlan(trainer=t, loader=ld, state_factory=sf,
                               world=world)

    autopilot = None
    if args.autopilot:
        # ISSUE 20: the policy layer rides the recorder as an observer
        # and is consulted by the Supervisor at clean segment boundaries;
        # nothing below this block exists when --autopilot is off.
        from ..control import Autopilot
        autopilot = Autopilot().attach()
    sup = Supervisor(trainer, ckpt, state_factory, loader, retry=retry,
                     guard=guard, injector=injector,
                     checkpoint_every_steps=args.checkpoint_every_steps,
                     resume_preempted=True, replan_cb=replan_cb,
                     capacity_watch=capacity, control=autopilot)
    error = None
    try:
        state, report = sup.run(args.epochs)
    except SupervisorError as e:
        state, report = None, e.report
        error = str(e)
    finally:
        guard.reset()
        ckpt.close()
        if autopilot is not None:
            autopilot.detach()
        telemetry.reset()  # close the JSONL; flights are already on disk
    flight_stats = check_flights(ckpt_dir, report.faults_fired,
                                 ignore=pre_existing_flights)
    decisions = (read_control_decisions(
        Path(ckpt_dir) / "telemetry_rank0.jsonl")
        if args.autopilot else [])

    parity = None
    if state is not None and not args.no_verify_parity:
        if report.resizes:
            # ELASTIC parity: the post-resize segment vs an independent
            # clean continuation at the shrunken world — restore the SAME
            # resize-point checkpoint with the old-world template, reshard
            # it through the same helpers, and train the remaining steps
            # with no supervisor segmentation. Bitwise equality proves the
            # reshard is a pure re-slice and the resumed sampler/RNG
            # schedule is the fixed-world-at-M one (PARITY.md).
            control = _elastic_control(args, ckpt_dir, report, rig_for)
        else:
            # control: same seed, same trainer (same compiled step), NO
            # faults, no supervisor segmentation — the uninterrupted
            # trajectory.
            _, _, control_loader = _build_rig(
                mesh, args.seed, args.dataset_size, args.per_device_batch,
                layout=args.layout, wire_dtype=args.wire_dtype)
            control = state_factory()
            spe = len(control_loader)
            for epoch in range(args.epochs):
                control, *_ = trainer.train_epoch(
                    control, control_loader.epoch(epoch), epoch, spe)
        parity = control is not None and all(
            bool(np.array_equal(np.asarray(jax.device_get(a)),
                                np.asarray(jax.device_get(b))))
            for a, b in zip(jax.tree_util.tree_leaves(state.params),
                            jax.tree_util.tree_leaves(control.params)))

    stats = {"metric": "chaos_recovery", "chaos": args.chaos,
             "epochs": args.epochs, "ckpt_dir": ckpt_dir,
             "elastic": args.elastic, "layout": args.layout,
             "wire_dtype": args.wire_dtype,
             "autopilot": args.autopilot,
             "control_decisions": [
                 {("action" if k == "name" else k): d.get(k)
                  for k in ("name", "rank", "epoch", "step", "world_from",
                            "world_to", "applied", "reason")
                  if d.get(k) is not None}
                 for d in decisions],
             "parity_bitwise": parity, "error": error,
             # the async-save instrument: loop-blocked ms vs snapshot ms
             "save_blocked_ms": round(ckpt.save_blocked_ms, 1),
             "snapshot_ms": round(ckpt.snapshot_ms, 1),
             **flight_stats,
             **report.as_dict()}
    # flights_ok is part of RECOVERED: a fault that left no postmortem
    # artifact would make the next real incident undiagnosable; an elastic
    # run that never resized (the schedule missed) proved nothing — and a
    # schedule whose capacity RETURNED but whose run never grew proved
    # only half of bidirectional elasticity
    grew = any(r.get("direction") == "grow"
               for r in report.resizes)
    capacity_returned = any(label.startswith("capacity_return")
                            for label in report.faults_fired)
    # the grow requirement binds only under --elastic: without a watch a
    # capacity_return fault fires into the void by design (faults.py) —
    # a fixed-world run that recovered must not be scored FAILED for it
    # the autopilot bar (ISSUE 20): the shrink must be the CONTROL
    # PLANE's doing (a resize whose cause is straggler_evict — no fault
    # raised in this schedule), and the full decision chain must be
    # readable back off the stream: a detect, an APPLIED evict, and the
    # accounting grow once capacity returned
    actions = [d.get("name") for d in decisions]
    evicted = any(r.get("cause") == "straggler_evict"
                  and r.get("direction") == "shrink"
                  for r in report.resizes)
    chain_ok = (not args.autopilot
                or (evicted and "detect" in actions and "grow" in actions
                    and any(d.get("name") == "evict" and d.get("applied")
                            for d in decisions)))
    ok = (report.completed and report.fence_violations == 0
          and parity is not False and error is None
          and flight_stats["flights_ok"]
          and (not args.elastic or bool(report.resizes))
          and (not args.elastic or not capacity_returned or grew)
          and chain_ok)
    if args.as_json:
        print(json.dumps(stats, sort_keys=True))
    else:
        for k in ("completed", "restarts", "preemptions_drained",
                  "checkpoints_skipped", "steps_run", "steps_replayed",
                  "fence_violations", "final_step", "parity_bitwise"):
            print(f"{k}: {stats[k]}")
        print(f"faults fired: {stats['faults_fired']}")
        for r in stats.get("resizes", []):
            print(f"elastic {r.get('direction', 'resize')}: "
                  f"{r['from_world']} -> {r['to_world']} replicas "
                  f"(available={r['survivors']}, anchor label "
                  f"{r['label']}, resumed epoch {r['epoch']} "
                  f"step {r['step']})")
        for d in stats["control_decisions"]:
            who = (f" rank {d['rank']}" if d.get("rank") is not None
                   else "")
            world = (f" world {d['world_from']}->{d['world_to']}"
                     if d.get("world_to") is not None else "")
            applied = " [applied]" if d.get("applied") else ""
            print(f"control {d['action']}:{who}{world}{applied} "
                  f"{d.get('reason', '')}")
        print(f"flight artifacts: {len(stats['flights'])} "
              f"(ok={stats['flights_ok']}"
              + (f", missing={stats['flights_missing']}"
                 if stats["flights_missing"] else "") + ")")
        if stats["faults_unfired"]:
            print(f"faults NEVER fired (schedule past the run?): "
                  f"{stats['faults_unfired']}")
        if error:
            print(f"error: {error}", file=sys.stderr)
        print("chaos: RECOVERED" if ok else "chaos: FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
