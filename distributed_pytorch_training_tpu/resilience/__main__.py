"""``python -m distributed_pytorch_training_tpu.resilience chaos`` — run a
scripted fault schedule against a short CPU-mesh training run and report
recovery stats. The demo AND the test harness: tier-1 drives this same
entry point (tests/test_resilience.py).

Also installed as the ``resilience`` console script (pyproject.toml).

The run is a tiny ResNet on synthetic data under the restart supervisor,
with the full recovery chain engaged: step-fence fault hooks in the train
loop, the torn-checkpoint hook on the save path, the stall hook in the
loader, manifest-verified restores, and preemption drain (the SIGTERM
fault goes through the real ``PreemptionGuard``). ``--verify-parity``
(default on) then re-runs the same seed WITHOUT faults and checks the
final params are BITWISE equal — recovery that changed the trajectory is a
failure, not a recovery.

Exit codes: 0 recovered (and parity held), 1 not.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path
from typing import List, Optional

# What an injected fault's flight artifact must say: fault KIND -> the
# substring its flight cause carries. The injected-crash causes quote the
# fault label verbatim ("FaultError: injected crash@step=3"); sigterm
# surfaces as the preemption drain; torn checkpoints as the integrity
# skip. loader_stall is absent by design: a stall is not an exit (the
# anomaly watchdog covers it as an `anomaly` event / optional abort).
FLIGHT_SIGNATURES = {
    "crash": "crash@step",
    "crash_during_save": "crash_during_save",
    "sigterm": "sigterm",
    "torn_ckpt": "torn_checkpoint",
}


def check_flights(flight_dir, fired: List[str],
                  ignore: Optional[set] = None) -> dict:
    """Verify every fired fault with a flight signature left a parseable
    ``flight_*.json`` whose cause matches — the chaos acceptance bar for
    the flight recorder (ISSUE 8). ``ignore`` holds flight paths that
    existed BEFORE the run: a reused ``--ckpt-dir`` must not let a
    previous run's postmortems satisfy (or a stale unparseable one fail)
    THIS run's verification."""
    flights = []
    for p in sorted(Path(flight_dir).glob("flight_*.json")):
        if ignore and p in ignore:
            continue
        try:
            body = json.loads(p.read_text())
            flights.append({"path": str(p), "cause": body.get("cause", ""),
                            "n_events": body.get("n_events")})
        except ValueError:
            flights.append({"path": str(p), "cause": None,
                            "error": "unparseable"})
    causes = [f["cause"] or "" for f in flights]
    missing = []
    for label in fired:
        sig = FLIGHT_SIGNATURES.get(label.split("@")[0])
        if sig is not None and not any(sig in c for c in causes):
            missing.append(label)
    ok = not missing and all(f["cause"] is not None for f in flights)
    return {"flights": flights, "flights_missing": missing,
            "flights_ok": ok}


def _build_rig(mesh, seed: int, dataset_size: int, per_device_batch: int,
               fault_hook=None):
    """(trainer, state_factory, loader) — the tiny-ResNet chaos workload
    (fp32, augmentation off: bitwise parity is the acceptance bar)."""
    import jax
    import numpy as np

    from ..data.datasets import ArrayDataset
    from ..data.loader import ShardedLoader
    from ..models import get_model
    from ..training import TrainConfig, Trainer
    from ..training.optim import sgd
    from ..training.tasks import ImageClassificationTask

    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, (dataset_size, 8, 8, 3)).astype(np.uint8)
    labels = (images.astype(np.float32).mean(axis=(1, 2, 3)) > 127
              ).astype(np.int32)
    ds = ArrayDataset(images=images, labels=labels, num_classes=2,
                      name="chaos-synthetic", synthetic=True)
    task = ImageClassificationTask(mean=(0.5, 0.5, 0.5),
                                   std=(0.25, 0.25, 0.25), augment=False)
    trainer = Trainer(task, mesh, TrainConfig(seed=seed, print_freq=10_000))
    # num_filters=8: a ~170k-param ResNet-18 — BatchNorm state and the full
    # recovery chain exercised, checkpoints small enough that the manifest
    # hashing and the several restores stay in tier-1 time
    model = get_model("resnet18", num_classes=2, cifar_stem=True,
                      num_filters=8)
    tx = sgd(0.05, momentum=0.9, weight_decay=5e-4)

    def state_factory():
        return trainer.init_state(model, np.zeros((1, 8, 8, 3), np.float32),
                                  tx, jax.random.PRNGKey(seed))

    loader = ShardedLoader(ds, mesh, per_device_batch, shuffle=True,
                           seed=seed, fault_hook=fault_hook)
    return trainer, state_factory, loader


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="resilience", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=["chaos"],
                   help="'chaos' runs the scripted fault schedule")
    p.add_argument("--chaos",
                   default="crash@step=3,torn_ckpt@save=2,"
                           "crash_during_save@save=2,sigterm@step=6",
                   help="fault plan (resilience/faults.py spec)")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--per-device-batch", type=int, default=2)
    p.add_argument("--dataset-size", type=int, default=64)
    p.add_argument("--checkpoint-every-steps", type=int, default=2)
    p.add_argument("--max-restarts", type=int, default=8)
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint directory (default: a fresh temp dir)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-verify-parity", action="store_true",
                   help="skip the no-fault same-seed control run")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable one-line report on stdout")
    args = p.parse_args(argv)

    # The zero1/grad_sync trick reused: chaos runs on the 8-device virtual
    # CPU mesh unless a real accelerator is already up.
    from ..analysis.__main__ import _ensure_test_mesh
    _ensure_test_mesh()

    import jax
    import numpy as np

    from ..parallel import MeshSpec, build_mesh
    from ..training.checkpoint import CheckpointManager
    from ..training.preemption import PreemptionGuard
    from .faults import FaultInjector, FaultPlan
    from .supervisor import RetryPolicy, Supervisor, SupervisorError

    mesh = build_mesh(MeshSpec(), devices=jax.devices())
    injector = FaultInjector(FaultPlan.parse(args.chaos))
    trainer, state_factory, loader = _build_rig(
        mesh, args.seed, args.dataset_size, args.per_device_batch,
        fault_hook=injector.on_loader_batch)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="dpt-chaos-")
    # Telemetry + flight recorder (telemetry/): the supervisor flushes a
    # flight_<ts>.json per failure/drain into this stream's directory —
    # the chaos run then VERIFIES every injected fault left its
    # postmortem (check_flights), not just that training recovered.
    from .. import telemetry
    telemetry.configure(str(Path(ckpt_dir) / "telemetry_rank0.jsonl"),
                        meta={"entry": "resilience chaos",
                              "chaos": args.chaos})
    # async saves ON (the production default): the schedule's
    # crash_during_save fault dies on the background writer and must
    # surface at the next save/wait barrier inside the recovery scope
    ckpt = CheckpointManager(ckpt_dir, post_save_hook=injector.on_save,
                             pre_finalize_hook=injector.on_save_finalize)
    guard = PreemptionGuard.install()
    # flights already in the dir belong to a PREVIOUS run (user-supplied
    # --ckpt-dir reuse) — excluded from this run's verification
    pre_existing_flights = set(Path(ckpt_dir).glob("flight_*.json"))
    # fast, deterministic backoff: chaos is a harness, not a prod outage
    retry = RetryPolicy(max_restarts=args.max_restarts, backoff_base_s=0.01,
                        backoff_max_s=0.05, seed=args.seed)
    sup = Supervisor(trainer, ckpt, state_factory, loader, retry=retry,
                     guard=guard, injector=injector,
                     checkpoint_every_steps=args.checkpoint_every_steps,
                     resume_preempted=True)
    error = None
    try:
        state, report = sup.run(args.epochs)
    except SupervisorError as e:
        state, report = None, e.report
        error = str(e)
    finally:
        guard.reset()
        ckpt.close()
        telemetry.reset()  # close the JSONL; flights are already on disk
    flight_stats = check_flights(ckpt_dir, report.faults_fired,
                                 ignore=pre_existing_flights)

    parity = None
    if state is not None and not args.no_verify_parity:
        # control: same seed, same trainer (same compiled step), NO faults,
        # no supervisor segmentation — the uninterrupted trajectory.
        _, _, control_loader = _build_rig(
            mesh, args.seed, args.dataset_size, args.per_device_batch)
        control = state_factory()
        spe = len(control_loader)
        for epoch in range(args.epochs):
            control, *_ = trainer.train_epoch(
                control, control_loader.epoch(epoch), epoch, spe)
        parity = all(
            bool(np.array_equal(np.asarray(jax.device_get(a)),
                                np.asarray(jax.device_get(b))))
            for a, b in zip(jax.tree_util.tree_leaves(state.params),
                            jax.tree_util.tree_leaves(control.params)))

    stats = {"metric": "chaos_recovery", "chaos": args.chaos,
             "epochs": args.epochs, "ckpt_dir": ckpt_dir,
             "parity_bitwise": parity, "error": error,
             # the async-save instrument: loop-blocked ms vs snapshot ms
             "save_blocked_ms": round(ckpt.save_blocked_ms, 1),
             "snapshot_ms": round(ckpt.snapshot_ms, 1),
             **flight_stats,
             **report.as_dict()}
    # flights_ok is part of RECOVERED: a fault that left no postmortem
    # artifact would make the next real incident undiagnosable
    ok = (report.completed and report.fence_violations == 0
          and parity is not False and error is None
          and flight_stats["flights_ok"])
    if args.as_json:
        print(json.dumps(stats, sort_keys=True))
    else:
        for k in ("completed", "restarts", "preemptions_drained",
                  "checkpoints_skipped", "steps_run", "steps_replayed",
                  "fence_violations", "final_step", "parity_bitwise"):
            print(f"{k}: {stats[k]}")
        print(f"faults fired: {stats['faults_fired']}")
        print(f"flight artifacts: {len(stats['flights'])} "
              f"(ok={stats['flights_ok']}"
              + (f", missing={stats['flights_missing']}"
                 if stats["flights_missing"] else "") + ")")
        if stats["faults_unfired"]:
            print(f"faults NEVER fired (schedule past the run?): "
                  f"{stats['faults_unfired']}")
        if error:
            print(f"error: {error}", file=sys.stderr)
        print("chaos: RECOVERED" if ok else "chaos: FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
