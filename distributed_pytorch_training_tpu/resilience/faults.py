"""Deterministic fault injection: a parsed ``FaultPlan`` + one-shot hooks.

Chaos testing for the training stack: the plan is a comma-separated spec
(CLI ``--chaos`` / env ``DPT_CHAOS``) of faults pinned to exact trigger
points, so every failure a test provokes is reproducible:

* ``crash@step=7``        — raise :class:`FaultError` at the step-7 fence
  (before the step executes; the optimizer never applies step 7).
* ``sigterm@step=12``     — deliver a real SIGTERM to this process at the
  step-12 fence (the preemption path, end to end through the installed
  ``PreemptionGuard``).
* ``torn_ckpt@save=2``    — truncate a data file of the 2nd checkpoint
  save AFTER it finalized (simulates post-commit corruption: disk
  truncation, a torn copy) so the manifest verification in
  ``training/checkpoint.py`` must catch and skip it.
* ``crash_during_save@save=3`` — raise :class:`FaultError` INSIDE the 3rd
  save, between the orbax commit and the manifest write (the
  ``CheckpointManager(pre_finalize_hook=...)`` window). Under async saves
  this is the writer-thread crash: the save never finalizes, the error
  surfaces at the next save/wait barrier, and ``restore_latest`` must
  skip the half-born checkpoint loudly (the pending marker) instead of
  trusting it as a legacy one.
* ``loader_stall@step=5:2.5s`` — sleep 2.5s in the data loader before
  producing the batch of (in-epoch) step 5.
* ``replica_death@step=7`` — raise :class:`ReplicaDeathError` at the
  step-7 fence: one data-parallel replica is lost (the preemptible-fleet
  failure). Under a Supervisor armed with ``replan_cb`` this triggers an
  ELASTIC restart — the mesh re-plans to the surviving replica count and
  the checkpoint reshards (resilience/elastic.py); without one it is an
  ordinary restartable crash.
* ``capacity_return@step=7`` — preempted capacity RETURNS at the step-7
  fence: the injector notifies its armed
  :class:`~.capacity.CapacityWatch` (``restore()`` — back to the full
  registry). Nothing raises: a Supervisor polling the watch grows the
  mesh at the NEXT segment boundary (drain → checkpoint → re-plan UP →
  reshard), so the grow is anchored at a durable coordinate exactly like
  the preemption drain. Without a watch the fault fires into the void
  (logged) — the schedule stays reproducible either way.

Any spec may carry a repeat count: ``replica_death@step=3x2`` fires TWICE
(the restart's replay re-crosses the step-3 fence and the second firing
shrinks the mesh again) — multi-fault elastic schedules without one-shot
workarounds. One-shot remains the default.

Step indices are the ABSOLUTE global step (``state.step`` before the step
executes, i.e. steps are 0-indexed from the start of the run) for ``crash``
and ``sigterm``; ``loader_stall`` uses the in-epoch step index (the loader
has no global-step view). ``save`` counts, 1-indexed: finalized saves for
``torn_ckpt`` (``on_save``), save ATTEMPTS reaching the finalize window
for ``crash_during_save`` (``on_save_finalize``) — separate counters, so
a crashed attempt does not shift the torn schedule.

Every fault fires ONCE: a crash at step k would otherwise re-fire on the
replay of step k after restore and the run could never make progress.
Hooks are threaded as plain optional callables (``training/loop.py``
``fault_hook``, ``CheckpointManager(post_save_hook=...)``,
``ShardedLoader(fault_hook=...)``) — when no plan is armed the hooks are
``None`` and the hot path is untouched.
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import sys
import threading
import time
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from ..utils.locktrace import named_lock

CHAOS_ENV = "DPT_CHAOS"

# kind -> the only trigger it accepts (a typo'd trigger must fail loudly).
FAULT_KINDS = {
    "crash": "step",
    "sigterm": "step",
    "loader_stall": "step",
    "torn_ckpt": "save",
    "crash_during_save": "save",
    "replica_death": "step",
    "capacity_return": "step",
}

# Repeat counts (`kind@trigger=N xK`, e.g. "replica_death@step=3x2"): the
# fault consumes one firing per matching trigger occurrence until K are
# spent. The one-shot default (no xK) is unchanged. The canonical use is
# multi-fault ELASTIC schedules: a replica death at step k restarts the
# run resharded, the replay re-crosses the step-k fence, and the second
# firing shrinks the mesh again — no one-shot workaround spec needed.
_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<trigger>[a-z]+)=(?P<at>\d+)"
    r"(?::(?P<arg>\d+(?:\.\d+)?)s?)?(?:\s*x(?P<rep>\d+))?$")


class FaultError(RuntimeError):
    """An injected crash — the supervisor's restartable failure class."""


class ReplicaDeathError(FaultError):
    """An injected loss of a data-parallel replica (``replica_death@step=k``
    — the preemptible-fleet failure a fixed-world restart cannot absorb).
    Raised at the step fence like ``crash``; a Supervisor armed with a
    ``replan_cb`` treats it as the elastic-resize trigger: restart at the
    surviving replica count instead of the dead world. ``survivors`` is
    filled by the supervisor (the injector has no world-size view)."""

    def __init__(self, message: str, survivors: Optional[int] = None):
        super().__init__(message)
        self.survivors = survivors


def _stderr_log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str        # crash | sigterm | loader_stall | torn_ckpt | ...
    trigger: str     # "step" or "save"
    at: int          # step index (0-based) or save count (1-based)
    seconds: float = 0.0  # loader_stall duration
    count: int = 1   # repeat count (the `xK` suffix): firings before spent

    def label(self, remaining: Optional[int] = None) -> str:
        """Base label of ONE firing (what `fired` records — signatures key
        on it); with ``remaining`` > 1 the spec-form repeat suffix rides
        along (what `unfired()` reports)."""
        tail = f":{self.seconds:g}s" if self.kind == "loader_stall" else ""
        rep = (f"x{remaining}" if remaining is not None and remaining > 1
               else "")
        return f"{self.kind}@{self.trigger}={self.at}{tail}{rep}"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable parsed plan; arm it by building a :class:`FaultInjector`."""

    faults: Tuple[Fault, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.faults)

    @classmethod
    def parse(cls, spec: Optional[str]) -> "FaultPlan":
        """``"crash@step=7,torn_ckpt@save=2,loader_stall@step=5:2.5s"``.
        Empty/None spec parses to the empty plan (nothing armed)."""
        faults: List[Fault] = []
        for item in filter(None, (s.strip()
                                  for s in (spec or "").split(","))):
            m = _SPEC_RE.match(item)
            if not m:
                raise ValueError(
                    f"chaos fault {item!r} is not kind@trigger=N[:SECs] "
                    f"(kinds: {sorted(FAULT_KINDS)})")
            kind, trigger = m.group("kind"), m.group("trigger")
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown chaos fault kind {kind!r} "
                                 f"(kinds: {sorted(FAULT_KINDS)})")
            if trigger != FAULT_KINDS[kind]:
                raise ValueError(
                    f"chaos fault {kind!r} triggers on "
                    f"{FAULT_KINDS[kind]!r}, not {trigger!r}")
            seconds = float(m.group("arg") or 0.0)
            if kind == "loader_stall" and seconds <= 0:
                raise ValueError(
                    f"loader_stall needs a duration ({item!r}; e.g. "
                    "loader_stall@step=5:2.5s)")
            if kind != "loader_stall" and m.group("arg"):
                raise ValueError(
                    f"chaos fault {kind!r} takes no :SECs argument ({item!r})")
            count = int(m.group("rep") or 1)
            if count < 1:
                raise ValueError(
                    f"chaos fault repeat count must be >= 1 ({item!r}; "
                    "omit the x-suffix for a one-shot fault)")
            faults.append(Fault(kind=kind, trigger=trigger,
                                at=int(m.group("at")), seconds=seconds,
                                count=count))
        return cls(faults=tuple(faults))

    @classmethod
    def from_env(cls, env: str = CHAOS_ENV) -> "FaultPlan":
        return cls.parse(os.environ.get(env))


def tear_checkpoint(step_dir: Path,
                    log: Callable[[str], None] = _stderr_log) -> Path:
    """Truncate the largest data file under a FINALIZED checkpoint step dir
    to half its size — the canonical torn checkpoint. Returns the torn
    file's path. Raises when the dir holds no file (tearing nothing would
    make a chaos run pass vacuously)."""
    files = sorted((p for p in Path(step_dir).rglob("*") if p.is_file()),
                   key=lambda p: p.stat().st_size, reverse=True)
    if not files:
        raise FileNotFoundError(f"no file to tear under {step_dir}")
    victim = files[0]
    size = victim.stat().st_size
    with open(victim, "r+b") as f:
        f.truncate(max(1, size // 2))
    log(f"chaos: TORE checkpoint file {victim} ({size} -> "
        f"{victim.stat().st_size} bytes)")
    return victim


class FaultInjector:
    """Armed, mutable state of one plan: each fault fires once, and what
    fired is recorded (``fired`` / ``unfired()`` feed the recovery report).

    The hook methods are what the stack calls:
    ``on_step(step)`` from the trainer's step fence (``fault_hook``),
    ``on_loader_batch(step)`` from the data loader, and
    ``on_save(label, step_dir)`` from the checkpoint manager after a save
    finalizes. All are cheap membership checks when nothing matches."""

    def __init__(self, plan: FaultPlan,
                 log: Callable[[str], None] = _stderr_log,
                 capacity_watch=None):
        self.plan = plan
        self.log = log
        # the grow-side registry a capacity_return fault notifies
        # (resilience/capacity.CapacityWatch, or None: the fault fires
        # into the void — logged, recorded in `fired`, changing nothing)
        self.capacity_watch = capacity_watch
        # [fault, remaining firings] — `remaining` starts at the parsed
        # repeat count (1 without an xK suffix) and the fault leaves the
        # pending list only once spent
        self._pending: List[list] = [[f, f.count] for f in plan.faults]  # guarded-by: _lock
        self.fired: List[str] = []   # guarded-by: _lock
        self.saves_seen = 0          # guarded-by: _lock
        self.finalizes_seen = 0      # guarded-by: _lock
        # the hooks fire from different threads (the step fence on the
        # main thread, on_loader_batch from the loader's producer thread)
        # and an unsynchronized take could skip a matching fault — the
        # schedule must stay deterministic under prefetch
        self._lock = named_lock("FaultInjector._lock")

    def unfired(self) -> List[str]:
        with self._lock:
            return [f.label(remaining=n) for f, n in self._pending]

    def _take(self, kind: str, at: int) -> Optional[Fault]:
        with self._lock:
            for entry in self._pending:
                f, remaining = entry
                if f.kind == kind and f.at == at:
                    if remaining <= 1:
                        self._pending.remove(entry)
                    else:
                        entry[1] = remaining - 1
                    self.fired.append(f.label())
                    return f
            return None

    def on_step(self, step: int) -> None:
        """Step fence, called BEFORE global step ``step`` executes."""
        if self._take("capacity_return", step) is not None:
            # checked before the raising kinds: capacity returning at the
            # same fence a crash fires on must still be registered (the
            # post-restart boundary poll then sees it)
            if self.capacity_watch is not None:
                avail = self.capacity_watch.restore()
                self.log(f"chaos: capacity returned at step {step} "
                         f"({avail}/{self.capacity_watch.total} replicas "
                         "available)")
            else:
                self.log(f"chaos: capacity returned at step {step} "
                         "(no CapacityWatch armed — nothing to notify)")
        if self._take("sigterm", step) is not None:
            self.log(f"chaos: delivering SIGTERM at step {step}")
            os.kill(os.getpid(), signal.SIGTERM)
        if self._take("replica_death", step) is not None:
            self.log(f"chaos: injected replica death at step {step}")
            raise ReplicaDeathError(
                f"injected replica_death@step={step} (one data-parallel "
                "replica lost)")
        if self._take("crash", step) is not None:
            self.log(f"chaos: injected crash at step {step}")
            raise FaultError(f"injected crash@step={step}")

    def on_loader_batch(self, step: int) -> None:
        """Called by the loader before producing (in-epoch) step ``step``."""
        f = self._take("loader_stall", step)
        if f is not None:
            self.log(f"chaos: stalling loader {f.seconds:g}s at step {step}")
            time.sleep(f.seconds)

    def on_save(self, label: int, step_dir: Path) -> None:
        """Called by CheckpointManager after save ``label`` finalized (the
        manifest is already written, so a tear here MUST be caught by the
        integrity verification at restore time)."""
        with self._lock:
            self.saves_seen += 1
            count = self.saves_seen
        if self._take("torn_ckpt", count) is not None:
            tear_checkpoint(Path(step_dir), log=self.log)

    def on_save_finalize(self, label: int) -> None:
        """Called by CheckpointManager between the orbax commit and the
        manifest write (``pre_finalize_hook``) — under async saves, on the
        writer thread. A ``crash_during_save`` fault raises here: the save
        dies half-born (committed step, no manifest, pending marker), the
        torn checkpoint the integrity verification must skip."""
        with self._lock:
            self.finalizes_seen += 1
            count = self.finalizes_seen
        if self._take("crash_during_save", count) is not None:
            self.log(f"chaos: injected crash during save {count} "
                     f"(checkpoint {label}, between orbax commit and "
                     "manifest)")
            raise FaultError(f"injected crash_during_save@save={count} "
                             f"(checkpoint {label})")
