"""resilience/ — fault-tolerant training: liveness, fault injection,
checkpoint-restart supervision.

The reference has no failure story (SURVEY.md §5: a crashed rank hangs the
NCCL job). This subsystem turns "a fault happened" into "the run finished
anyway", composing three pieces that previously existed only in isolation:

* :mod:`.heartbeat` — the generalized relay-port liveness layer
  (``Deathwatch`` + ``LivenessPolicy``), extracted from ``bench.py``'s
  ADVICE-r5-hardened deathwatch so bench and train share ONE source of
  truth for the 8082/8083/8087 relay-port set and the
  bounded-PJRT-close-on-partial-death behavior.
* :mod:`.faults` — deterministic fault injection (``FaultPlan`` /
  ``FaultInjector``): ``crash@step=7``, ``sigterm@step=12``,
  ``torn_ckpt@save=2``, ``loader_stall@step=5:2.5s``. Hooks thread through
  ``training/loop.py``, the checkpoint save path, and ``data/loader.py``,
  and are plain ``None`` when no plan is armed — the hot path is untouched.
* :mod:`.supervisor` — the in-process restart supervisor wrapping the
  epoch loop: on a step/save failure it restores the latest *valid*
  checkpoint (``training/checkpoint.py`` manifest verification skips torn
  ones), replays behind a step fence (no optimizer step double-applies;
  same-seed data order via the deterministic sampler + ``state.step``-folded
  RNG + restored EF residuals) and retries under a bounded
  exponential-backoff-with-jitter ``RetryPolicy``, draining preemptions
  gracefully instead of racing them.

Elasticity is BIDIRECTIONAL (ISSUE 11 shrank, ISSUE 12 grows and crosses
process boundaries):

* :mod:`.elastic` — the N↔M reshard orchestration (``plan_elastic_world``,
  ``reshard_train_state``, the raw cross-process variant
  ``reshard_raw_state``);
* :mod:`.capacity` — the grow-side analog of the Deathwatch: a pollable
  ``CapacityWatch`` registry the ``capacity_return@step=k`` chaos fault
  (or a real cluster probe) feeds, polled by the Supervisor at segment
  boundaries to re-plan UP when preempted capacity returns;
* :mod:`.fleet` — the cross-PROCESS orchestrator: launches ``train.py``
  children, watches exit codes, and relaunches with a *different* world
  size over the shared checkpoint directory (``resilience fleet``).

``python -m distributed_pytorch_training_tpu.resilience chaos`` (also the
``resilience`` console script) runs a scripted fault schedule against a
short CPU-mesh training run and reports recovery stats — the demo and the
test harness in one; ``resilience fleet`` runs the subprocess-relaunch
scenario end to end.
"""

from .capacity import CapacityWatch  # noqa: F401
from .faults import FaultError, FaultInjector, FaultPlan  # noqa: F401
from .heartbeat import Deathwatch, LivenessPolicy  # noqa: F401
from .supervisor import RetryPolicy, RunReport, Supervisor  # noqa: F401
