"""Capacity watcher: the grow-side analog of the Deathwatch (ISSUE 12).

The Deathwatch (heartbeat.py) notices capacity LEAVING — a dead relay, a
lost replica — and turns it into a prompt, recoverable exit. Nothing in
the stack noticed capacity COMING BACK: a run that shrank 8 -> 4 after a
preemption stayed shrunk forever, paying double per-device batch (and the
matching step-time) long after the preempted chips returned. The
:class:`CapacityWatch` closes that half:

* it is a REGISTRY — ``total`` replicas exist in the fleet, ``available``
  of them are currently usable. Replica deaths call :meth:`lose`,
  capacity returns call :meth:`restore` (the chaos injector's
  ``capacity_return@step=k`` fault drives it deterministically; a real
  deployment points ``probe`` at its device/cluster feed);
* it is POLLED, never raced: the Supervisor asks :meth:`poll_grow` at
  SEGMENT BOUNDARIES only — after the segment drained and its checkpoint
  was written — so a grow is always anchored at a durable, labeled
  coordinate (the same discipline as the preemption drain). A mid-step
  capacity blip can never tear a step;
* growing is a RE-PLAN, not a guess: the Supervisor hands the available
  count to its ``replan_cb``, which picks the largest feasible world
  ``<= available`` dividing the FIXED global batch
  (:func:`.elastic.plan_elastic_world`) — capacity that returns in a
  quantity no feasible world can use (5 survivors, batch 16) changes
  nothing.

Thread-safe: the injector's step fence (main thread), a probe thread, and
the Supervisor's boundary poll may all touch the counts.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..telemetry import recorder as _telemetry
from ..utils.locktrace import named_lock


class CapacityWatch:
    """Pollable fleet-capacity registry.

    ``total`` is the full fleet size (replicas). ``available`` starts at
    ``total`` unless given. ``probe`` (optional) is a zero-arg callable
    returning the CURRENT available count from an external source — when
    set, it is consulted (and the internal count synced to it) on every
    :meth:`available` read; ``lose``/``restore`` still work as manual
    overrides between probes (the chaos harness path).

    Probe failures are CONTAINED (ISSUE 20 satellite): a probe that
    raises — or, with ``probe_timeout_s`` set, hangs past the budget —
    degrades that read to the last committed count and emits a loud
    ``capacity_probe_errors`` counter event; it never escapes into the
    Supervisor's boundary poll or grow path. An external feed (GKE/GCE
    preemption watchers, control/probe.py ``FileCapacityFeed``) WILL
    have bad days, and a flaky feed must cost staleness, not the run.
    """

    def __init__(self, total: int, available: Optional[int] = None,
                 probe: Optional[Callable[[], int]] = None,
                 probe_timeout_s: Optional[float] = None):
        if total < 1:
            raise ValueError(f"a fleet needs >= 1 replica, got {total}")
        if probe_timeout_s is not None and probe_timeout_s <= 0:
            raise ValueError("probe_timeout_s must be positive "
                             f"(got {probe_timeout_s})")
        self.total = int(total)
        self._available = int(total if available is None else available)  # guarded-by: _lock
        if not 0 <= self._available <= self.total:
            raise ValueError(
                f"available ({self._available}) must lie in "
                f"[0, total={self.total}]")
        self._probe = probe   # set once here, immutable after
        # hang containment: with a timeout set, probe calls ride ONE
        # lazily-started daemon worker (`_ProbeWorker`) and a call
        # overrunning the budget degrades like a raise. None = direct
        # call (zero threads — the autopilot-off pin); the worker only
        # ever exists when BOTH a probe and a timeout are armed.
        self._probe_timeout_s = probe_timeout_s
        self._probe_worker: Optional[_ProbeWorker] = None  # guarded-by: _worker_lock
        self._worker_lock = threading.Lock()
        self._lock = named_lock("CapacityWatch._lock")
        # set whenever capacity INCREASES (restore / a probe reading above
        # the last one) — a cheap "worth polling" hint for callers that
        # want to wait instead of poll; cleared by poll_grow
        self.returned = threading.Event()

    def _consult_probe(self) -> Optional[int]:
        """One contained probe read: the clamped fresh count, or None
        when the probe raised/hung (degrade to last-known)."""
        try:
            if self._probe_timeout_s is None:
                raw = self._probe()
            else:
                with self._worker_lock:
                    if self._probe_worker is None:
                        self._probe_worker = _ProbeWorker(self._probe)
                    worker = self._probe_worker
                raw = worker.call(self._probe_timeout_s)
            return max(0, min(int(raw), self.total))
        except Exception as e:  # noqa: BLE001 — ANY probe failure is a
            # degraded reading, never a poll/grow-path error
            _telemetry.counter(
                "capacity_probe_errors", 1, error=type(e).__name__,
                detail=str(e)[:200])
            return None

    def available(self) -> int:
        """Current available replica count (probe-synced when armed;
        probe failures degrade to the last committed reading)."""
        # consult the probe OUTSIDE the lock: it is an arbitrary external
        # callable (a device/cluster feed — possibly a network round
        # trip, possibly re-entering this registry), and holding the
        # lock across it would serialize every lose/restore/sync on the
        # slowest probe — and self-deadlock on a re-entrant one
        fresh: Optional[int] = None
        if self._probe is not None:
            fresh = self._consult_probe()
        with self._lock:
            if fresh is not None:
                if fresh > self._available:
                    self.returned.set()
                self._available = fresh
            return self._available

    def lose(self, n: int = 1) -> int:
        """``n`` replicas left the fleet (a replica death); returns the
        new available count (never below 0)."""
        with self._lock:
            self._available = max(0, self._available - int(n))
            return self._available

    def sync(self, available: int) -> int:
        """Set the available count ABSOLUTELY (clamped to [0, total]) —
        the Supervisor's death-restart bookkeeping: a replica death
        re-plans over the SURVIVING ACTIVE replicas (``old_world - 1``),
        and the registry must agree with that decision or the next
        boundary poll would see phantom idle capacity and grow right back
        mid-incident. Capacity genuinely returning is :meth:`restore`
        (the ``capacity_return`` fault / a probe reading)."""
        with self._lock:
            self._available = max(0, min(int(available), self.total))
            return self._available

    def restore(self, n: Optional[int] = None) -> int:
        """``n`` replicas came back (``None`` = all of them: available
        returns to ``total``); returns the new available count."""
        with self._lock:
            if n is None:
                self._available = self.total
            else:
                self._available = min(self.total,
                                      self._available + int(n))
            self.returned.set()
            return self._available

    def poll_grow(self, current_world: Optional[int]) -> Optional[int]:
        """The Supervisor's segment-boundary poll: the available count
        when it EXCEEDS ``current_world`` (a grow may be feasible — the
        replan decides whether a larger world actually divides the global
        batch), else None. Emits a ``capacity_watch`` telemetry span so
        the summary's step-time split accounts the polling, and clears
        :attr:`returned`."""
        with _telemetry.span("capacity_watch", world=current_world):
            avail = self.available()
            # the /metrics capacity gauge: every boundary poll publishes
            # what the fleet registry currently believes is available
            _telemetry.gauge("capacity_available", avail)
            self.returned.clear()
            if current_world is None or avail <= current_world:
                return None
            return avail


class _ProbeWorker:
    """One daemon thread boxing a possibly-hanging probe callable.

    ``call(timeout)`` submits a request and waits at most ``timeout``
    seconds; an overrun raises TimeoutError to the caller while the
    worker keeps running the hung call. The next ``call`` first tries to
    reap that stale result (the probe recovered: discard the old answer,
    submit fresh); while the old call is STILL in flight it fails fast
    with TimeoutError instead of queueing behind a wedged feed — every
    path out of here is a contained degrade in
    ``CapacityWatch._consult_probe``, never a stuck boundary poll."""

    def __init__(self, fn: Callable[[], int]):
        import queue

        self._fn = fn
        self._req: "queue.Queue" = queue.Queue()
        self._res: "queue.Queue" = queue.Queue()
        self._in_flight = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dpt-capacity-probe")
        self._thread.start()

    def _run(self) -> None:
        while True:
            self._req.get()
            try:
                result = ("ok", self._fn())
            except BaseException as e:  # noqa: BLE001 — relayed verbatim
                result = ("err", e)
            self._res.put(result)

    def call(self, timeout: float) -> int:
        import queue

        if self._in_flight.is_set():
            # a previous call overran its budget; reap it if it finished
            try:
                self._res.get_nowait()
                self._in_flight.clear()   # recovered — stale answer dropped
            except queue.Empty:
                raise TimeoutError(
                    "capacity probe still hung from a previous poll")
        self._in_flight.set()
        self._req.put(None)
        try:
            tag, value = self._res.get(timeout=timeout)
        except queue.Empty:
            # leave _in_flight set: the worker is still inside the probe
            raise TimeoutError(
                f"capacity probe exceeded its {timeout:g}s budget")
        self._in_flight.clear()
        if tag == "err":
            raise value
        return value
