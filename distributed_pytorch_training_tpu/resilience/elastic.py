"""Elastic data parallelism: re-plan the mesh on restart, reshard the
restored state from N to M replicas (ISSUE 11 tentpole).

The reference's whole premise is a static ``world_size`` (torch.distributed
init, train_ddp.py:53-68): lose one replica in a preemptible fleet and the
run stalls until the exact same world comes back. Here the flat-padded 1/N
layouts the repo already ships (zero1's weight-update sharding, explicit
FSDP's at-rest params+moments, the int8 wires' EF residuals) make a resize
a RE-SLICE, not a gather:

* **The plan** (:func:`plan_elastic_world`): the largest DP degree ``M <=
  survivors`` that divides the (fixed) global batch. The GLOBAL batch is
  held constant across resizes — per-device batch grows — so the sampler's
  permutation, the steps-per-epoch arithmetic, the step fence, and the
  per-step RNG fold (``state.step``) are all UNCHANGED by a resize; only
  the layout of the same trajectory changes.

* **The reshard** (:func:`reshard_train_state`): leaf-at-a-time host
  re-chunking from the old-N flat-padded layout into a new-M template's
  shapes and shardings — replicated leaves pass through, flat-padded
  leaves truncate-or-zero-extend (`parallel.sharding.reshard_flat_padded`;
  the pad region of a valid flat-padded leaf is exactly zero, so the
  re-slice is EXACT), and the per-replica EF residual rows fold N -> M
  preserving the telescoping column-wise total
  (`parallel.grad_sync.fold_ef_rows`). Never gathers more than one leaf /
  layer group at a time: peak host memory is one leaf beyond the state
  itself.

The Supervisor drives this through ``replan_cb`` (supervisor.py) — on a
``replica_death`` restart (shrink, restore-then-reshard) AND at a
capacity-return segment boundary (grow, live-state reshard M -> N with
zero-extended shards/EF rows, ISSUE 12); :func:`reshard_raw_state` is
the cross-PROCESS arm (a fleet relaunch reshards a template-free raw
restore, resilience/fleet.py + train.py's elastic ``--resume``). The
``resilience chaos --elastic`` harness proves the post-resize segment
bitwise-equal to a clean same-seed continuation at the new world in
both directions (PARITY.md "Exactness model: elastic reshard").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """What a ``replan_cb`` hands back to the Supervisor after a replica
    death: a trainer/loader/state_factory rebuilt on the surviving-device
    mesh at ``world`` batch shards. The loader MUST keep the old run's
    GLOBAL batch (the supervisor rejects a steps-per-epoch change — the
    step fence arithmetic depends on it)."""

    trainer: Any
    loader: Any
    state_factory: Callable[[], Any]
    world: int


def plan_elastic_world(survivors: int, global_batch: int) -> int:
    """The mesh re-plan: largest DP degree ``M <= survivors`` dividing the
    fixed global batch (M=1 always qualifies — a single survivor still
    trains). Not simply ``survivors``: 7 survivors of 8 with a global
    batch of 16 re-plan to 4 — the batch must still split evenly, and a
    non-divisor world would change the per-shard batch shapes mid-run."""
    if survivors < 1:
        raise ValueError(f"cannot re-plan a mesh for {survivors} surviving "
                         "replica(s)")
    if global_batch < 1:
        raise ValueError(f"global batch must be >= 1, got {global_batch}")
    for m in range(min(survivors, global_batch), 0, -1):
        if global_batch % m == 0:
            return m
    return 1


def _place_leaf(value, template_leaf):
    """One host value -> a device array in the template leaf's layout."""
    import jax

    return jax.device_put(
        np.asarray(value).astype(template_leaf.dtype),
        template_leaf.sharding)


def _reshard_and_place(old_tree, template_tree):
    """`parallel.sharding.reshard_flat_leaf` per leaf plus placement, one
    leaf at a time (device_get -> re-chunk -> device_put before the next
    leaf is touched — the bounded-host-memory variant of
    `reshard_flat_tree`); failures name the offending leaf path."""
    import jax

    from ..parallel.sharding import _path_str, reshard_flat_leaf

    def one(path, old, tmpl):
        v = reshard_flat_leaf(jax.device_get(old), tmpl.shape,
                              name=_path_str(path))
        return _place_leaf(v, tmpl)

    return jax.tree_util.tree_map_with_path(one, old_tree, template_tree)


def _reshard_grad_sync(old_gs, template_gs, trainer, old_n: int,
                       new_n: int):
    """Reshard the EF residuals (TrainState.grad_sync) into the new-world
    layout the trainer expects. Three layouts, matched to the trainer's
    engaged mode exactly as Trainer.init_state built them:

    * fsdp: ``{"ef": {group: (n, n*row)}}`` — destination-major per-group
      stacking; rows fold N->M, each row re-chunks leaf-by-leaf
      (`reshard_fsdp_ef_row`, old/new LayerPlans from the shapes-only
      fsdp template — one group in memory at a time);
    * zero1: ``{"ef": per-leaf (n, flat_padded(leaf, n))}`` — rows fold,
      each row truncate-or-extends to the new per-leaf padding;
    * bucketed reducer: ``{"ef": (n, R)}`` with R = flat total ("int8") or
      the padded-per-bucket multihop layout (re-chunked per bucket via
      `reshard_multihop_ef_row`, same bucket_cap_mb on both sides — the
      plan-dependence ef_state_bucketed documents).
    """
    import jax

    from ..parallel.grad_sync import (
        build_layer_plan, fold_ef_rows, reshard_fsdp_ef_row,
    )

    old_leaves = jax.tree_util.tree_leaves(old_gs)
    tmpl_leaves = jax.tree_util.tree_leaves(template_gs)
    if not old_leaves and not tmpl_leaves:
        return template_gs
    if bool(old_leaves) != bool(tmpl_leaves):
        raise ValueError(
            "error-feedback residuals exist on only one side of the "
            "resize (old vs new trainer wire modes differ) — an elastic "
            "resize must keep the training config, only the mesh changes")

    if getattr(trainer, "_fsdp", False):
        tmpl = trainer._fsdp_template
        old_plan = build_layer_plan(tmpl, old_n)
        new_plan = build_layer_plan(tmpl, new_n)
        old_groups = {g.name: g for g in old_plan.groups}
        new_groups = {g.name: g for g in new_plan.groups}
        out = {}
        for name, tmpl_leaf in template_gs["ef"].items():
            rows = fold_ef_rows(
                np.asarray(jax.device_get(old_gs["ef"][name])), new_n)
            new = np.stack([
                reshard_fsdp_ef_row(r, old_groups[name], new_groups[name],
                                    old_n, new_n)
                for r in rows])
            out[name] = _place_leaf(new, tmpl_leaf)
        return {"ef": out}

    if getattr(trainer, "_grad_sync", False):
        # bucketed reducer: one (n, R) array
        tmpl_leaf = template_gs["ef"]
        rows = fold_ef_rows(np.asarray(jax.device_get(old_gs["ef"])),
                            new_n)
        if rows.shape[1] != tmpl_leaf.shape[1]:
            # the multihop padded-per-bucket layout is the only bucketed
            # residual whose length depends on the shard count — it is
            # handled upstream (reshard_train_state's multihop branch)
            raise ValueError(
                "bucketed EF residual length changed across the resize "
                f"({rows.shape[1]} -> {tmpl_leaf.shape[1]}) but the wire "
                "is not int8_multihop — the state was built under a "
                "different bucket plan")
        return {"ef": _place_leaf(rows, tmpl_leaf)}

    # zero1: per-leaf tree of (n, padded) rows
    def one(old, tmpl):
        from ..parallel.sharding import reshard_flat_padded

        rows = fold_ef_rows(np.asarray(jax.device_get(old)), new_n)
        if rows.shape[1] != tmpl.shape[1]:
            rows = np.stack([reshard_flat_padded(r, int(tmpl.shape[1]))
                             for r in rows])
        return _place_leaf(rows, tmpl)

    return {"ef": jax.tree_util.tree_map(one, old_gs["ef"],
                                         template_gs["ef"])}


def reshard_raw_state(arrays: dict, old_n: int, new_n: int, trainer,
                      template) -> Any:
    """Cross-PROCESS elastic restore (ISSUE 12): reshard the RAW host
    arrays of a checkpoint — ``training.checkpoint.CheckpointManager.
    restore_latest_raw``'s output, saved at ``old_n`` — into the current
    run's ``new_n`` layout.

    A relaunched process at a different world size cannot build the old
    world's device templates (that mesh no longer exists here), so the
    checkpoint's own saved shapes ARE the old-world template: the raw
    nested containers are re-treed onto the current template's pytree
    structure positionally (orbax flattens the same TrainState both
    sides, so leaf order matches — checked by leaf count, and every leaf
    then passes the reshard's own shape dispatch), wrapped into a
    pseudo-state, and run through :func:`reshard_train_state`. A
    checkpoint written before EF residuals existed restores with the
    template's zero residuals — error feedback restarts its telescope,
    exactly as the fixed-template restore path does."""
    import jax

    def retree(name: str, tmpl_sub):
        raw_sub = arrays[name]
        leaves = jax.tree_util.tree_leaves(raw_sub)
        treedef = jax.tree_util.tree_structure(tmpl_sub)
        if len(leaves) != treedef.num_leaves:
            raise ValueError(
                f"checkpoint subtree {name!r} holds {len(leaves)} "
                f"array(s) but this run's template expects "
                f"{treedef.num_leaves} — the relaunch changed the model/"
                "optimizer/wire configuration, not just the world size "
                "(an elastic relaunch must keep the training config)")
        return jax.tree_util.tree_unflatten(treedef, leaves)

    raw_gs = arrays.get("grad_sync")
    pseudo = template.replace(
        step=np.asarray(arrays["step"]),
        params=retree("params", template.params),
        opt_state=retree("opt_state", template.opt_state),
        batch_stats=retree("batch_stats", template.batch_stats),
        grad_sync=(retree("grad_sync", template.grad_sync)
                   if raw_gs is not None else {}))
    if raw_gs is None:
        # pre-EF checkpoint into an EF template: reshard everything else,
        # keep the template's zero residuals (a fresh telescope start)
        out = reshard_train_state(pseudo, old_n, new_n, trainer,
                                  template.replace(grad_sync={}))
        return out.replace(grad_sync=template.grad_sync)
    return reshard_train_state(pseudo, old_n, new_n, trainer, template)


def reshard_train_state(state, old_n: int, new_n: int, trainer,
                        template) -> Any:
    """Reshard a restored TrainState from the old-N layout into the new-M
    ``template``'s layout (a fresh ``trainer.init_state(...)`` output on
    the new mesh — used for SHAPES, dtypes and shardings only; its values
    are discarded).

    Exactness (PARITY.md): the re-slice is value-exact — replicated leaves
    and the true region of every flat-padded leaf are copied bit-for-bit,
    pad regions are zeros on both sides, and the EF residual column totals
    are preserved. The new mesh placement changes WHERE bytes live, never
    what they are. One leaf (one layer group for fsdp EF) is gathered to
    host at a time."""
    import jax

    new_params = _reshard_and_place(state.params, template.params)
    new_opt = _reshard_and_place(state.opt_state, template.opt_state)
    new_stats = _reshard_and_place(state.batch_stats, template.batch_stats)
    multihop_bucketed = (
        getattr(trainer, "_grad_sync", False)
        and trainer.config.wire_dtype == "int8_multihop"
        and jax.tree_util.tree_leaves(state.grad_sync))
    if multihop_bucketed:
        from ..parallel.grad_sync import (
            build_bucket_plan, fold_ef_rows, reshard_multihop_ef_row,
        )

        # the multihop residual re-chunks per bucket, against the SAME
        # bucket plan (same cap, model-shaped params) on both sides —
        # the bucketed reducer only runs with replicated params
        plan = build_bucket_plan(template.params,
                                 trainer.config.bucket_cap_mb)
        rows = fold_ef_rows(
            np.asarray(jax.device_get(state.grad_sync["ef"])), new_n)
        rows = np.stack([reshard_multihop_ef_row(r, plan, old_n, new_n)
                         for r in rows])
        new_gs = {"ef": _place_leaf(rows, template.grad_sync["ef"])}
    else:
        new_gs = _reshard_grad_sync(state.grad_sync, template.grad_sync,
                                    trainer, old_n, new_n)
    return template.replace(
        step=_place_leaf(jax.device_get(state.step), template.step),
        params=new_params, opt_state=new_opt, batch_stats=new_stats,
        grad_sync=new_gs)


def adopt_state(state, template):
    """Carry a live TrainState into a SAME-WORLD template built under a
    different training config (the control plane's segment-boundary
    retune, ISSUE 20).

    Per leaf path: when the template has a leaf of identical shape and
    dtype at the same path, the live value is carried — placed into the
    template leaf's sharding, bit-for-bit (params, optimizer moments,
    batch stats, the step counter: a config re-plan must not move the
    trajectory). Leaves the new config re-shapes or introduces (a wire
    change swaps the error-feedback residual layout; fp32 -> compressed
    grows one) take the template's FRESH value — exactly the state a
    same-config restart from the boundary checkpoint would start with,
    which is the retune's stated exactness model (PARITY.md "Control
    decisions never change numerics").

    Returns ``(new_state, resets)`` where ``resets`` names the leaf
    paths that took the template's value — the retune decision records
    them, so a reset EF buffer is an audit-trail fact, not a surprise.
    """
    import jax

    old_leaves = {
        jax.tree_util.keystr(path): leaf
        for path, leaf in jax.tree_util.tree_leaves_with_path(state)}
    resets = []

    def pick(path, tmpl_leaf):
        key = jax.tree_util.keystr(path)
        old = old_leaves.get(key)
        if (old is not None
                and getattr(old, "shape", None) == getattr(tmpl_leaf,
                                                           "shape", None)
                and getattr(old, "dtype", None) == getattr(tmpl_leaf,
                                                           "dtype", None)):
            return _place_leaf(jax.device_get(old), tmpl_leaf)
        resets.append(key)
        return tmpl_leaf

    new_state = jax.tree_util.tree_map_with_path(pick, template)
    return new_state, resets
