"""Cross-process fleet orchestrator (ISSUE 12): relaunch ``train.py``
children at whatever world size the fleet actually has.

The in-process elastic path (supervisor.py + elastic.py) resizes over
surviving LOCAL devices — but a real preemptible fleet loses whole
processes/hosts, and the relaunch comes back with a *different process
count*, not a shrunken in-process mesh. This module is the external half:

* **launch** a training child per *generation* (``argv_for`` builds the
  command; the launch generation + rank ride the env —
  ``DPT_FLEET_GENERATION`` / ``DPT_FLEET_RANK`` — and every flight the
  child flushes carries them in its cause, telemetry/flight.py);
* **watch the exit code**: rc=0 with the target step reached is
  completion; rc=0 short of it is a drained preemption (train.py's
  SIGTERM drain checkpoints and exits clean); rc=70 is the Deathwatch
  contract (heartbeat.py); anything else is a crash. Progress is probed
  from the checkpoint directory's integrity MANIFESTS alone
  (:func:`checkpoint_progress`) — the orchestrator is jax/orbax-free by
  design, it must never initialize a backend;
* **relaunch at the capacity the fleet has**: each generation asks the
  capacity feed (scripted in the harness; a cluster API in production)
  and plans the largest feasible world ``<= available`` dividing the
  fixed global batch (:func:`.elastic.plan_elastic_world`) — the child is
  launched with that many devices and ``--mesh data=<world>``, resuming
  over the SHARED checkpoint directory. Cross-world restores ride
  train.py's elastic ``--resume`` (raw restore + reshard;
  ``CheckpointWorldSizeMismatch`` never escapes a relaunch — the
  orchestrator scans child logs and counts any escape as a hard error).

``resilience fleet`` (:func:`fleet_main`) runs the canonical CPU-mesh
scenario end to end: kill at full world → relaunch at half world →
capacity returns → relaunch at full world, then verifies one flight per
abnormal child exit and (``--verify-parity``) that the final segment is
bitwise-equal to an uninterrupted control child continuing from the last
relaunch point.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..telemetry.aggregate import (
    StreamFollower,
    aggregate_segments,
    last_step_of,
    split_streams,
    stitch_perfetto,
)
from ..telemetry.flight import FLEET_GENERATION_ENV, FLEET_RANK_ENV
from ..telemetry.metrics_http import METRICS_PORT_ENV
from ..telemetry.recorder import stream_filename
from .elastic import plan_elastic_world
from .heartbeat import DEATHWATCH_EXIT_CODE

# FLEET_GENERATION_ENV / FLEET_RANK_ENV are telemetry/flight.py's (one
# definition: the reader of the stamp owns the names) — re-exported here
# because the orchestrator is the writer.
__all__ = ["FLEET_GENERATION_ENV", "FLEET_RANK_ENV", "FleetOrchestrator",
           "FleetLaunch", "FleetReport", "ReplicaProc", "ServingFleet",
           "checkpoint_progress", "check_fleet_flights", "fleet_main"]

# runtime/dist.py's multi-host rendezvous contract (setup_distributed):
# the orchestrator is the WRITER of these stamps, the child's
# jax.distributed.initialize the reader — one generation spanning
# `hosts` processes rendezvouses through them (ISSUE 20).
DIST_COORD_ENV = "DPT_COORDINATOR_ADDRESS"
DIST_NPROC_ENV = "DPT_NUM_PROCESSES"
DIST_PROC_ID_ENV = "DPT_PROCESS_ID"

_DEVICE_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _stderr_log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _xla_flags_for(world: int, base: str = "") -> str:
    """``base`` XLA flags with the host-platform device count replaced by
    ``world`` — the CPU-mesh stand-in for launching a child on a fleet of
    ``world`` chips (any inherited count, e.g. the test harness's 8, must
    not leak into a half-world child)."""
    kept = [f for f in (base or "").split()
            if not f.startswith(_DEVICE_COUNT_FLAG)]
    kept.append(f"{_DEVICE_COUNT_FLAG}={world}")
    return " ".join(kept)


def checkpoint_progress(ckpt_dir) -> Tuple[int, Optional[int]]:
    """``(step, world_size)`` of the newest FINALIZED checkpoint, read
    from the integrity manifests alone (``.manifests/<label>.json``,
    training/checkpoint.py) — no jax, no orbax, no backend. A label whose
    ``.pending`` marker survives without a manifest never finalized and
    does not count. ``(-1, None)`` when nothing is finalized."""
    mdir = Path(ckpt_dir) / ".manifests"
    best_label, best = -1, (-1, None)
    if not mdir.is_dir():
        return best
    for p in mdir.glob("*.json"):
        try:
            label = int(p.stem)
            body = json.loads(p.read_text())
            step = int(body.get("step", -1))
        except (ValueError, OSError):
            continue  # torn/foreign manifest: not progress
        if label > best_label:
            best_label = label
            world = body.get("world_size")
            best = (step, int(world) if world is not None else None)
    return best


@dataclasses.dataclass
class FleetLaunch:
    """One child launch: what ran, how it exited, what progress it left."""

    generation: int
    world: int
    available: int
    resume: bool
    argv: List[str] = dataclasses.field(default_factory=list)
    # multi-host generations (ISSUE 20): exit codes of ranks 1..hosts-1
    # (rank 0's rc stays in `rc` — it is the generation's verdict; any
    # non-zero peer marks the generation crashed)
    peer_rcs: List[int] = dataclasses.field(default_factory=list)
    rc: Optional[int] = None
    seconds: float = 0.0
    outcome: str = "launched"   # completed | drained | crashed | relay_death
    step_after: int = -1
    log_path: str = ""
    # live observability (ISSUE 14): the largest step seen in the child's
    # telemetry stream WHILE it ran (the tail thread's progress probe),
    # and the /metrics smoke verdict when a metrics port was stamped
    # (None = no port / never scrapeable before exit)
    live_last_step: int = -1
    metrics_scrapes: int = 0
    metrics_ok: Optional[bool] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FleetReport:
    """The orchestrator's verdict (the ``resilience fleet`` JSON body)."""

    target_step: int = -1
    completed: bool = False
    relaunches: int = 0
    final_step: int = -1
    final_world: Optional[int] = None
    mismatch_escapes: int = 0   # CheckpointWorldSizeMismatch in child logs
    launches: List[dict] = dataclasses.field(default_factory=list)
    errors: List[str] = dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FleetOrchestrator:
    """Launch-watch-relaunch over a shared checkpoint directory.

    ``argv_for(world, generation, resume)`` builds one child's command
    line (the CLI builds a train.py invocation; tests use stub scripts).
    ``capacity_for`` is the capacity feed: a callable ``generation ->
    available replicas``, or a sequence whose last value repeats — the
    scripted stand-in for a cluster's capacity API. ``global_batch`` is
    FIXED across generations (the elastic invariant: per-device batch
    changes, the trajectory doesn't). ``target_step`` decides completion:
    a child exiting rc=0 short of it was drained (preempted), not done.
    ``on_child_exit(generation, launch)`` fires after every child exit —
    the CLI snapshots the checkpoint directory there for the parity
    control. ``set_child_devices=True`` pins each child to a CPU mesh of
    exactly ``world`` virtual devices (JAX_PLATFORMS=cpu + XLA_FLAGS);
    pass False when ``argv_for`` manages the child environment itself.

    Live observability (ISSUE 14): ``telemetry_dir`` names the directory
    the children write their telemetry streams into — when set, the
    orchestrator TAILS the per-rank stream while each child runs and
    logs per-generation progress lines (``gen G live — step S``), so a
    fleet run is watchable without attaching to any child.
    ``metrics_port`` stamps ``DPT_METRICS_PORT`` (+rank offset) into the
    child env so every child serves /metrics + /healthz, and the watch
    loop smoke-scrapes it (``launch.metrics_ok``).

    Federation (ISSUE 15): ``federation_port`` additionally runs ONE
    fan-in proxy (telemetry/metrics_http.FederationServer) over the
    children's per-rank ports for the whole fleet run — a single
    Prometheus scrape target whose every series is gen/rank-labelled
    (identities read from each child's own ``dpt_build_info``), with
    exited generations' last pages kept in the merge marked down. The
    final merged page lands in ``self.federation_page`` after
    :meth:`run`.

    Multi-host generations (ISSUE 20): ``hosts > 1`` makes one
    generation span ``hosts`` processes. The orchestrator stamps the
    ``runtime.setup_distributed`` rendezvous contract into every child's
    env — ``DPT_COORDINATOR_ADDRESS`` (``127.0.0.1:coordinator_port +
    generation``, advancing per generation so a relaunch never races the
    previous coordinator's socket), ``DPT_NUM_PROCESSES=hosts`` and a
    per-child ``DPT_PROCESS_ID`` — launches ranks 1..hosts-1 alongside
    rank 0, and gives each child ``world // hosts`` local devices. Rank
    0 stays the watched child whose rc names the outcome; a non-zero
    peer rc marks the generation ``crashed`` (the collective world was
    torn) and a peer outliving rank 0 is killed after a grace window.
    ``argv_for`` is then called with an extra ``rank`` kwarg, and the
    federation proxy fans in over ``hosts`` per-rank metrics ports.
    """

    def __init__(self, argv_for: Callable[..., List[str]], ckpt_dir,
                 *, global_batch: int, target_step: int,
                 capacity_for: Union[Callable[[int], int], Sequence[int]],
                 max_launches: int = 8,
                 env_extra: Optional[Dict[str, str]] = None,
                 set_child_devices: bool = True,
                 on_child_exit: Optional[Callable[..., None]] = None,
                 log_dir=None,
                 telemetry_dir=None,
                 metrics_port: Optional[int] = None,
                 federation_port: Optional[int] = None,
                 hosts: int = 1,
                 coordinator_port: Optional[int] = None,
                 progress_poll_s: float = 0.5,
                 log: Callable[[str], None] = _stderr_log):
        if max_launches < 1:
            raise ValueError(f"max_launches must be >= 1, "
                             f"got {max_launches}")
        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        if hosts > 1 and coordinator_port is None:
            raise ValueError(
                "multi-host generations need a coordinator_port (the "
                "DPT_COORDINATOR_ADDRESS rendezvous every child of a "
                "generation initializes through)")
        self.argv_for = argv_for
        self.ckpt_dir = Path(ckpt_dir)
        self.global_batch = int(global_batch)
        self.target_step = int(target_step)
        self._capacity = (capacity_for if callable(capacity_for)
                          else self._sequence_feed(capacity_for))
        self.max_launches = int(max_launches)
        self.env_extra = dict(env_extra or {})
        self.set_child_devices = set_child_devices
        self.on_child_exit = on_child_exit
        self.log_dir = Path(log_dir) if log_dir is not None \
            else self.ckpt_dir / "fleet_logs"
        self.telemetry_dir = (Path(telemetry_dir)
                              if telemetry_dir is not None else None)
        self.metrics_port = metrics_port
        self.federation_port = federation_port
        self.federation_page: Optional[str] = None
        # multi-host generations (ISSUE 20): one generation = `hosts`
        # children rendezvousing via runtime.setup_distributed's env
        # contract; argv_for is then called with a `rank` kwarg per child
        self.hosts = int(hosts)
        self.coordinator_port = (int(coordinator_port)
                                 if coordinator_port is not None else None)
        self.progress_poll_s = float(progress_poll_s)
        self.log = log

    @staticmethod
    def _sequence_feed(seq: Sequence[int]) -> Callable[[int], int]:
        values = [int(v) for v in seq]
        if not values:
            raise ValueError("capacity sequence is empty")

        def feed(generation: int) -> int:
            return values[min(generation, len(values) - 1)]

        return feed

    def _child_env(self, world: int, generation: int,
                   rank: int = 0) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.env_extra)
        env[FLEET_GENERATION_ENV] = str(generation)
        env[FLEET_RANK_ENV] = str(rank)
        if self.metrics_port:
            # stamp the BASE port: the child applies its own rank offset
            # (resolve_metrics_port reads DPT_FLEET_RANK), so stamping
            # base+rank here would offset twice — co-hosted ranks get
            # base+0, base+1, ... from one stamped value
            env[METRICS_PORT_ENV] = str(int(self.metrics_port))
        local_world = world
        if self.hosts > 1:
            # one generation spans `hosts` processes: each child reads
            # this rendezvous contract in runtime.setup_distributed()
            # (jax.distributed.initialize) and owns world/hosts local
            # devices. The coordinator port advances per generation —
            # a relaunch must not race the previous coordinator's socket
            # in TIME_WAIT.
            env[DIST_COORD_ENV] = (
                f"127.0.0.1:{self.coordinator_port + generation}")
            env[DIST_NPROC_ENV] = str(self.hosts)
            env[DIST_PROC_ID_ENV] = str(rank)
            local_world = max(1, world // self.hosts)
        if self.set_child_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = _xla_flags_for(local_world,
                                              env.get("XLA_FLAGS", ""))
        return env

    def _outcome(self, rc: int, step_after: int) -> str:
        if rc == 0:
            return ("completed" if step_after >= self.target_step
                    else "drained")
        if rc == DEATHWATCH_EXIT_CODE:
            return "relay_death"
        return "crashed"

    def _scrape_metrics(self, port: int) -> Optional[str]:
        """One best-effort /metrics scrape of a running child — the
        shared telemetry helper (a child mid-compile simply has no
        listener yet and that is not an error)."""
        from ..telemetry.metrics_http import scrape_metrics

        return scrape_metrics(port)

    def _watch_child(self, proc: "subprocess.Popen", launch: FleetLaunch,
                     generation: int) -> None:
        """Block until the child exits, tailing its telemetry stream for
        live per-generation progress lines and smoke-scraping /metrics
        when a port was stamped. A child with no stream (stub tests,
        --no-telemetry) just waits — the poll loop costs nothing."""
        follower = None
        if self.telemetry_dir is not None:
            # start at the file's CURRENT end: earlier generations
            # appended to the same stream, and their steps are not this
            # child's progress (events are also gen-filtered below — the
            # seek just avoids re-parsing the whole backlog per child)
            follower = StreamFollower(self.telemetry_dir
                                      / stream_filename(0),
                                      start_at_end=True)
        # the child listens on base + its rank (resolve_metrics_port);
        # today's children are single-process rank 0
        port = (int(self.metrics_port) if self.metrics_port else 0)
        last_logged = -1
        while True:
            try:
                proc.wait(timeout=self.progress_poll_s)
                break
            except subprocess.TimeoutExpired:
                pass
            if follower is not None:
                launch.live_last_step = last_step_of(
                    follower.poll(), launch.live_last_step,
                    gen=generation)
                if launch.live_last_step > last_logged:
                    last_logged = launch.live_last_step
                    self.log(f"fleet: generation {generation} live — "
                             f"step {last_logged + 1}/"
                             f"{self.target_step} (world {launch.world})")
            if port:
                body = self._scrape_metrics(port)
                if body is not None:
                    launch.metrics_scrapes += 1
                    ok = "dpt_steps_total" in body
                    # the smoke holds once ANY successful scrape carried
                    # the step counter — later scrapes can only confirm
                    launch.metrics_ok = bool(launch.metrics_ok) or ok
        # drain whatever the stream gained between the last poll and exit
        if follower is not None:
            launch.live_last_step = last_step_of(
                follower.poll(), launch.live_last_step, gen=generation)

    def _rank_argv(self, world: int, generation: int, resume: bool,
                   rank: int) -> List[str]:
        """One child's command line. Single-host keeps the historical
        ``argv_for(world, generation, resume)`` contract untouched;
        multi-host generations pass the child's rank so the builder can
        address per-rank artifacts (stub tests, per-rank output dirs) —
        topology itself rides the env, not the argv."""
        if self.hosts == 1:
            return list(self.argv_for(world=world, generation=generation,
                                      resume=resume))
        return list(self.argv_for(world=world, generation=generation,
                                  resume=resume, rank=rank))

    def _launch_peers(self, world: int, generation: int,
                      resume: bool) -> List["subprocess.Popen"]:
        peers: List["subprocess.Popen"] = []
        try:
            for rank in range(1, self.hosts):
                p_log = self.log_dir / f"gen{generation}_rank{rank}.log"
                lf = open(p_log, "wb")
                try:
                    peers.append(subprocess.Popen(
                        self._rank_argv(world, generation, resume, rank),
                        env=self._child_env(world, generation, rank=rank),
                        stdout=lf, stderr=subprocess.STDOUT))
                finally:
                    lf.close()  # the child holds its own dup of the fd
        except BaseException:
            for p in peers:
                p.kill()
            for p in peers:
                p.wait()
            raise
        return peers

    def _wait_peers(self, peers: List["subprocess.Popen"],
                    launch: FleetLaunch, report: FleetReport,
                    generation: int, grace_s: float = 60.0) -> None:
        """Collect ranks 1..hosts-1 after rank 0 exited. A peer outliving
        rank 0 by the grace window is wedged (a torn rendezvous blocks in
        a collective forever) — killed and recorded, never waited on
        unboundedly."""
        for rank, p in enumerate(peers, start=1):
            try:
                launch.peer_rcs.append(int(p.wait(timeout=grace_s)))
            except subprocess.TimeoutExpired:
                p.kill()
                launch.peer_rcs.append(int(p.wait()))
                report.errors.append(
                    f"generation {generation}: rank {rank} outlived "
                    f"rank 0 by {grace_s:.0f}s and was killed")

    def run(self) -> FleetReport:
        report = FleetReport(target_step=self.target_step)
        self.log_dir.mkdir(parents=True, exist_ok=True)
        federation = None
        if self.federation_port and self.metrics_port:
            from ..telemetry.metrics_http import FederationServer

            # background refresh faster than the child watch poll: a
            # short-lived generation must still land in the cache before
            # it exits (the final merged page carries every generation)
            # one target per co-hosted rank: every child of a multi-host
            # generation listens on base + its fleet rank, and the fan-in
            # merges them all into one gen/rank-labelled page
            federation = FederationServer(
                int(self.federation_port),
                targets=[int(self.metrics_port) + r
                         for r in range(self.hosts)],
                refresh_s=min(0.3, self.progress_poll_s))
            try:
                port = federation.start()
                self.log(f"fleet: federated /metrics on :{port} "
                         f"(fan-in over child port {self.metrics_port})")
            except OSError as e:
                self.log(f"fleet: federation port "
                         f"{self.federation_port} could not bind ({e}) — "
                         "continuing without the fan-in")
                federation = None
        try:
            return self._run_generations(report)
        finally:
            if federation is not None:
                # one last fan-out so a child that exited between polls
                # is still merged, then keep the final page for the CLI
                federation.refresh()
                self.federation_page = federation.render()
                federation.stop()

    def _run_generations(self, report: FleetReport) -> FleetReport:
        for generation in range(self.max_launches):
            available = int(self._capacity(generation))
            world = plan_elastic_world(available, self.global_batch)
            step_before, _ = checkpoint_progress(self.ckpt_dir)
            resume = step_before >= 0
            argv = self._rank_argv(world, generation, resume, rank=0)
            launch = FleetLaunch(generation=generation, world=world,
                                 available=available, resume=resume,
                                 argv=list(argv))
            log_path = self.log_dir / f"gen{generation}.log"
            launch.log_path = str(log_path)
            self.log(f"fleet: generation {generation} — launching world "
                     f"{world} ({available} available"
                     + (f", {self.hosts} host(s)" if self.hosts > 1
                        else "")
                     + (", --resume" if resume else ", fresh") + ")")
            t0 = time.perf_counter()
            peers: List["subprocess.Popen"] = []
            with open(log_path, "wb") as lf:
                proc = subprocess.Popen(
                    argv, env=self._child_env(world, generation),
                    stdout=lf, stderr=subprocess.STDOUT)
                try:
                    # peers 1..hosts-1 of a multi-host generation launch
                    # NOW: the whole generation rendezvouses through the
                    # stamped coordinator before any child trains
                    peers = self._launch_peers(world, generation, resume)
                    self._watch_child(proc, launch, generation)
                    self._wait_peers(peers, launch, report, generation)
                except BaseException:
                    # subprocess.run's contract, kept: Ctrl-C (or a
                    # raising watch callback) must not orphan a running
                    # training child — it would keep writing the shared
                    # checkpoint dir and holding the metrics port
                    for p in [proc] + peers:
                        p.kill()
                    for p in [proc] + peers:
                        p.wait()
                    raise
            launch.rc = proc.returncode
            launch.seconds = round(time.perf_counter() - t0, 3)
            step_after, world_after = checkpoint_progress(self.ckpt_dir)
            launch.step_after = step_after
            launch.outcome = self._outcome(launch.rc, step_after)
            if launch.outcome in ("completed", "drained") \
                    and any(rc != 0 for rc in launch.peer_rcs):
                # rank 0 exiting clean does not absolve a dead peer: the
                # generation's collective world was torn
                launch.outcome = "crashed"
            try:
                text = log_path.read_text(errors="replace")
            except OSError:
                text = ""
            if "CheckpointWorldSizeMismatch" in text:
                # the acceptance gate: every cross-world restore must ride
                # the elastic resume path — a named mismatch reaching a
                # child's output means a relaunch DIED on (or even just
                # warned about) the exact failure this orchestrator exists
                # to absorb
                report.mismatch_escapes += 1
                report.errors.append(
                    f"generation {generation}: CheckpointWorldSizeMismatch"
                    " escaped into the child log")
            self.log(f"fleet: generation {generation} exited rc="
                     f"{launch.rc} after {launch.seconds:.1f}s — "
                     f"{launch.outcome} (checkpoint step {step_after}/"
                     f"{self.target_step})")
            report.launches.append(launch.as_dict())
            report.final_step = step_after
            report.final_world = world_after
            if self.on_child_exit is not None:
                self.on_child_exit(generation, launch)
            if launch.outcome == "completed":
                report.completed = True
                break
        report.relaunches = max(0, len(report.launches) - 1)
        if not report.completed:
            report.errors.append(
                f"fleet did not reach step {self.target_step} within "
                f"{self.max_launches} launch(es)")
        return report


@dataclasses.dataclass
class ReplicaProc:
    """One serving replica child under `ServingFleet`: the live process,
    plus the death/relaunch history the report commits."""

    rank: int
    proc: Optional["subprocess.Popen"] = None
    relaunches: int = 0
    rc_history: List[int] = dataclasses.field(default_factory=list)
    log_paths: List[str] = dataclasses.field(default_factory=list)

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ServingFleet:
    """N LONG-LIVED serving replicas under one supervisor — the serving
    sibling of `FleetOrchestrator` (which runs training children one
    generation at a time; a serving fleet runs its replicas
    CONCURRENTLY, forever).

    * ``argv_for(rank, generation)`` builds each replica's command (the
      CLI passes ``serving serve --port base+rank --metrics-port ...``;
      tests pass stubs — the supervisor is jax-free by the same design
      rule as the training orchestrator and never inspects the argv);
    * a replica that EXITS is relaunched (generation + 1, same rank)
      until its ``max_relaunches`` budget is spent — a router in front
      sees the gap as a failed /healthz and resubmits in the meantime;
    * ``drain()`` is the SIGTERM contract fleet-wide: forward the signal
      to every live child (each drains its own queue), wait, collect rcs;
    * ``federation_port`` serves ONE merged /metrics page over the
      replicas' ports (telemetry FederationServer) — the per-replica
      ``serving_queue_depth`` / slot-occupancy gauges land on a single
      dashboard, each row stamped with its replica's identity.

    Generation + rank ride the child env exactly as training launches do
    (``DPT_FLEET_GENERATION`` / ``DPT_FLEET_RANK``), so a dying replica's
    flight is attributable to its slot in the fleet.
    """

    def __init__(self, argv_for: Callable[..., Sequence[str]],
                 replicas: int,
                 metrics_ports: Optional[Sequence[int]] = None,
                 federation_port: Optional[int] = None,
                 log_dir=None, env_extra: Optional[Dict[str, str]] = None,
                 set_child_devices: bool = True, world: int = 8,
                 max_relaunches: int = 2, poll_s: float = 0.2,
                 log: Callable[[str], None] = _stderr_log):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if metrics_ports is not None and len(metrics_ports) != replicas:
            raise ValueError(
                f"metrics_ports must name one port per replica, got "
                f"{len(metrics_ports)} for {replicas}")
        self.argv_for = argv_for
        self.n_replicas = int(replicas)
        self.metrics_ports = (list(int(p) for p in metrics_ports)
                              if metrics_ports else None)
        self.federation_port = federation_port
        self.log_dir = Path(log_dir) if log_dir is not None \
            else Path(tempfile.mkdtemp(prefix="serving_fleet_"))
        self.env_extra = dict(env_extra or {})
        self.set_child_devices = set_child_devices
        self.world = int(world)
        self.max_relaunches = int(max_relaunches)
        self.poll_s = float(poll_s)
        self.log = log
        self.replicas: List[ReplicaProc] = [
            ReplicaProc(rank=r) for r in range(self.n_replicas)]
        self.federation_page: Optional[str] = None
        self._federation = None

    def _child_env(self, rank: int, generation: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.env_extra)
        env[FLEET_GENERATION_ENV] = str(generation)
        env[FLEET_RANK_ENV] = str(rank)
        if self.set_child_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = _xla_flags_for(self.world,
                                              env.get("XLA_FLAGS", ""))
        return env

    def _spawn(self, rep: ReplicaProc) -> None:
        generation = rep.relaunches
        argv = list(self.argv_for(rank=rep.rank, generation=generation))
        log_path = self.log_dir / f"replica{rep.rank}_gen{generation}.log"
        rep.log_paths.append(str(log_path))
        lf = open(log_path, "wb")
        try:
            rep.proc = subprocess.Popen(
                argv, env=self._child_env(rep.rank, generation),
                stdout=lf, stderr=subprocess.STDOUT)
        finally:
            # the child holds its own dup of the fd; Popen failure must
            # not leak ours either
            lf.close()
        self.log(f"serving fleet: replica {rep.rank} up "
                 f"(generation {generation}, pid {rep.proc.pid})")

    def start(self) -> None:
        self.log_dir.mkdir(parents=True, exist_ok=True)
        if self.federation_port and self.metrics_ports:
            from ..telemetry.metrics_http import FederationServer

            self._federation = FederationServer(
                int(self.federation_port),
                targets=self.metrics_ports, refresh_s=self.poll_s)
            try:
                port = self._federation.start()
                self.log(f"serving fleet: federated /metrics on :{port} "
                         f"(fan-in over {self.metrics_ports})")
            except OSError as e:
                self.log(f"serving fleet: federation port "
                         f"{self.federation_port} could not bind ({e}) — "
                         "continuing without the fan-in")
                self._federation = None
        for rep in self.replicas:
            self._spawn(rep)

    def poll(self) -> int:
        """One supervision pass: collect exits, relaunch within budget.
        Returns how many replicas are currently alive."""
        alive = 0
        for rep in self.replicas:
            if rep.alive:
                alive += 1
                continue
            if rep.proc is not None and rep.proc.returncode is not None \
                    and (not rep.rc_history
                         or len(rep.rc_history) <= rep.relaunches):
                rc = rep.proc.returncode
                rep.rc_history.append(rc)
                self.log(f"serving fleet: replica {rep.rank} exited "
                         f"rc={rc} (generation {rep.relaunches})")
                if rep.relaunches < self.max_relaunches:
                    rep.relaunches += 1
                    self._spawn(rep)
                    alive += 1
                else:
                    self.log(f"serving fleet: replica {rep.rank} relaunch "
                             f"budget spent ({self.max_relaunches}) — "
                             "leaving it down")
        return alive

    def run(self, stop, duration_s: Optional[float] = None) -> int:
        """Supervise until ``stop`` is set (or ``duration_s`` elapses),
        then drain. Returns the number of replicas still alive at drain
        time."""
        deadline = (time.perf_counter() + duration_s
                    if duration_s is not None else None)
        try:
            while not stop.is_set():
                self.poll()
                if deadline is not None and \
                        time.perf_counter() >= deadline:
                    break
                stop.wait(self.poll_s)
        finally:
            alive = sum(1 for r in self.replicas if r.alive)
            self.drain()
        return alive

    def kill_replica(self, rank: int) -> None:
        """Chaos hook: hard-kill one replica (the injected death the
        acceptance drill routes around)."""
        rep = self.replicas[rank]
        if rep.alive:
            rep.proc.kill()
            rep.proc.wait()

    def drain(self, grace_s: float = 30.0) -> List[Optional[int]]:
        """SIGTERM every live replica (each drains its own queue), wait
        up to ``grace_s`` each, then collect return codes (kill-on-
        timeout — a wedged replica must not hang the supervisor)."""
        for rep in self.replicas:
            if rep.alive:
                rep.proc.terminate()
        rcs: List[Optional[int]] = []
        for rep in self.replicas:
            if rep.proc is None:
                rcs.append(None)
                continue
            try:
                rcs.append(rep.proc.wait(timeout=grace_s))
            except subprocess.TimeoutExpired:
                self.log(f"serving fleet: replica {rep.rank} ignored "
                         f"SIGTERM for {grace_s:.0f}s — killing")
                rep.proc.kill()
                rcs.append(rep.proc.wait())
        if self._federation is not None:
            self._federation.refresh()
            self.federation_page = self._federation.render()
            self._federation.stop()
            self._federation = None
        return rcs

    def report(self) -> dict:
        return {
            "replicas": self.n_replicas,
            "per_replica": [{
                "rank": r.rank,
                "relaunches": r.relaunches,
                "rc_history": list(r.rc_history),
                "alive": r.alive,
            } for r in self.replicas],
            "federation_page": bool(self.federation_page),
        }


# ---------------------------------------------------------------------------
# the `resilience fleet` CLI scenario: train.py children on the CPU mesh
# ---------------------------------------------------------------------------


def _repo_train_py() -> Path:
    path = Path(__file__).resolve().parents[2] / "train.py"
    if not path.is_file():
        raise FileNotFoundError(
            f"train.py not found at {path} — `resilience fleet` drives "
            "the repo checkout's training entry point")
    return path


def _train_argv(args, world: int, resume: bool, chaos: Optional[str],
                ckpt_dir: str, out_dir: str) -> List[str]:
    """One train.py child: the tiny synthetic-CIFAR ResNet workload
    (augmentation off, fp32 — bitwise parity is the acceptance bar),
    sized so per-device batch = global_batch / world at every world."""
    if args.global_batch % world:
        raise ValueError(f"global batch {args.global_batch} does not "
                         f"divide over world {world}")
    argv = [sys.executable, str(_repo_train_py()),
            "--model", "resnet18",
            "--model-overrides", "num_filters=4",
            "--cifar-stem", "--no-augment",
            "--dataset", "cifar10", "--synthetic",
            "--synthetic-size", str(args.synthetic_size),
            "--epochs", str(args.epochs),
            "--batch-size", str(args.global_batch // world),
            "--mesh", f"data={world}",
            "--seed", str(args.seed),
            "--lr", "0.05",
            "--print-freq", "1000",
            "--checkpoint-dir", ckpt_dir,
            "--checkpoint-every", "1",
            "--output-dir", out_dir]
    if args.layout == "zero1":
        argv.append("--zero1")
    elif args.layout == "fsdp":
        argv.append("--fsdp-explicit")
    if args.wire_dtype != "fp32":
        argv += ["--wire-dtype", args.wire_dtype]
    if resume:
        argv.append("--resume")
    if chaos:
        argv += ["--chaos", chaos]
    return argv


def _parse_gen_chaos(spec: Optional[str], spe: int,
                     target_step: int) -> Dict[int, str]:
    """``"0:crash@step=6;1:sigterm@step=10"`` -> {0: ..., 1: ...}.
    Default: the canonical kill -> drain -> stall schedule — generation 0
    crashes mid-epoch-1 (after one epoch checkpoint exists), generation 1
    drains on SIGTERM two steps short of the end (a mid-epoch preemption
    save the full-world relaunch must resume from), and generation 2 (the
    grown full-world finisher) takes a 1.5s ``loader_stall`` the merged
    fleet summary's straggler detector must rank- AND phase-attribute
    (ISSUE 14's acceptance probe — the stall is non-fatal, the child
    still completes)."""
    if spec is None:
        crash_at = spe + max(1, spe // 2)
        drain_at = max(crash_at + 1, target_step - spe + 1)
        return {0: f"crash@step={crash_at}",
                1: f"sigterm@step={drain_at}",
                2: "loader_stall@step=2:1.5s"}
    out: Dict[int, str] = {}
    for item in filter(None, (s.strip() for s in spec.split(";"))):
        gen_s, _, chaos = item.partition(":")
        if not chaos:
            raise ValueError(f"--gen-chaos item {item!r} is not "
                             "GEN:SPEC")
        out[int(gen_s)] = chaos
    return out


def _compare_final_checkpoints(real_dir: str, control_dir: str,
                               log=_stderr_log) -> Optional[bool]:
    """Bitwise comparison of the newest valid checkpoint in two
    directories, RAW (saved shapes; no template, no mesh — works at any
    world) and over the WHOLE saved state: params, optimizer moments,
    batch stats, EF residuals, step counters. Params alone would let a
    reshard bug that corrupts only the moments or residual rows (which
    never reaches a loss before the final save) score as parity. None
    when either side has nothing to compare."""
    import numpy as np

    from ..training.checkpoint import CheckpointManager

    def load(d):
        mgr = CheckpointManager(d)
        try:
            return mgr.restore_latest_raw()
        finally:
            mgr.close()

    real, control = load(real_dir), load(control_dir)
    if real is None or control is None:
        return None
    real_arrays, real_label, real_world, *_ = real
    ctl_arrays, ctl_label, ctl_world, *_ = control
    if real_label != ctl_label or real_world != ctl_world \
            or sorted(real_arrays) != sorted(ctl_arrays):
        log(f"fleet: parity control diverged structurally — real "
            f"label/world {real_label}/{real_world} vs control "
            f"{ctl_label}/{ctl_world}")
        return False
    import jax.tree_util as jtu

    for key in sorted(real_arrays):
        real_leaves = jtu.tree_leaves(real_arrays[key])
        ctl_leaves = jtu.tree_leaves(ctl_arrays[key])
        if len(real_leaves) != len(ctl_leaves) or not all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(real_leaves, ctl_leaves)):
            log(f"fleet: parity mismatch in checkpoint subtree {key!r}")
            return False
    return True


def check_fleet_flights(flight_dir, launches: List[dict],
                        ignore=None) -> dict:
    """One flight per ABNORMAL child exit, attributable by generation:
    a crashed/relay-death child must leave exactly one flight stamped
    ``[fleet gen=G ...]`` whose cause matches a crash; a drained child
    exactly one whose cause names the preemption. A completed child must
    leave none. ``ignore`` holds flight paths that existed BEFORE this
    fleet ran: a reused ``--ckpt-dir`` must not let a previous run's
    postmortems satisfy — or fail — THIS run's accounting (the same
    guard the chaos harness applies)."""
    flights = []
    for p in sorted(Path(flight_dir).glob("flight_*.json")):
        if ignore and p in ignore:
            continue
        try:
            body = json.loads(p.read_text())
            flights.append({"path": str(p),
                            "cause": body.get("cause", ""),
                            "generation": body.get("fleet_generation")})
        except ValueError:
            flights.append({"path": str(p), "cause": None,
                            "generation": None})
    problems = []
    for launch in launches:
        gen = str(launch["generation"])
        mine = [f for f in flights if f["generation"] == gen]
        outcome = launch["outcome"]
        if outcome in ("crashed", "relay_death", "drained"):
            if len(mine) != 1:
                problems.append(
                    f"generation {gen} ({outcome}) left {len(mine)} "
                    "flight(s), expected exactly 1")
            elif outcome == "drained" \
                    and "preemption" not in (mine[0]["cause"] or ""):
                problems.append(
                    f"generation {gen} drained but its flight cause "
                    f"is {mine[0]['cause']!r}, not a preemption")
        elif outcome == "completed" and mine:
            problems.append(
                f"generation {gen} completed but left "
                f"{len(mine)} flight(s)")
    ok = not problems and all(f["cause"] is not None for f in flights)
    return {"flights": flights, "flight_problems": problems,
            "flights_ok": ok}


def fleet_main(args) -> int:
    """The ``resilience fleet`` scenario. Exit 0 iff the fleet completed,
    every abnormal child exit left exactly one attributable flight, no
    ``CheckpointWorldSizeMismatch`` escaped, and (unless
    ``--no-verify-parity``) the final checkpoint is bitwise-equal to an
    uninterrupted control child continuing from the last relaunch
    point."""
    if getattr(args, "federation_port", None) \
            and not getattr(args, "metrics_port", None):
        raise SystemExit("--federation-port requires --metrics-port (the "
                         "fan-in proxies the children's per-rank ports)")
    base = Path(args.ckpt_dir or tempfile.mkdtemp(prefix="dpt-fleet-"))
    base.mkdir(parents=True, exist_ok=True)
    ckpt_dir = base / "ckpt"
    out_dir = base / "out"       # children's flights + telemetry
    spe, leftover = divmod(args.synthetic_size, args.global_batch)
    if leftover or spe < 2:
        raise SystemExit(
            f"--synthetic-size {args.synthetic_size} must be a multiple "
            f"of --global-batch {args.global_batch} (>= 2 steps/epoch)")
    if args.epochs < 3:
        raise SystemExit("the fleet scenario needs --epochs >= 3 (one "
                         "epoch per phase: full world, shrunken world, "
                         "grown world)")
    target_step = spe * args.epochs
    gen_chaos = _parse_gen_chaos(args.gen_chaos, spe, target_step)
    capacity = [int(x) for x in args.capacity.split(",") if x.strip()]

    snapshots: Dict[int, Path] = {}

    def snapshot(generation: int, _launch) -> None:
        # the checkpoint directory AS THE NEXT GENERATION WILL SEE IT —
        # the parity control relaunches from exactly this state
        dest = base / f"snap_gen{generation}"
        if dest.exists():
            shutil.rmtree(dest)
        if ckpt_dir.exists():
            shutil.copytree(ckpt_dir, dest)
            snapshots[generation] = dest

    orch = FleetOrchestrator(
        lambda world, generation, resume: _train_argv(
            args, world, resume, gen_chaos.get(generation),
            str(ckpt_dir), str(out_dir)),
        ckpt_dir, global_batch=args.global_batch,
        target_step=target_step, capacity_for=capacity,
        max_launches=args.max_launches, on_child_exit=snapshot,
        telemetry_dir=out_dir,
        metrics_port=getattr(args, "metrics_port", None),
        federation_port=getattr(args, "federation_port", None))
    # flights already present belong to a PREVIOUS fleet run over this
    # --ckpt-dir — excluded from this run's per-generation accounting
    pre_existing_flights = set(Path(out_dir).glob("flight_*.json"))
    # ... and so do telemetry streams: children APPEND to the shared
    # per-rank file, so a reused --ckpt-dir would fold the previous
    # run's segments into THIS run's merged summary, trace, and
    # straggler verdict (a stale loader_stall row could satisfy the
    # acceptance probe). Rotate them aside — same guard as the flights,
    # done by rename because exclusion-by-path cannot split an appended
    # file.
    for stale in sorted(Path(out_dir).glob("telemetry_rank*.jsonl")):
        stale.rename(stale.with_name(
            stale.name + f".prev-{int(time.time())}"))
    report = orch.run()

    flight_stats = check_fleet_flights(out_dir, report.launches,
                                       ignore=pre_existing_flights)

    # The merged fleet view (ISSUE 14): ONE fleet summary + ONE stitched
    # Perfetto trace covering every generation and rank — successive
    # children APPENDED to the shared per-rank stream, so the aggregator
    # splits at meta headers and the trace gets one stable pid per
    # (gen, rank). The straggler table inside the summary is the
    # acceptance probe for the injected loader_stall.
    stream_paths = sorted(Path(out_dir).glob("telemetry_rank*.jsonl"))
    fleet_summary = None
    summary_path = trace_path = None
    if stream_paths:
        unreadable: List[str] = []
        segments = split_streams(stream_paths, missing=unreadable)
        fleet_summary = aggregate_segments(segments, missing=unreadable)
        summary_path = base / "fleet_summary.json"
        summary_path.write_text(
            json.dumps(fleet_summary, sort_keys=True))
        trace_path = base / "fleet_trace.json"
        trace_path.write_text(json.dumps(stitch_perfetto(segments)))

    # a scheduled loader_stall must come back ATTRIBUTED: the stalled
    # child's generation, the data_wait phase — "one rank is slow and
    # here is why" is the observability this plane exists to give
    launched_gens = {launch["generation"] for launch in report.launches}
    stall_gens = sorted(g for g, c in gen_chaos.items()
                        if "loader_stall" in c and g in launched_gens)
    straggler_attributed = None
    if stall_gens:
        hits = [s for s in (fleet_summary or {}).get("stragglers", [])
                if s["phase"] == "data_wait" and s["gen"] in stall_gens]
        straggler_attributed = bool(hits)
        if not straggler_attributed:
            report.errors.append(
                f"loader_stall chaos on generation(s) {stall_gens} was "
                "not rank/phase-attributed by the fleet straggler "
                "detector (expected a data_wait straggler row)")

    metrics_smoke = None
    if getattr(args, "metrics_port", None):
        metrics_smoke = any(launch.get("metrics_ok")
                            for launch in report.launches)
        if not metrics_smoke:
            report.errors.append(
                "--metrics-port was set but no child's /metrics endpoint "
                "ever answered a scrape with the step counter")

    # the gen-2 straggler verdict's device upgrade (ISSUE 15): recorded,
    # never gated — span-based attribution is the contractual fallback
    # when no capture overlapped the flagged step
    straggler_device_attributed = None
    if stall_gens:
        straggler_device_attributed = any(
            s.get("device") for s in (fleet_summary or {})
            .get("stragglers", []) if s["gen"] in stall_gens)

    # federation (ISSUE 15): the run must end with ONE merged page whose
    # per-rank series are gen/rank-labelled — every generation that
    # provably served /metrics while alive must appear in it
    federation_ok = None
    federation_page_path = None
    federated_identities: List[List[str]] = []
    if getattr(args, "federation_port", None):
        page = orch.federation_page or ""
        if page:
            federation_page_path = base / "fleet_metrics.prom"
            federation_page_path.write_text(page)
        import re as _re

        federated_identities = sorted(
            {(m.group(1), m.group(2)) for m in _re.finditer(
                r'dpt_steps_total\{gen="([^"]*)",rank="([^"]*)"\}', page)})
        federated_identities = [list(t) for t in federated_identities]
        scraped_gens = {str(launch["generation"])
                        for launch in report.launches
                        if launch.get("metrics_ok")}
        merged_gens = {g for g, _ in
                       (tuple(t) for t in federated_identities)}
        federation_ok = bool(federated_identities) \
            and scraped_gens <= merged_gens
        if not federation_ok:
            report.errors.append(
                "--federation-port was set but the merged /metrics page "
                f"is missing gen/rank-labelled step rows (merged gens "
                f"{sorted(merged_gens)}, scraped gens "
                f"{sorted(scraped_gens)})")

    parity = None
    if (report.completed and not args.no_verify_parity
            and len(report.launches) > 1):
        final = report.launches[-1]
        snap = snapshots.get(final["generation"] - 1)
        if snap is not None:
            control_ckpt = base / "control_ckpt"
            if control_ckpt.exists():
                shutil.rmtree(control_ckpt)
            shutil.copytree(snap, control_ckpt)
            control_out = base / "control_out"
            argv = _train_argv(args, final["world"], resume=True,
                               chaos=None, ckpt_dir=str(control_ckpt),
                               out_dir=str(control_out))
            orch.log(f"fleet: parity control — uninterrupted relaunch at "
                     f"world {final['world']} from the last handoff")
            env = orch._child_env(final["world"], final["generation"])
            env.pop(FLEET_GENERATION_ENV, None)
            env.pop(FLEET_RANK_ENV, None)
            ctl_log = orch.log_dir / "control.log"
            with open(ctl_log, "wb") as lf:
                rc = subprocess.run(argv, env=env, stdout=lf,
                                    stderr=subprocess.STDOUT).returncode
            if rc != 0:
                report.errors.append(f"parity control child exited {rc}")
                parity = False
            else:
                parity = _compare_final_checkpoints(
                    str(ckpt_dir), str(control_ckpt), log=orch.log)

    # "proved nothing" guards (the chaos CLI's discipline): a scheduled
    # chaos scenario whose run never relaunched exercised none of the
    # machinery this command exists to verify, and a relaunching run
    # whose parity control could not be evaluated proved only half
    if gen_chaos and report.relaunches == 0:
        report.errors.append(
            "chaos was scheduled but the fleet never relaunched — the "
            "kill/shrink/grow machinery was not exercised (chaos step "
            "past the run's end, or a reused directory already at the "
            "target)")
    if (not args.no_verify_parity and report.relaunches > 0
            and parity is None):
        report.errors.append(
            "parity control could not be evaluated (missing handoff "
            "snapshot or un-restorable checkpoints)")

    stats = {"metric": "fleet_chaos", "dir": str(base),
             "worlds": [launch["world"] for launch in report.launches],
             "gen_chaos": {str(k): v for k, v in gen_chaos.items()},
             "parity_bitwise": parity,
             "fleet_summary": fleet_summary,
             "fleet_summary_path": (str(summary_path)
                                    if summary_path else None),
             "fleet_trace_path": str(trace_path) if trace_path else None,
             "stragglers": (fleet_summary or {}).get("stragglers", []),
             "straggler_attributed": straggler_attributed,
             "straggler_device_attributed": straggler_device_attributed,
             "metrics_smoke": metrics_smoke,
             "federation_ok": federation_ok,
             "federated_identities": federated_identities,
             "federation_page_path": (str(federation_page_path)
                                      if federation_page_path else None),
             **flight_stats, **report.as_dict()}
    ok = (report.completed and parity is not False
          and flight_stats["flights_ok"]
          and report.mismatch_escapes == 0
          and not (gen_chaos and report.relaunches == 0)
          and straggler_attributed is not False
          and metrics_smoke is not False
          and federation_ok is not False
          and (args.no_verify_parity or report.relaunches == 0
               or parity is True))
    if args.as_json:
        print(json.dumps(stats, sort_keys=True))
    else:
        for launch in report.launches:
            live = (f", live step {launch['live_last_step'] + 1}"
                    if launch.get("live_last_step", -1) >= 0 else "")
            print(f"generation {launch['generation']}: world "
                  f"{launch['world']} rc={launch['rc']} "
                  f"{launch['outcome']} (step {launch['step_after']}/"
                  f"{target_step}, {launch['seconds']:.1f}s{live})")
        print(f"final step: {report.final_step}/{target_step} at world "
              f"{report.final_world}")
        print(f"flights: {len(flight_stats['flights'])} "
              f"(ok={flight_stats['flights_ok']})")
        for problem in flight_stats["flight_problems"]:
            print(f"flight problem: {problem}")
        if fleet_summary is not None:
            print(f"fleet summary: {summary_path} "
                  f"({fleet_summary['n_streams']} stream segment(s)); "
                  f"merged trace: {trace_path}")
            for s in fleet_summary["stragglers"]:
                print(f"straggler: gen={s['gen']} rank={s['rank']} "
                      f"step={s['step']} {s['phase']} {s['dur_s']:.3f}s "
                      f"({s['factor']}x {s['basis']})")
        if metrics_smoke is not None:
            print(f"metrics_smoke: {metrics_smoke}")
        if federation_ok is not None:
            print(f"federation: ok={federation_ok} identities="
                  f"{federated_identities} page={federation_page_path}")
        if straggler_device_attributed is not None:
            print(f"straggler_device_attributed: "
                  f"{straggler_device_attributed}")
        print(f"parity_bitwise: {parity}")
        for err in report.errors:
            print(f"error: {err}", file=sys.stderr)
        print("fleet: RECOVERED" if ok else "fleet: FAILED")
    return 0 if ok else 1
