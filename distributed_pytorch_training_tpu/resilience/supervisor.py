"""In-process restart supervisor: the epoch loop that survives its faults.

Wraps ``Trainer.train_epoch`` in segments of at most
``checkpoint_every_steps`` steps. After each segment it writes a
step-granular checkpoint (manifest-verified by ``training/checkpoint.py``);
when a segment raises — an injected :class:`~.faults.FaultError`, a real
step failure, a torn save — it restores the latest *valid* checkpoint and
replays behind the **step fence**:

* the checkpoint coordinate ``(epoch, step_in_epoch)`` decides where the
  data iterator resumes (the sampler is deterministic in seed+epoch, so the
  replayed batches are the exact batches of the lost steps);
* the restored ``state.step`` drives the per-step RNG fold, so the replayed
  steps draw the same randomness;
* the restored int8 error-feedback residuals (``TrainState.grad_sync``)
  re-enter the telescoping sum where it left off;
* the fence check ``int(state.step) == epoch * steps_per_epoch + step``
  catches the double-apply class: a restore whose optimizer step count
  disagrees with its data coordinate would replay an already-applied
  update (or skip one) — reported loudly, never silent.

Retries are bounded by :class:`RetryPolicy` (exponential backoff with
deterministic jitter); preemptions (the ``PreemptionGuard`` flag) are
DRAINED, not raced: the segment stops at the next step boundary, a
checkpoint is written, and the supervisor either returns (production: the
relaunch resumes with ``--resume``) or — in chaos harnesses with
``resume_preempted=True`` — simulates the relaunch by restoring its own
checkpoint and continuing.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, List, Optional, Tuple

from ..telemetry import flush_flight
from ..telemetry import recorder as _telemetry
from ..utils.logging import log_main
from .faults import ReplicaDeathError


class SupervisorError(RuntimeError):
    """The retry budget is exhausted; the last failure is the __cause__."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``max_restarts`` bounds CONSECUTIVE restore-and-replay attempts: a
    completed clean segment (train + save + barrier, no exception) resets
    the counter — and with it the backoff exponent — back to zero
    (ISSUE 12; previously the counter only ever grew, so a long run with
    sporadic faults spread hours apart still exhausted the budget and
    died). Only a fault loop that cannot get one segment through gives
    up; ``RunReport.restarts`` still counts every restart over the whole
    run. Jitter is seeded so chaos runs are reproducible; consecutive
    attempt n sleeps ``min(base * factor^(n-1), max) * (1 + jitter * u)``
    with ``u ~ U[0, 1)`` from the policy's own RNG stream."""

    max_restarts: int = 3
    backoff_base_s: float = 0.25
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.25
    seed: int = 0

    def delay_s(self, restart_index: int, rng: random.Random) -> float:
        base = min(self.backoff_base_s
                   * self.backoff_factor ** max(0, restart_index - 1),
                   self.backoff_max_s)
        return base * (1.0 + self.jitter_frac * rng.random())


@dataclasses.dataclass
class RunReport:
    """Recovery stats of one supervised run (the chaos CLI's JSON body)."""

    completed: bool = False
    preempted: bool = False
    relay_death: bool = False   # advisory deathwatch fired mid-run
    restarts: int = 0
    preemptions_drained: int = 0
    steps_run: int = 0        # train steps actually executed, incl. replays
    steps_replayed: int = 0   # executed more than once (lost to a restore)
    final_step: int = -1
    fence_violations: int = 0
    checkpoints_skipped: int = 0   # torn checkpoints integrity skipped
    faults_fired: List[str] = dataclasses.field(default_factory=list)
    faults_unfired: List[str] = dataclasses.field(default_factory=list)
    failures: List[str] = dataclasses.field(default_factory=list)
    # elastic resizes: one record per mesh re-plan — {from_world,
    # to_world, survivors, label, epoch, step, direction} where `label` is
    # the checkpoint anchoring the resize (the resharded restore's label
    # for a shrink; the boundary save's for a grow; None = no checkpoint
    # manager / restarted from scratch), (epoch, step) is where the run
    # resumed, and direction is "shrink" (replica_death restart) or
    # "grow" (capacity-return boundary re-plan, ISSUE 12)
    resizes: List[dict] = dataclasses.field(default_factory=list)
    # control-plane retunes (ISSUE 20): one record per applied
    # segment-boundary config re-plan — {epoch, step, overrides, label,
    # resets, cause} where `label` is the anchoring checkpoint and
    # `resets` names the state leaves the new config's template replaced
    # (wire-codec buffers; params/opt/step always carry over bitwise)
    retunes: List[dict] = dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Supervisor:
    """Drive ``trainer`` over ``loader`` for N epochs, surviving failures.

    ``state_factory`` must build a FRESH initial TrainState (same seed/
    structure as the run's): it is both the restore template and the
    from-scratch fallback — after a failure the in-flight state's buffers
    may already be donated, so the supervisor never reuses them.
    ``ckpt`` is a ``training.checkpoint.CheckpointManager`` (or None: no
    persistence — a failure then restarts from scratch, which is still a
    correct trajectory, just a long replay). ``injector`` is an armed
    ``FaultInjector`` or None. ``epoch_end_cb(epoch, state, loss, acc,
    seconds)`` runs after each COMPLETED epoch (validation / CSV hooks).
    ``trust_existing=False`` restricts restores to checkpoints THIS run
    wrote: a fresh (non ``--resume``) run pointed at a directory holding a
    previous run's checkpoints must never restore one mid-recovery — the
    highest stale label could place the trajectory past ``epochs`` and the
    run would "complete" on another run's params (train.py passes
    ``args.resume``; harnesses with their own directories keep the
    default).

    ``deathwatch`` is an ADVISORY ``resilience.heartbeat.Deathwatch``
    (``LivenessPolicy(lethal=False)``) or None: when its ``died`` event
    sets mid-epoch (the relay tunnel collapsed), the running segment
    drains at the next step boundary, the segment checkpoint is written
    and the pending async save FLUSHED, and the run aborts with
    ``report.relay_death=True`` — checkpoint-then-abort instead of the
    bare lethal rc=70, so the relaunch resumes instead of replaying the
    epoch (ROADMAP "resilience follow-ups").

    ``capacity_watch`` (with ``replan_cb``) arms BIDIRECTIONAL elasticity
    (ISSUE 12): replica deaths debit the watch (its count feeds the
    shrink re-plan's survivors), and when returned capacity makes a
    larger feasible world available the supervisor GROWS at the next
    segment boundary — drain, checkpoint (the anchor label), re-plan UP,
    reshard the live state, continue. A grow is not a restart: nothing
    replays, no flight is flushed, the retry budget is untouched.

    Async saves: segment checkpoints ride the CheckpointManager's
    background writer (training continues over the orbax write + manifest
    hashing); a failed write surfaces at the next save/wait barrier, which
    is INSIDE the recovery try — "on a step/save failure, restore the
    latest valid checkpoint" covers the async window too, and the run's
    final save is flushed before ``run`` declares completion so a lost
    last save is a recovered failure, not a silent one.
    """

    def __init__(self, trainer, ckpt, state_factory: Callable[[], Any],
                 loader, *, retry: RetryPolicy = RetryPolicy(),
                 guard=None, injector=None,
                 checkpoint_every_steps: Optional[int] = None,
                 resume_preempted: bool = False,
                 trust_existing: bool = True,
                 epoch_end_cb: Optional[Callable[..., None]] = None,
                 deathwatch=None,
                 replan_cb: Optional[Callable[[int], Any]] = None,
                 capacity_watch=None,
                 retune_cb: Optional[Callable[[dict], Any]] = None,
                 control=None,
                 sleep: Callable[[float], None] = time.sleep):
        if checkpoint_every_steps is not None and checkpoint_every_steps <= 0:
            raise ValueError("checkpoint_every_steps must be positive "
                             f"(got {checkpoint_every_steps})")
        self.trainer = trainer
        self.ckpt = ckpt
        self.state_factory = state_factory
        self.loader = loader
        self.retry = retry
        self.guard = guard
        self.injector = injector
        self.every = checkpoint_every_steps
        self.resume_preempted = resume_preempted
        self.trust_existing = trust_existing
        self.epoch_end_cb = epoch_end_cb
        self.deathwatch = deathwatch
        # Elastic mode (ISSUE 11): ``replan_cb(survivors) -> ElasticPlan``
        # rebuilds the rig on the surviving-device mesh after a
        # ReplicaDeathError. The resize rides the NORMAL restart path —
        # one restart counted, one flight flushed, the same deterministic
        # RetryPolicy backoff — then the restore goes through a per-label
        # world-size template (restore_latest(template_factory=...)) and
        # reshards (resilience/elastic.py) when the checkpoint's world
        # differs from the new one. None = fixed-world behavior, verbatim.
        self.replan_cb = replan_cb
        # Grow side (ISSUE 12): a resilience.capacity.CapacityWatch the
        # replica deaths debit and capacity returns credit. Polled at
        # SEGMENT BOUNDARIES only (after the segment's checkpoint): when
        # available > current world AND the replan finds a larger
        # feasible world, the LIVE state reshards M -> N in place and the
        # run continues — no restart, no replay, one `elastic_grow` span.
        self.capacity_watch = capacity_watch
        # Control plane (ISSUE 20): ``retune_cb(overrides) -> ElasticPlan``
        # rebuilds the rig at the SAME world under a new training config
        # (the online tuner's apply path, `boundary_retune`), and
        # ``control`` is a control.Autopilot-shaped object whose
        # ``on_segment_boundary(supervisor=, report=, state=, epoch=,
        # step=)`` is consulted at every clean segment boundary — the
        # drained, checkpoint-anchored point where a decision may act.
        # Both default off; the None path is byte-identical to a build
        # without the control package.
        self.retune_cb = retune_cb
        self.control = control
        self.sleep = sleep
        # consecutive restore-and-replay attempts since the last CLEAN
        # segment — the RetryPolicy's budget/backoff index (resets to 0
        # after every completed segment; report.restarts never resets)
        self._consecutive_failures = 0
        self._last_saved_label: Optional[int] = None
        self._last_step_entered = -1
        self._saved_labels: set = set()
        self._skipped_labels: set = set()
        # world-size bookkeeping: the manifest records what each save was
        # laid out for, and _factories keeps one template factory per
        # world this run has ever trained at (elastic restores build the
        # OLD world's template, then reshard into the current one)
        self._world: Optional[int] = getattr(trainer, "batch_shards", None)
        self._factories = ({self._world: state_factory}
                           if self._world is not None else {})
        self._last_restore_label: Optional[int] = None

    @property
    def world_size(self) -> int:
        """Current data-parallel world (batch shards) — the number every
        control decision records its from/to transition against. 1 when
        the trainer exposes no shard count (single-device rigs)."""
        return int(self._world) if self._world is not None else 1

    # -- fence / bookkeeping hooks ----------------------------------------

    def _fault_hook(self, report: RunReport, seg_start_abs: int):
        """The per-step fence handed to train_epoch: records progress (so a
        restore can account the replay) and fires injected faults BEFORE
        the step executes — a crash here means the optimizer never applied
        this step."""
        injector = self.injector

        def hook(i: int) -> None:
            step = seg_start_abs + i
            self._last_step_entered = step
            if injector is not None:
                injector.on_step(step)
            report.steps_run += 1

        return hook

    def _segment_stop(self, seg_len: int):
        """stop_fn for one segment: break after seg_len steps, or at the
        next step boundary once a preemption was requested (the drain) or
        the advisory deathwatch reported the relay dead (checkpoint-then-
        abort needs the segment drained first)."""
        count = [0]
        guard = self.guard
        watch = self.deathwatch

        def stop() -> bool:
            count[0] += 1
            if count[0] >= seg_len:
                return True
            if watch is not None and watch.died.is_set():
                return True
            return bool(guard is not None and guard.should_stop)

        return stop

    # -- checkpoint plumbing ----------------------------------------------

    def _save(self, epoch: int, step: int, spe: int, state) -> None:
        if self.ckpt is None:
            return
        if step >= spe:  # epoch-complete: the epoch-boundary label form
            label, save_epoch, in_epoch = (epoch + 1) * spe, epoch + 1, 0
        else:
            label, save_epoch, in_epoch = epoch * spe + step, epoch, step
        # async (snapshot-then-write): only the device→host copy blocks;
        # the orbax write + manifest overlap the next segment's training.
        # The manager itself joins any previous in-flight write first, so
        # an earlier failed save surfaces HERE — inside the recovery try.
        self.ckpt.save(label, state, epoch=save_epoch,
                       step_in_epoch=in_epoch, world_size=self._world)
        self._saved_labels.add(label)
        self._last_saved_label = label  # the grow anchor (resize record)

    def _replan(self, err: ReplicaDeathError, report: RunReport) -> dict:
        """The elastic resize: hand the surviving replica count to
        ``replan_cb`` and swap in the rig it builds. Invariants enforced
        loudly: the new loader must keep the old steps-per-epoch (the
        GLOBAL batch is fixed across resizes — the step fence, sampler
        permutation and per-step RNG all depend on it). Returns the
        resize record (label/epoch/step filled after the restore)."""
        old_world = self._world
        survivors = getattr(err, "survivors", None)
        if survivors is None:
            survivors = (old_world - 1) if old_world else None
        if survivors is not None and self.capacity_watch is not None:
            # keep the registry consistent with the shrink decision: a
            # death re-plans over the surviving ACTIVE replicas, so the
            # boundary poll must not see phantom idle capacity and grow
            # straight back mid-incident (capacity genuinely returning
            # goes through watch.restore — the capacity_return fault)
            self.capacity_watch.sync(survivors)
        if not survivors or survivors < 1:
            err2 = SupervisorError(
                f"replica death at world size {old_world} leaves no "
                "survivors to re-plan onto")
            err2.report = report  # the chaos CLI reports even a loss
            raise err2 from err
        with _telemetry.span("elastic_replan", from_world=old_world,
                             survivors=survivors):
            plan = self.replan_cb(survivors)
        if len(plan.loader) != len(self.loader):
            err2 = SupervisorError(
                f"elastic re-plan changed steps-per-epoch "
                f"({len(self.loader)} -> {len(plan.loader)}) — the replan "
                "must keep the GLOBAL batch fixed (grow the per-device "
                "batch), or the step fence and sampler schedule no longer "
                "describe the same trajectory")
            err2.report = report
            raise err2
        self.trainer = plan.trainer
        self.loader = plan.loader
        self.state_factory = plan.state_factory
        self._world = plan.world
        self._factories[plan.world] = plan.state_factory
        _telemetry.counter("elastic_resizes", 1, from_world=old_world,
                           to_world=plan.world, survivors=survivors)
        # the /metrics world-size gauge tracks every resize live
        _telemetry.gauge("world_size", plan.world)
        log_main(f"supervisor: elastic resize — mesh re-planned "
                 f"{old_world} -> {plan.world} replicas "
                 f"({survivors} survivor(s)); restoring and resharding")
        # a death restart normally shrinks, but capacity that returned
        # before the restart can make the re-plan land larger — direction
        # records what actually happened, not the trigger
        return {"from_world": old_world, "to_world": plan.world,
                "survivors": survivors,
                "direction": ("grow" if old_world is not None
                              and plan.world > old_world else "shrink")}

    def _maybe_grow(self, report: RunReport, state, epoch: int,
                    step: int):
        """Segment-boundary grow poll (ISSUE 12): when the capacity
        registry reports more replicas than the current world AND the
        re-plan finds a larger feasible world (divides the fixed global
        batch), reshard the LIVE state into the new world's layout and
        swap the rig — no restart, no replay, no data-order change (the
        sampler/fence/per-step RNG are world-independent by the elastic
        design). The just-written segment checkpoint anchors the resize
        record: the parity control restores THAT label at its recorded
        world and reshards the same way (``resilience chaos --elastic``).
        Returns the (possibly resharded) state."""
        avail = self.capacity_watch.poll_grow(self._world)
        if avail is None:
            return state
        plan = self.replan_cb(avail)
        if self._world is not None and plan.world <= self._world:
            # capacity returned in a quantity no feasible world can use
            # (e.g. 5 available, global batch 16): keep training at M —
            # the poll repeats at the next boundary
            return state
        if len(plan.loader) != len(self.loader):
            err = SupervisorError(
                f"elastic grow re-plan changed steps-per-epoch "
                f"({len(self.loader)} -> {len(plan.loader)}) — the replan "
                "must keep the GLOBAL batch fixed (shrink the per-device "
                "batch), or the step fence and sampler schedule no longer "
                "describe the same trajectory")
            err.report = report
            raise err
        if self.ckpt is not None:
            try:
                # the anchor must be DURABLE before the rig swaps: the
                # resize record names the just-saved label and the parity
                # control restores it — at a mid-epoch boundary that save
                # may still be on the async writer, and anchoring a grow
                # on a write that later fails would score a correct
                # recovery as a parity failure
                self.ckpt.wait()
            except Exception as e:  # noqa: BLE001 — the anchor save was
                # lost; its label is torn (pending marker) and later
                # restores skip it. Defer the grow: the capacity is still
                # there and the poll repeats at the next boundary, where
                # a fresh segment save anchors it.
                report.failures.append(
                    f"{type(e).__name__}: {e} (anchor save lost at a "
                    "grow boundary — grow deferred to the next segment)")
                log_main(f"supervisor: grow deferred — the boundary "
                         f"checkpoint's async write failed "
                         f"({type(e).__name__}: {e}); the label is torn "
                         "and the next boundary re-anchors")
                return state
        old_world = self._world
        from .elastic import reshard_train_state

        with _telemetry.span("elastic_grow", from_world=old_world,
                             to_world=plan.world, available=avail):
            state = reshard_train_state(state, old_world, plan.world,
                                        plan.trainer,
                                        plan.state_factory())
        self.trainer = plan.trainer
        self.loader = plan.loader
        self.state_factory = plan.state_factory
        self._world = plan.world
        self._factories[plan.world] = plan.state_factory
        _telemetry.counter("elastic_resizes", 1, from_world=old_world,
                           to_world=plan.world, direction="grow")
        _telemetry.gauge("world_size", plan.world)
        report.resizes.append({
            "from_world": old_world, "to_world": plan.world,
            "survivors": avail, "label": self._last_saved_label,
            "epoch": epoch, "step": step, "direction": "grow"})
        log_main(f"supervisor: elastic GROW — capacity returned "
                 f"({avail} available), mesh re-planned {old_world} -> "
                 f"{plan.world} replicas at epoch {epoch} step {step} "
                 f"(live reshard, anchor checkpoint "
                 f"{self._last_saved_label}; sampler/RNG unchanged)")
        return state

    # -- control-plane re-plan surface (ISSUE 20) --------------------------
    #
    # The two boundary methods below are the Supervisor's half of the
    # control loop: policy lives in control/, but the elastic invariants
    # (fixed global batch, steps-per-epoch, durable anchor before the rig
    # swaps) live HERE, where every other resize already enforces them.
    # Both return (state, applied, detail): a False apply is a refusal the
    # caller logs as a decision — never an exception, because a declined
    # control action must leave the run exactly as it was.

    def boundary_shrink(self, report: RunReport, state, *, epoch: int,
                        step: int, evicted_rank: Optional[int] = None,
                        cause: str = ""):
        """Evict one rank at a clean segment boundary: treat it as a
        capacity loss of exactly one replica — re-plan to the largest
        feasible smaller world, reshard the LIVE state (no restart, no
        replay, the `_maybe_grow` mechanics in the shrink direction), and
        debit the capacity watch so a later ``restore()`` re-admits the
        share through the normal grow poll."""
        if self.replan_cb is None:
            return state, False, ("no replan_cb armed (fixed-world "
                                  "supervisor cannot shrink)")
        if self._world is None:
            return state, False, "trainer exposes no world size"
        survivors = self._world - 1
        if survivors < 1:
            return state, False, "cannot shrink below one replica"
        plan = self.replan_cb(survivors)
        if plan.world >= self._world:
            return state, False, (
                f"no feasible world below {self._world} replicas for "
                f"{survivors} survivor(s) (global batch divisibility)")
        if len(plan.loader) != len(self.loader):
            return state, False, (
                f"eviction re-plan changed steps-per-epoch "
                f"({len(self.loader)} -> {len(plan.loader)}) — the replan "
                "must keep the GLOBAL batch fixed")
        if self.ckpt is not None:
            try:
                # same durable-anchor rule as a grow: the resize record
                # names the just-saved label and the parity control
                # restores it — never anchor on a write still in flight
                self.ckpt.wait()
            except Exception as e:  # noqa: BLE001 — anchor lost; defer
                report.failures.append(
                    f"{type(e).__name__}: {e} (anchor save lost at an "
                    "eviction boundary — eviction deferred)")
                return state, False, (
                    f"anchor save lost ({type(e).__name__}); eviction "
                    "deferred to the next boundary")
        old_world = self._world
        from .elastic import reshard_train_state

        with _telemetry.span("elastic_replan", from_world=old_world,
                             to_world=plan.world, survivors=survivors,
                             cause=cause or "straggler_evict"):
            state = reshard_train_state(state, old_world, plan.world,
                                        plan.trainer,
                                        plan.state_factory())
        self.trainer = plan.trainer
        self.loader = plan.loader
        self.state_factory = plan.state_factory
        self._world = plan.world
        self._factories[plan.world] = plan.state_factory
        if self.capacity_watch is not None:
            # the evicted rank is out of service until something
            # (capacity_return chaos, a real probe) restores it
            self.capacity_watch.sync(survivors)
        _telemetry.counter("elastic_resizes", 1, from_world=old_world,
                           to_world=plan.world, survivors=survivors,
                           direction="shrink")
        _telemetry.gauge("world_size", plan.world)
        report.resizes.append({
            "from_world": old_world, "to_world": plan.world,
            "survivors": survivors, "label": self._last_saved_label,
            "epoch": epoch, "step": step, "direction": "shrink",
            "cause": cause or "straggler_evict",
            "evicted_rank": evicted_rank})
        log_main(f"supervisor: control EVICTION — rank {evicted_rank} "
                 f"drained, mesh re-planned {old_world} -> {plan.world} "
                 f"replicas at epoch {epoch} step {step} (live reshard, "
                 f"anchor checkpoint {self._last_saved_label}; capacity "
                 f"watch debited to {survivors})")
        return state, True, ""

    def boundary_retune(self, report: RunReport, state, *, epoch: int,
                        step: int, overrides: dict, cause: str = ""):
        """Apply a contract-passed config re-plan at a clean segment
        boundary: rebuild the rig at the SAME world under the new
        TrainConfig (``retune_cb``), carry every state leaf whose
        layout the new config preserves (params, optimizer moments, the
        step counter — bitwise), and take the fresh template's value for
        leaves the new config re-shapes (wire-codec error-feedback
        buffers). The caller is responsible for gating: this method
        trusts that the overrides already passed their contract."""
        if self.retune_cb is None:
            return state, False, ("no retune_cb armed (this supervisor "
                                  "cannot rebuild its rig under a new "
                                  "config)")
        plan = self.retune_cb(dict(overrides))
        if self._world is not None and plan.world != self._world:
            return state, False, (
                f"retune re-plan changed the world ({self._world} -> "
                f"{plan.world}) — a retune must keep capacity fixed "
                "(evictions/grows own world changes)")
        if len(plan.loader) != len(self.loader):
            return state, False, (
                f"retune re-plan changed steps-per-epoch "
                f"({len(self.loader)} -> {len(plan.loader)})")
        if self.ckpt is not None:
            try:
                self.ckpt.wait()
            except Exception as e:  # noqa: BLE001 — anchor lost; defer
                report.failures.append(
                    f"{type(e).__name__}: {e} (anchor save lost at a "
                    "retune boundary — retune deferred)")
                return state, False, (
                    f"anchor save lost ({type(e).__name__}); retune "
                    "deferred to the next boundary")
        from .elastic import adopt_state

        with _telemetry.span("control_retune", cause=cause,
                             overrides=dict(overrides)):
            state, resets = adopt_state(state, plan.state_factory())
        self.trainer = plan.trainer
        self.loader = plan.loader
        self.state_factory = plan.state_factory
        self._factories[plan.world] = plan.state_factory
        _telemetry.counter("control_retunes", 1)
        report.retunes.append({
            "epoch": epoch, "step": step, "overrides": dict(overrides),
            "label": self._last_saved_label, "resets": list(resets),
            "cause": cause})
        log_main(f"supervisor: control RETUNE — config re-planned at "
                 f"epoch {epoch} step {step} with {overrides} (anchor "
                 f"checkpoint {self._last_saved_label}; "
                 f"{len(resets)} state leaf/leaves reset: {resets})")
        return state, True, ""

    def _template_for_world(self, world: Optional[int]):
        """Restore template for a checkpoint recorded at ``world`` batch
        shards (None = legacy manifest: assume the current world). Only
        worlds this run has trained at are known — a foreign world in the
        directory is a loud error, not a guess."""
        if world is None or world == self._world:
            return self.state_factory()
        factory = self._factories.get(world)
        if factory is None:
            raise RuntimeError(
                f"checkpoint was written at world size {world}, but this "
                f"supervisor only knows worlds {sorted(self._factories)} "
                "— checkpoints from another run's mesh need a matching "
                "template (train.py --resume with the original --mesh)")
        return factory()

    def _restore_or_fresh(self, report: RunReport, spe: int
                          ) -> Tuple[Any, int, int]:
        """Latest VALID checkpoint (torn ones are skipped by the manifest
        verification), or a fresh state when none exists. Returns
        ``(state, epoch, step_in_epoch)`` and enforces the step fence.
        In elastic mode the restore template is built at the CHECKPOINT's
        recorded world size and the state reshards into the current
        layout when the worlds differ (the N -> M re-slice)."""
        among = None if self.trust_existing else self._saved_labels
        self._last_restore_label = None
        if self.ckpt is None:
            restored = None
        elif self.replan_cb is not None:
            restored = self.ckpt.restore_latest(
                among=among, template_factory=self._template_for_world)
        else:
            restored = self.ckpt.restore_latest(self.state_factory(),
                                                among=among)
        if self.ckpt is not None:
            # a torn checkpoint is skipped by EVERY later restore; count
            # distinct labels, not skip events
            fresh_skips = sorted(set(self.ckpt.last_skipped)
                                 - self._skipped_labels)
            self._skipped_labels.update(self.ckpt.last_skipped)
            report.checkpoints_skipped = len(self._skipped_labels)
            if fresh_skips:
                # each NEWLY-discovered torn checkpoint leaves its own
                # postmortem (the torn_ckpt chaos fault's flight artifact)
                flush_flight(
                    cause=f"torn_checkpoint: labels {fresh_skips} failed "
                          "integrity verification",
                    detail="supervisor restore skipped torn checkpoint(s)")
        if restored is None:
            if self.ckpt is not None:
                log_main("supervisor: no valid checkpoint — "
                         "(re)starting from scratch")
            return self.state_factory(), 0, 0
        state, epoch, step = restored
        self._last_restore_label = self.ckpt.last_restored
        if self.replan_cb is not None:
            ckpt_world = self.ckpt.checkpoint_world_size(
                self._last_restore_label)
            if (ckpt_world is not None and self._world is not None
                    and ckpt_world != self._world):
                # the elastic re-slice: old-N flat-padded layouts re-chunk
                # into the new-M template, EF residual rows fold — exact
                # (pad regions are zeros), one leaf at a time
                from .elastic import reshard_train_state

                with _telemetry.span("elastic_reshard",
                                     from_world=ckpt_world,
                                     to_world=self._world,
                                     label=self._last_restore_label):
                    state = reshard_train_state(
                        state, ckpt_world, self._world, self.trainer,
                        self.state_factory())
                log_main(f"supervisor: resharded checkpoint "
                         f"{self._last_restore_label} from world "
                         f"{ckpt_world} to {self._world} (flat-padded "
                         "re-slice; sampler/RNG unchanged behind the "
                         "step fence)")
        expected = epoch * spe + step
        got = int(state.step)
        if got != expected:
            # The double-apply class: optimizer step count disagreeing with
            # the data coordinate means a replay would re-apply (or skip)
            # an update. Loud, counted, and resumed at the OPTIMIZER's
            # position (the authoritative trajectory coordinate).
            report.fence_violations += 1
            log_main(f"supervisor: STEP FENCE VIOLATION — restored "
                     f"optimizer step {got} != checkpoint coordinate "
                     f"epoch {epoch} * {spe} + step {step} = {expected}; "
                     "resuming at the optimizer's step to avoid a "
                     "double-apply")
            epoch, step = divmod(got, spe)
        return state, epoch, step

    # -- the loop ----------------------------------------------------------

    def run(self, epochs: int,
            initial: Optional[Tuple[Any, int, int]] = None):
        """Run to completion (or a drained preemption / exhausted retries).
        ``initial`` is an already-built ``(state, epoch, step)`` start
        point (train.py's --resume restore); default restores from the
        manager. Returns ``(final_state, RunReport)``."""
        spe = len(self.loader)
        report = RunReport()
        rng = random.Random(self.retry.seed)
        if initial is not None:
            state, epoch, step = initial
        else:
            state, epoch, step = self._restore_or_fresh(report, spe)

        while epoch < epochs:
            seg_start_abs = epoch * spe + step
            seg_len = (spe - step if self.every is None
                       else min(self.every, spe - step))
            try:
                state, loss, acc, seconds, done = self.trainer.train_epoch(
                    state, self.loader.epoch(epoch, start_step=step),
                    epoch, spe, start_step=step,
                    stop_fn=self._segment_stop(seg_len),
                    fault_hook=self._fault_hook(report, seg_start_abs))
                step += done
                # the save is inside the recovery scope too: "on a
                # step/SAVE failure, restore the latest valid checkpoint"
                self._save(epoch, step, spe, state)
                if self.ckpt is not None and step >= spe:
                    # Epoch-boundary barrier (the ISSUE-6 design: async
                    # saves barrier at epoch end): a failed background
                    # write must surface HERE, inside the recovery scope
                    # and before epoch_end_cb emits the epoch's
                    # validation/CSV row — otherwise the failure raises
                    # one segment late at the next save, the replay
                    # re-runs the epoch, and the cb fires twice for it
                    # (duplicate validation + duplicate CSV row). Also
                    # covers the run's last save: completing with a
                    # silently lost final checkpoint would not be
                    # completing.
                    self.ckpt.wait()
            except Exception as e:  # noqa: BLE001 — every step failure is
                # a restart candidate; non-restartable ones exhaust the
                # budget and re-raise as SupervisorError below.
                if self.guard is not None and self.guard.should_stop:
                    # A failure DURING the drain window: restarting now
                    # would race the preemption's hard-exit deadline.
                    # Leave whatever checkpoint exists; the relaunch
                    # resumes from it.
                    report.preempted = True
                    report.failures.append(
                        f"{type(e).__name__}: {e} (during preemption drain"
                        " — not restarted)")
                    flush_flight(
                        cause=f"{type(e).__name__}: {e}",
                        detail="failure during preemption (sigterm) drain "
                               "— not restarted", rc=1)
                    log_main("supervisor: failure during preemption drain; "
                             "stopping (relaunch resumes from the last "
                             "checkpoint)")
                    break
                report.restarts += 1
                self._consecutive_failures += 1
                report.failures.append(f"{type(e).__name__}: {e}")
                # the per-failure postmortem: the injected chaos faults'
                # flight artifacts carry the fault label verbatim in the
                # cause (e.g. "FaultError: injected crash@step=3")
                flush_flight(
                    cause=f"{type(e).__name__}: {e}",
                    detail=f"supervisor restart {report.restarts} "
                           f"(consecutive {self._consecutive_failures}/"
                           f"{self.retry.max_restarts})")
                _telemetry.counter("restarts", 1)
                if self._consecutive_failures > self.retry.max_restarts:
                    report.final_step = -1
                    if self.injector is not None:
                        report.faults_fired = list(self.injector.fired)
                        report.faults_unfired = self.injector.unfired()
                    flush_flight(
                        cause=f"supervisor abort: retry budget "
                              f"({self.retry.max_restarts}) exhausted; "
                              f"last failure: {type(e).__name__}: {e}",
                        detail="SupervisorError", rc=1)
                    err = SupervisorError(
                        f"giving up after {self.retry.max_restarts} "
                        f"consecutive restart(s); last failure: {e}")
                    err.report = report  # the chaos CLI reports even a loss
                    raise err from e
                delay = self.retry.delay_s(self._consecutive_failures, rng)
                log_main(f"supervisor: step failure ({type(e).__name__}: "
                         f"{e}) — restart {self._consecutive_failures}/"
                         f"{self.retry.max_restarts} in {delay:.2f}s")
                self.sleep(delay)
                # elastic resize rides THIS restart (already counted,
                # flighted, and backed off above — a resize is one
                # restart, never two): re-plan the mesh to the surviving
                # replica count, then restore-and-reshard below
                resize = None
                if (self.replan_cb is not None
                        and isinstance(e, ReplicaDeathError)):
                    resize = self._replan(e, report)
                state, epoch, step = self._restore_or_fresh(report, spe)
                if resize is not None:
                    resize.update(label=self._last_restore_label,
                                  epoch=epoch, step=step)
                    report.resizes.append(resize)
                restored_abs = epoch * spe + step
                if self._last_step_entered >= 0:
                    report.steps_replayed += max(
                        0, self._last_step_entered - restored_abs)
                continue

            # the segment completed CLEAN (train + save + barrier): the
            # retry budget and backoff exponent reset — max_restarts
            # bounds consecutive failures, not lifetime faults (a long
            # run with sporadic faults hours apart must not die on its
            # Nth isolated fault; only a loop that can't get one segment
            # through exhausts the budget)
            self._consecutive_failures = 0

            if step >= spe:
                # epoch complete — BEFORE the drain check: a preemption
                # landing exactly at the boundary must still emit the
                # finished epoch's validation/CSV row (the plain loop
                # does; the supervised path keeps the identical contract)
                if self.epoch_end_cb is not None:
                    self.epoch_end_cb(epoch, state, loss, acc, seconds)
                epoch, step = epoch + 1, 0

            if (self.control is not None and epoch < epochs
                    and not (self.deathwatch is not None
                             and self.deathwatch.died.is_set())
                    and not (self.guard is not None
                             and self.guard.should_stop)):
                # Control-plane boundary hook (ISSUE 20), BEFORE the grow
                # poll: the segment is drained and its checkpoint written
                # — the only anchor a decision may act on. An eviction
                # here debits the capacity watch, so the grow poll just
                # below cannot phantom-refill the evicted share; a dying
                # run (relay death / drain pending) never consults the
                # control plane on its way out.
                state = self.control.on_segment_boundary(
                    supervisor=self, report=report, state=state,
                    epoch=epoch, step=step)

            if (self.capacity_watch is not None
                    and self.replan_cb is not None and epoch < epochs
                    and not (self.deathwatch is not None
                             and self.deathwatch.died.is_set())
                    and not (self.guard is not None
                             and self.guard.should_stop)):
                # the GROW side of elasticity (ISSUE 12): the segment is
                # drained and its checkpoint written — the only place a
                # resize can anchor — so poll the capacity registry and
                # re-plan UP when returned capacity admits a larger
                # feasible world. A dying run (relay death / preemption
                # drain pending below) never grows on its way out.
                state = self._maybe_grow(report, state, epoch, step)

            if (self.deathwatch is not None
                    and self.deathwatch.died.is_set() and epoch < epochs):
                # Advisory relay deathwatch: the tunnel died mid-run. The
                # segment drained at a step boundary and its checkpoint is
                # already written (possibly still in the async writer) —
                # FLUSH it, then abort: checkpoint-then-abort instead of
                # the lethal watch's bare rc=70, so the relaunch resumes
                # this exact step instead of replaying the epoch.
                report.relay_death = True
                if self.ckpt is not None:
                    try:
                        self.ckpt.wait()
                    except Exception as e:  # the pending save was lost —
                        # re-save synchronously; durable > fast while dying
                        report.failures.append(
                            f"{type(e).__name__}: {e} (async save lost "
                            "during relay-death abort; re-saved)")
                        try:
                            self._save(epoch, step, spe, state)
                            self.ckpt.wait()
                        except Exception as e2:
                            # The storage path itself is dying with the
                            # relay. A raw escape here would lose the
                            # RunReport AND train.py's rc=70 abort — the
                            # relaunch replays from the last durable save
                            # instead, which is exactly what the report
                            # must say.
                            report.failures.append(
                                f"{type(e2).__name__}: {e2} (relay-death "
                                "re-save ALSO failed; aborting on the "
                                "last durable checkpoint)")
                flush_flight(
                    cause=f"relay_death: ports "
                          f"{getattr(self.deathwatch, 'dead_ports', [])} "
                          "dead (advisory deathwatch)",
                    detail=f"checkpoint-then-abort at epoch {epoch} step "
                           f"{step}/{spe}", rc=70)
                log_main(f"supervisor: relay tunnel died (ports "
                         f"{getattr(self.deathwatch, 'dead_ports', [])}) — "
                         f"checkpointed epoch {epoch} step {step}/{spe}; "
                         "aborting for relaunch")
                break

            if (self.guard is not None and self.guard.should_stop
                    and epoch < epochs):
                # (a preemption landing after the LAST epoch finished has
                # nothing left to drain — the run is simply complete)
                report.preemptions_drained += 1
                # sigterm's flight artifact (both branches: a drained stop
                # AND the chaos harness's simulated relaunch record what
                # was interrupted and where it resumes)
                flush_flight(
                    cause=f"preemption (sigterm) drained at epoch {epoch} "
                          f"step {step}/{spe}",
                    detail="supervisor drain"
                           + ("" if not self.resume_preempted
                              else " + simulated relaunch"), rc=0)
                if not self.resume_preempted:
                    report.preempted = True
                    log_main(f"supervisor: preempted — checkpointed epoch "
                             f"{epoch} step {step}/{spe}; relaunch with "
                             "--resume to continue")
                    break
                # chaos harness: simulate the relaunch in-process — reset
                # the guard (disarms its hard-exit deadline) and resume
                # from the checkpoint just written.
                log_main("supervisor: preemption drained; simulating "
                         "relaunch (restore + resume)")
                self.guard.reset()
                state, epoch, step = self._restore_or_fresh(report, spe)
                continue
        else:
            report.completed = True

        report.final_step = int(state.step)
        if self.injector is not None:
            report.faults_fired = list(self.injector.fired)
            report.faults_unfired = self.injector.unfired()
        return state, report
