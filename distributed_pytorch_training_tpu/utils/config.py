"""CLI config — the reference's argparse surface, preserved flag-for-flag
(/root/reference/train_ddp.py:19-46: same names, same defaults, same
per-device ``--batch-size`` semantic, ref :27), plus TPU-native extensions
(model/dataset selection, mesh spec, checkpointing, profiling).
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def parse_args(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="TPU-native distributed training (parity: DDP training of "
                    "ResNet-18 on CIFAR-10, ref train_ddp.py:20)")

    # --- reference flags, identical names and defaults (ref :22-44) ---
    parser.add_argument("--data-dir", default="./data", type=str,
                        help="directory to store CIFAR-10")
    parser.add_argument("--epochs", default=10, type=int,
                        help="number of total epochs to run")
    parser.add_argument("--batch-size", default=128, type=int,
                        help="mini-batch size *per device* (ref: per GPU)")
    parser.add_argument("--workers", default=4, type=int,
                        help="host-side prefetch depth (ref: DataLoader workers)")
    parser.add_argument("--lr", default=0.1, type=float,
                        help="initial learning rate")
    parser.add_argument("--momentum", default=0.9, type=float,
                        help="SGD momentum")
    parser.add_argument("--weight-decay", default=5e-4, type=float,
                        help="weight decay")
    parser.add_argument("--amp", "--bf16", dest="amp", action="store_true",
                        help="mixed precision: bf16 compute, fp32 params "
                             "(ref --amp; no GradScaler needed on TPU)")
    parser.add_argument("--print-freq", default=50, type=int,
                        help="print frequency (in steps)")
    parser.add_argument("--output-dir", default="./experiments", type=str,
                        help="directory to save logs")
    parser.add_argument("--seed", default=42, type=int,
                        help="random seed")

    # --- TPU-native extensions ---
    parser.add_argument("--model", default="resnet18", type=str,
                        help="model name (resnet18/resnet50/vit_b16/bert_base/"
                             "gpt2_124m/gpt2_355m/gpt2_moe)")
    parser.add_argument("--model-overrides", default="", type=str,
                        help="comma-separated field=value constructor "
                             "overrides, e.g. 'depth=2,hidden_dim=64' — "
                             "shrunk-architecture runs of a named config "
                             "(CPU sanity, CI); values parse as int/float "
                             "when they look numeric")
    parser.add_argument("--dataset", default="cifar10", type=str,
                        help="dataset name (cifar10/imagenet)")
    parser.add_argument("--download", action="store_true",
                        help="fetch the dataset archive (checksum-verified) "
                             "if absent; process 0 downloads, others wait at "
                             "the barrier (ref :106-112 contract)")
    parser.add_argument("--synthetic", action="store_true",
                        help="force synthetic data (zero-egress environments)")
    parser.add_argument("--synthetic-size", default=None, type=int,
                        help="synthetic dataset size override")
    parser.add_argument("--mesh", default="data=-1", type=str,
                        help="mesh spec, e.g. 'data=4,model=2' (default: pure DP)")
    parser.add_argument("--slices", default=1, type=int,
                        help="factor the data-parallel world into this many "
                             "topology slices (the outer/slow-tier mesh "
                             "axis, e.g. TPU pods joined by DCN): folds a "
                             "'slice=N' axis into --mesh, the tier "
                             "--wire-dtype int8_hier compresses across. 1 "
                             "= flat topology (default). The world must "
                             "factor: remaining data shards = world/N")
    parser.add_argument("--slice-axis", default="slice", type=str,
                        help="mesh axis name int8_hier treats as the slow "
                             "tier (default 'slice', the axis --slices "
                             "populates); must be one of the mesh's batch "
                             "axes")
    parser.add_argument("--microbatches", default=4, type=int,
                        help="GPipe microbatches per step when the mesh has "
                             "a pipe axis > 1 (bubble fraction "
                             "(P-1)/(M+P-1))")
    parser.add_argument("--optimizer", default="sgd", type=str,
                        help="sgd | adamw")
    parser.add_argument("--seq-len", default=None, type=int,
                        help="sequence length for LM configs (default: 512 "
                             "for bert_base, 1024 for gpt2)")
    parser.add_argument("--attention", default="auto", type=str,
                        choices=["auto", "xla", "flash", "ring", "ulysses"],
                        help="attention implementation for LM configs: auto "
                             "(flash on TPU, xla otherwise — the default), "
                             "xla einsum, Pallas flash kernel, ring (KV "
                             "rotation over the mesh seq axis), or ulysses "
                             "(all-to-all head sharding over seq); ring and "
                             "ulysses are causal-only (gpt2 families)")
    parser.add_argument("--grad-accum", default=1, type=int,
                        help="gradient accumulation: microbatches per "
                             "optimizer step inside the jitted step "
                             "(reference-scale global batches on few chips)")
    parser.add_argument("--bucket-cap-mb", default=0.0, type=float,
                        help="explicit bucketed gradient sync (the DDP "
                             "reducer's bucket_cap_mb): flatten gradients "
                             "into contiguous fp32 buckets of at most this "
                             "many MB, one collective per bucket — "
                             "O(buckets) large transfers instead of "
                             "O(leaves) small ones. 0 = implicit XLA-"
                             "scheduled sync (the default). Incompatible "
                             "with --zero1")
    parser.add_argument("--wire-dtype", default="fp32", type=str,
                        choices=["fp32", "bf16", "int8", "int8_multihop",
                                 "int8_hier"],
                        help="gradient wire dtype for the explicit sync "
                             "path: bf16 halves the wire bytes; int8 adds "
                             "per-bucket scales + error feedback (bucketed "
                             "form is gather-based — a byte win at small "
                             "DP degrees, break-even ~9 replicas); "
                             "int8_multihop is the n-independent DynamiQ "
                             "form (s8 reduce-scatter, requantize, s8 "
                             "all-gather — 2 collectives/bucket, ~2 "
                             "B/element at any DP degree); int8_hier is "
                             "the two-tier topology-aware form on a "
                             "--slices factored mesh (exact fp32 reduce-"
                             "scatter inside a slice, the s8 multihop "
                             "exchange ACROSS slices — slow-link bytes ~2 "
                             "B/element per slice independent of the slice "
                             "count, exact intra-slice gather back); "
                             "master accumulation and the optimizer stay "
                             "fp32. bf16/int8 compose with --zero1 (the "
                             "reduce-scatter half compresses, n-"
                             "independently); int8_multihop + --zero1 is "
                             "rejected; int8_hier composes with --zero1 "
                             "and --fsdp-explicit but not explicit TP")
    parser.add_argument("--fused-quantize", default="auto", type=str,
                        choices=["auto", "on", "off"],
                        help="fused Pallas int8 codec kernels "
                             "(ops/quantize.py) for the int8 wire dtypes: "
                             "quantize (absmax-scale+round/clip) and "
                             "receive-side dequant-accumulate run as one "
                             "VMEM pass each instead of XLA's composed op "
                             "chain — bit-identical by contract "
                             "(PARITY.md). auto = TPU only (CPU keeps the "
                             "XLA-composed reference; DPT_FUSED_QUANTIZE "
                             "env overrides); on forces the kernels "
                             "(interpreter mode on CPU — for parity "
                             "tests/A-Bs); off forces the XLA path")
    parser.add_argument("--no-overlap-grad-sync", action="store_true",
                        help="with --bucket-cap-mb and --grad-accum > 1: "
                             "reduce buckets once after the microbatch "
                             "scan instead of inside it (exposes the "
                             "communication; for measuring the overlap "
                             "win)")
    parser.add_argument("--fsdp-explicit", action="store_true",
                        help="explicit full-parameter FSDP (SimpleFSDP): "
                             "params AND optimizer moments live flat-"
                             "sharded 1/N per replica at rest; each layer's "
                             "params are all-gathered just-in-time inside "
                             "the step (one collective per layer group, "
                             "chained one layer ahead so gathers overlap "
                             "compute) and gradients reduce-scatter "
                             "straight back into the shard layout. "
                             "Parameter memory at rest divides by the "
                             "data-parallel degree — the mode that unlocks "
                             "models whose replicated params+moments "
                             "don't fit one device. Composes with "
                             "--wire-dtype (bf16/int8 compress the "
                             "gradient scatter; int8_multihop also "
                             "compresses the param gathers as s8 codes + "
                             "per-chunk scales). Incompatible with --zero1 "
                             "(this IS zero1 plus sharded params) and "
                             "--bucket-cap-mb (the per-layer cut owns the "
                             "wire layout)")
    parser.add_argument("--zero1", action="store_true",
                        help="ZeRO-1 cross-replica weight-update sharding "
                             "for data-parallel meshes: reduce-scatter "
                             "gradients, update 1/N of the params + "
                             "optimizer state per replica, all-gather the "
                             "new params — optimizer compute/memory / N. "
                             "Default off (replicated DDP-style update). "
                             "On meshes with a model axis the update "
                             "shards per-leaf via GSPMD constraints "
                             "instead of the manual shard_map (fp32 wire "
                             "only there)")
    parser.add_argument("--remat", action="store_true",
                        help="gradient checkpointing: recompute each "
                             "transformer block in the backward pass "
                             "(jax.checkpoint) — trades FLOPs for HBM, "
                             "enabling longer sequences / bigger batches")
    parser.add_argument("--schedule", default="constant", type=str,
                        help="lr schedule: constant | cosine | linear_warmup")
    parser.add_argument("--warmup-steps", default=0, type=int)
    parser.add_argument("--drop-last", action="store_true",
                        help="drop the final partial batch (ref default: keep it)")
    parser.add_argument("--no-augment", action="store_true",
                        help="disable train-time crop/flip augmentation")
    parser.add_argument("--cifar-stem", action="store_true",
                        help="3x3/1 ResNet stem for 32x32 inputs (ref uses the "
                             "unmodified ImageNet stem)")
    parser.add_argument("--checkpoint-dir", default=None, type=str,
                        help="enable checkpointing to this directory")
    parser.add_argument("--checkpoint-every", default=1, type=int,
                        help="checkpoint every N epochs")
    parser.add_argument("--resume", action="store_true",
                        help="resume from latest checkpoint in --checkpoint-dir")
    parser.add_argument("--max-restarts", default=0, type=int,
                        help="in-process restart supervisor "
                             "(resilience/supervisor.py): on a step/save "
                             "failure, restore the latest VALID checkpoint "
                             "(torn ones are integrity-skipped) and replay "
                             "behind the step fence, retrying under bounded "
                             "exponential backoff at most this many times. "
                             "0 = off. Requires --checkpoint-dir")
    parser.add_argument("--chaos", default=None, type=str,
                        help="deterministic fault injection "
                             "(resilience/faults.py), e.g. 'crash@step=7,"
                             "sigterm@step=12,torn_ckpt@save=2,"
                             "loader_stall@step=5:2.5s'. Each fault fires "
                             "once; compose with --max-restarts to watch "
                             "the run recover (or without it, to watch it "
                             "die and --resume)")
    parser.add_argument("--profile-dir", default=None, type=str,
                        help="capture a jax.profiler trace into this directory")
    parser.add_argument("--profile-steps", default="10,20", type=str,
                        help="start,stop step of the profiled window")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="disable the structured run telemetry stream "
                             "(telemetry_rank0.jsonl in --output-dir, "
                             "process 0 only) and the flight recorder. "
                             "Telemetry is host-side only and never "
                             "changes training numerics (PARITY.md)")
    parser.add_argument("--telemetry-all-ranks", action="store_true",
                        help="stream telemetry from EVERY rank "
                             "(telemetry_rank<R>.jsonl per process) "
                             "instead of rank 0 only — the fleet "
                             "aggregation input (`telemetry aggregate`). "
                             "Also armed by DPT_TELEMETRY_ALL_RANKS=1 "
                             "(how the fleet orchestrator reaches "
                             "children). Default off: one stream, "
                             "unchanged disk cost")
    parser.add_argument("--metrics-port", default=None, type=int,
                        help="serve live /metrics (Prometheus text) + "
                             "/healthz (step-fence liveness) on this "
                             "port + rank offset "
                             "(telemetry/metrics_http.py). Default: "
                             "DPT_METRICS_PORT env, else off — off "
                             "starts zero threads")
    parser.add_argument("--autopilot", action="store_true",
                        help="attach the control-plane autopilot "
                             "(control/): straggler detection over the "
                             "telemetry stream with gated eviction at "
                             "segment boundaries. Requires "
                             "--max-restarts (the Supervisor owns the "
                             "boundaries) and telemetry ON. Off (the "
                             "default) constructs nothing: zero threads, "
                             "zero observers, an event stream and "
                             "lowered HLO byte-identical to a build "
                             "without the control package")
    parser.add_argument("--autopilot-tune", action="store_true",
                        help="also arm the autopilot's online perf "
                             "tuner: exposed-comm ratios from profiled "
                             "windows propose a wire re-plan, applied at "
                             "a segment boundary ONLY after the "
                             "control_replan contract matrix passes the "
                             "candidate (refused and logged otherwise)")
    parser.add_argument("--telemetry-abort", action="store_true",
                        help="turn the anomaly watchdog's abort hook ON: "
                             "a detected non-finite loss / step-time spike "
                             "/ loader stall raises instead of only "
                             "emitting an `anomaly` event (under "
                             "--max-restarts that means restore+replay)")

    return parser.parse_args(argv)


def parse_model_overrides(spec: str) -> dict:
    """'depth=2,hidden_dim=64' -> {'depth': 2, 'hidden_dim': 64}. Values
    parse as int, then float, then bool ('true'/'false'), else string."""
    out: dict = {}
    for item in filter(None, (s.strip() for s in spec.split(","))):
        if "=" not in item:
            raise ValueError(
                f"--model-overrides entry {item!r} is not field=value")
        key, val = (s.strip() for s in item.split("=", 1))
        for cast in (int, float):
            try:
                out[key] = cast(val)
                break
            except ValueError:
                continue
        else:
            out[key] = {"true": True, "false": False}.get(val.lower(), val)
    return out
