"""Metrics persistence + throughput measurement.

Reproduces the reference's observable surfaces exactly (the judge-comparable
contract, SURVEY.md §5):
* ``metrics_rank0.csv`` with header
  ``epoch,train_loss,train_acc,val_loss,val_acc,epoch_time_seconds``
  (/root/reference/train_ddp.py:349-354), append-only across runs (header
  written only if the file is absent, ref :350), written by process 0 only.
* The samples/s throughput meter (ref :224-243): global samples per wall
  second, windowed between print boundaries.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Optional

from .logging import is_main_process


class MetricsCSV:
    """Process-0 CSV writer with the reference's exact schema."""

    HEADER = "epoch,train_loss,train_acc,val_loss,val_acc,epoch_time_seconds\n"

    def __init__(self, output_dir: str, filename: str = "metrics_rank0.csv"):
        self.path = Path(output_dir) / filename
        if is_main_process():
            self.path.parent.mkdir(parents=True, exist_ok=True)
            if not self.path.exists():  # append-only across runs (ref :350)
                self.path.write_text(self.HEADER)

    def append(self, epoch: int, train_loss: float, train_acc: float,
               val_loss: float, val_acc: float, epoch_time: float) -> None:
        """One row per epoch (ref :380-384; formats match exactly).

        Durable per row: flush + fsync before the handle closes, so a
        crash/SIGKILL right after an epoch completes (the chaos faults
        make that a routine scenario) cannot drop the row of an epoch
        whose work was already fully paid for. One fsync per EPOCH is
        noise; losing an epoch's row silently is not."""
        if not is_main_process():
            return
        with self.path.open("a") as f:
            f.write(
                f"{epoch + 1},{train_loss:.4f},{train_acc:.2f},"
                f"{val_loss:.4f},{val_acc:.2f},{epoch_time:.4f}\n"
            )
            f.flush()
            os.fsync(f.fileno())


class ThroughputMeter:
    """Windowed samples/s (ref :192-193, :224-235): accumulate wall time and
    global sample counts, read+reset at print boundaries.

    Timed with ``time.perf_counter()`` — monotonic. ``time.time()`` is
    wall-clock and steps under NTP corrections, so one adjustment inside a
    window would corrupt the published samples/s (even negative dt)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._samples = 0

    def update(self, n_global_samples: int) -> None:
        self._samples += n_global_samples

    def rate(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._samples / dt if dt > 0 else 0.0
