"""Runtime lock tracing: the dynamic half of the concurrency rules.

The static pass (analysis/concurrency_rules.py) reads lexical ``with``
nesting — it cannot see an acquisition reached through a method call in
another class (scheduler ``step`` holding its lock while ``queue.take``
waits on the queue's condition). This module closes that gap at TEST
time: every lock the control plane constructs goes through
:func:`named_lock` / :func:`named_condition`, and under ``DPT_LOCKCHECK=1``
those return instrumented locks that record

* the per-thread nested acquisition order (``(outer, inner)`` edges,
  same ``ClassName.attr`` identities the static graph uses), and
* hold-while-blocking events (a probed blocking call — ``time.sleep``,
  ``socket.create_connection`` — entered while the thread holds any
  traced lock).

:func:`cross_check` merges the observed edges into the static graph and
returns the inconsistencies (reversed orders, cycles) — the tier-1
interleaving tests assert it comes back empty.

**Zero cost when off** (the PARITY.md contract): with ``DPT_LOCKCHECK``
unset, ``named_lock`` returns a plain ``threading.Lock`` and
``named_condition`` a plain ``threading.Condition`` — no wrapper object,
no recording, no threads, no import of jax or the analysis engine —
so HLO and telemetry streams are bit-identical either way. This module
is stdlib-only; the analysis engine must never import it (the parent
package pulls jax), which is why :func:`cross_check` imports the static
graph lazily in the other direction.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple


def enabled() -> bool:
    return os.environ.get("DPT_LOCKCHECK", "") == "1"


class LockTrace:
    """The global recorder: per-thread held stacks, acquisition-order
    edges, hold-while-blocking events. One instance (module-level
    ``_TRACE``); its own bookkeeping lock is never exposed."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._held: Dict[int, List[str]] = {}
        self.acquisitions: List[Tuple[str, ...]] = []
        self.edges: Dict[Tuple[str, str], int] = {}
        self.blocking_events: List[Tuple[str, Tuple[str, ...]]] = []

    def reset(self) -> None:
        with self._mu:
            self._held.clear()
            self.acquisitions.clear()
            self.edges.clear()
            self.blocking_events.clear()

    def note_acquire(self, name: str) -> None:
        tid = threading.get_ident()
        with self._mu:
            stack = self._held.setdefault(tid, [])
            for outer in stack:
                if outer != name:
                    key = (outer, name)
                    self.edges[key] = self.edges.get(key, 0) + 1
            stack.append(name)
            self.acquisitions.append(tuple(stack))

    def note_release(self, name: str) -> None:
        tid = threading.get_ident()
        with self._mu:
            stack = self._held.get(tid, [])
            # remove the innermost occurrence (re-entrant RLocks release
            # in LIFO order; a plain Lock has exactly one)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == name:
                    del stack[i]
                    break
            if not stack:
                self._held.pop(tid, None)

    def held_by_current_thread(self) -> Tuple[str, ...]:
        with self._mu:
            return tuple(self._held.get(threading.get_ident(), ()))

    def note_blocking(self, desc: str) -> None:
        """Record `desc` as a blocking operation IF the calling thread
        holds any traced lock (otherwise it is uninteresting)."""
        held = self.held_by_current_thread()
        if held:
            with self._mu:
                self.blocking_events.append((desc, held))

    def order_edges(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self.edges)


_TRACE = LockTrace()


def trace() -> LockTrace:
    """The process-wide trace (meaningful only under DPT_LOCKCHECK=1)."""
    return _TRACE


class TracedLock:
    """A named, recording stand-in for ``threading.Lock``. Duck-typed
    (not a subclass — stdlib locks are C objects): acquire / release /
    locked / context manager, plus the private ``_release_save`` trio
    ``threading.Condition`` falls back to for non-stdlib locks, so
    ``named_condition`` can wrap one."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str,
                 inner: Optional[threading.Lock] = None) -> None:
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _TRACE.note_acquire(self.name)
        return got

    def release(self) -> None:
        _TRACE.note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TracedLock({self.name!r}, locked={self.locked()})"


def named_lock(name: str) -> "threading.Lock | TracedLock":
    """A lock whose acquisitions are traced under DPT_LOCKCHECK=1, and a
    plain ``threading.Lock`` (zero overhead, no wrapper) otherwise.
    ``name`` must match the static graph identity — ``ClassName.attr``
    for instance locks, ``module._NAME`` for module-level ones."""
    if enabled():
        return TracedLock(name)
    return threading.Lock()


def named_condition(name: str) -> threading.Condition:
    """A Condition over a traced lock under DPT_LOCKCHECK=1 (CPython's
    Condition duck-types non-stdlib locks through acquire/release), else
    a plain ``threading.Condition``. ``wait()`` releases the lock — the
    trace sees that as release + re-acquire, exactly the runtime truth."""
    if enabled():
        return threading.Condition(TracedLock(name))  # type: ignore[arg-type]
    return threading.Condition()


# ---------------------------------------------------------------------------
# Blocking-call probes (hold-while-blocking detection)
# ---------------------------------------------------------------------------

_PROBED: Dict[str, Tuple[object, str, Callable]] = {}


def install_probes() -> None:
    """Patch a small set of blocking entry points (``time.sleep``,
    ``socket.create_connection``) to record a hold-while-blocking event
    when called with any traced lock held. No-op unless DPT_LOCKCHECK=1;
    idempotent; undone by :func:`uninstall_probes`. Test-harness wiring
    — never called on import."""
    if not enabled() or _PROBED:
        return

    def wrap(owner: object, attr: str, desc: str) -> None:
        orig = getattr(owner, attr)

        def probed(*args, **kwargs):
            _TRACE.note_blocking(desc)
            return orig(*args, **kwargs)

        _PROBED[desc] = (owner, attr, orig)
        setattr(owner, attr, probed)

    wrap(time, "sleep", "time.sleep")
    wrap(socket, "create_connection", "socket.create_connection")


def uninstall_probes() -> None:
    for owner, attr, orig in _PROBED.values():
        setattr(owner, attr, orig)
    _PROBED.clear()


# ---------------------------------------------------------------------------
# Static cross-check
# ---------------------------------------------------------------------------


def cross_check(
        runtime_edges: Optional[Set[Tuple[str, str]]] = None) -> List[str]:
    """Merge the observed acquisition orders (default: the live trace)
    into the static lock-order graph and return the inconsistencies —
    empty means every runtime order is consistent with (acyclic under)
    the lexical graph. Imports the analysis engine lazily: the linter
    must stay importable without this module, not vice versa."""
    from ..analysis.concurrency_rules import check_runtime_consistency

    edges = runtime_edges if runtime_edges is not None \
        else _TRACE.order_edges()
    return check_runtime_consistency(edges)
