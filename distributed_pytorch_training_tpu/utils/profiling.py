"""Profiling — jax.profiler trace capture.

The reference promises a "Profiling run" and a gradient-sync share-of-step
analysis but implements neither (/root/reference/README.md:23,:35; SURVEY.md
§5). Here: a step-windowed `jax.profiler` trace (collective time is read off
the XLA trace timeline — on TPU the compiler fuses/overlaps the all-reduce,
so a timer around `.backward()` has no equivalent; trace analysis is the
correct instrument, BASELINE.json:5).
"""

from __future__ import annotations

import jax

from .logging import log_main


class StepProfiler:
    """Captures a jax.profiler trace for global steps [start, stop).

    Use as the Trainer's `step_hook`: fires `start_trace` when entering step
    `start` and `stop_trace` when entering step `stop`. Process 0 only (one
    trace per job; the XLA timeline includes every device it can see).
    """

    def __init__(self, log_dir: str, start: int, stop: int):
        if stop <= start:
            raise ValueError(f"profile window needs stop > start, got {start},{stop}")
        self.log_dir = log_dir
        self.start = start
        self.stop = stop
        self._active = False
        self._done = False
        self._seen = 0

    def __call__(self, step_in_epoch: int) -> None:
        step = self._seen
        self._seen += 1
        if self._done or jax.process_index() != 0:
            return
        if not self._active and self.start <= step < self.stop:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            log_main(f"Profiler trace (steps {self.start}-{self.stop}) "
                     f"written to {self.log_dir}")

    def close(self) -> None:
        """Stop the trace if the run ended inside the window."""
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            log_main(f"Profiler trace written to {self.log_dir}")

    # Context-manager protocol: an aborted profiled run (exception mid-
    # epoch) must not leave the jax profiler session open — a leaked
    # session makes every later start_trace in the process fail and drops
    # the partial trace on the floor. `with StepProfiler(...) as p:` closes
    # on ANY exit path.
    def __enter__(self) -> "StepProfiler":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()
