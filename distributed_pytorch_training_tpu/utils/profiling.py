"""Profiling — jax.profiler trace capture.

The reference promises a "Profiling run" and a gradient-sync share-of-step
analysis but implements neither (/root/reference/README.md:23,:35; SURVEY.md
§5). Here: a step-windowed `jax.profiler` trace (collective time is read off
the XLA trace timeline — on TPU the compiler fuses/overlaps the all-reduce,
so a timer around `.backward()` has no equivalent; trace analysis is the
correct instrument, BASELINE.json:5).

ISSUE 15 promotes the one-shot pre-run window to a *re-armable* capture
plane: :meth:`StepProfiler.request_capture` arms a short window at RUNTIME
(the ``POST /profile`` endpoint and the anomaly watchdog's capture hook both
land here), each armed capture lands in its own subdirectory and fires an
``on_capture`` callback (telemetry/device.py ingests the trace into a typed
``device_profile`` event), and every jax profiler session in the repo routes
through this module's session guard — a second ``start_trace`` while one is
open used to raise deep inside jax and poison the process's profiler; now it
is refused-and-logged with a ``profiler_busy`` counter (the
``profiler-session-via-stepprofiler-only`` AST rule keeps bare
``jax.profiler.start_trace`` calls from reappearing elsewhere).
"""

from __future__ import annotations

import contextlib
import os
import threading
from pathlib import Path
from typing import Any, Callable, Dict, Optional

import jax

from .locktrace import named_lock
from .logging import log_main

# ---------------------------------------------------------------------------
# The process-wide session guard. jax.profiler holds ONE global session per
# process; opening a second raises from deep inside jax (and a leaked open
# session fails every later start_trace). Every start/stop in this repo
# acquires here first, so a conflict is a refused capture + a counter, never
# a crash mid-training-run.
# ---------------------------------------------------------------------------

_SESSION_LOCK = named_lock("profiling._SESSION_LOCK")
# read without the lock by session_owner(): a racy diagnostic HINT (the
# busy-counter label); every decision-making read sits under the lock
_SESSION_OWNER: Optional[str] = None


def _acquire_session(owner: str) -> bool:
    global _SESSION_OWNER
    with _SESSION_LOCK:
        if _SESSION_OWNER is not None:
            return False
        _SESSION_OWNER = owner
        return True


def _release_session() -> None:
    global _SESSION_OWNER
    with _SESSION_LOCK:
        _SESSION_OWNER = None


def session_owner() -> Optional[str]:
    """Who holds the process's jax profiler session (None = free)."""
    return _SESSION_OWNER


def _note_busy(owner: str, wanted: str) -> None:
    """A refused capture is observability, not an error: one counter on the
    stream (no-op when telemetry is off) + one log line."""
    from .. import telemetry

    telemetry.counter("profiler_busy", 1, holder=owner, wanted=wanted)
    log_main(f"Profiler: capture {wanted!r} refused — session held by "
             f"{owner!r} (profiler_busy)")


@contextlib.contextmanager
def trace_session(log_dir: str, owner: str = "trace_session"):
    """The sanctioned raw-session form (experiments/trace_analysis.py's
    ``capture_step_trace`` rides it): start a jax.profiler trace into
    ``log_dir`` under the process-wide guard, yield True; if another
    session is open, yield False WITHOUT touching jax (the caller decides
    whether a missing trace is fatal). Always balanced: the stop runs on
    every exit path."""
    if not _acquire_session(owner):
        _note_busy(_SESSION_OWNER or "?", owner)
        yield False
        return
    jax.profiler.start_trace(str(log_dir))
    try:
        yield True
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            _release_session()


class StepProfiler:
    """Step-windowed + on-demand jax.profiler capture.

    Three ways a trace starts, all sharing one session guard:

    * the **static window** (``start``/``stop`` constructor args — the
      ``--profile-dir``/``--profile-steps`` CLI contract, unchanged):
      fires ``start_trace`` when entering step ``start`` and
      ``stop_trace`` entering step ``stop``, once per run, into
      ``log_dir`` itself;
    * an **armed capture** (:meth:`request_capture`, thread-safe — the
      ``POST /profile`` handler and the watchdog's anomaly hook call it
      from other threads/contexts): the next ``__call__`` opens a window
      of K steps into ``log_dir/capture_<pid>_<n>/``;
    * an **immediate capture** (:meth:`capture`, a context manager for
      mid-run host code): opens right now, closes at block exit.

    Use as the Trainer's `step_hook`: process 0 only (one trace per job;
    the XLA timeline includes every device it can see). When a window
    closes, ``on_capture(trace_dir, info)`` fires with the window's step
    range / reason / trigger — exceptions there are contained (a broken
    ingestor must never take the training run down).
    """

    def __init__(self, log_dir: str, start: Optional[int] = None,
                 stop: Optional[int] = None,
                 on_capture: Optional[Callable[[str, Dict[str, Any]],
                                               None]] = None,
                 max_captures: int = 16):
        if (start is None) != (stop is None):
            raise ValueError("profile window needs both start and stop "
                             f"(or neither), got {start},{stop}")
        if start is not None and stop <= start:
            raise ValueError(f"profile window needs stop > start, got "
                             f"{start},{stop}")
        self.log_dir = log_dir
        self.start = start
        self.stop = stop
        self.on_capture = on_capture
        self.max_captures = int(max_captures)
        # _active/_done/_seen/_window are STEP-THREAD state by design:
        # only __call__/close (the trainer's hook thread) touch them, so
        # they need no lock — cross-thread traffic comes in through
        # _pending only
        self._active = False          # the static window's session
        self._done = False            # the static window fired already
        self._seen = 0
        self._lock = named_lock("StepProfiler._lock")
        self._pending: Optional[Dict[str, Any]] = None   # guarded-by: _lock
        self._window: Optional[Dict[str, Any]] = None  # armed, in flight
        self._n_captures = 0          # guarded-by: _lock
        self.busy_refused = 0         # guarded-by: _lock

    # -- on-demand arming (thread-safe: HTTP/watchdog callers) -----------

    def request_capture(self, steps: int, reason: str = "http",
                        trigger_step: Optional[int] = None) -> bool:
        """Arm a capture of the next ``steps`` steps. Returns False —
        with a ``profiler_busy`` counter — when a window is already armed
        or in flight, the static window is open, another component holds
        the jax session, or the per-run capture budget is spent (the
        ``/profile`` 409 contract: refuse, never clobber)."""
        try:
            steps = int(steps)
        except (TypeError, ValueError):
            return False
        if steps < 1:
            return False
        if jax.process_index() != 0:
            # non-zero processes never open windows (__call__ returns
            # before the armed logic) — accepting the arm would wedge
            # this rank's profiler on a pending that can never fire
            return False
        with self._lock:
            if (self._pending is not None or self._window is not None
                    or self._active or session_owner() is not None
                    or self._n_captures >= self.max_captures):
                self.busy_refused += 1
                holder = session_owner() or (
                    "capture budget spent"
                    if self._n_captures >= self.max_captures
                    else "StepProfiler")
                _note_busy(holder, reason)
                return False
            self._pending = {"steps": steps, "reason": reason,
                             "trigger_step": trigger_step}
            return True

    def _capture_dir(self) -> str:   # lock-held: _lock
        # pid-qualified: fleet children of successive generations share
        # one profiles directory, and trace parsing globs recursively —
        # two captures must never mix sessions under one subdir
        d = Path(self.log_dir) / f"capture_{os.getpid()}_{self._n_captures:03d}"
        self._n_captures += 1
        return str(d)

    def _fire_on_capture(self, trace_dir: str, info: Dict[str, Any]) -> None:
        if self.on_capture is None:
            return
        try:
            self.on_capture(trace_dir, info)
        except Exception as e:  # noqa: BLE001 — ingestion is observability
            log_main(f"Profiler: on_capture ingestion failed ({e}) — "
                     "trace kept on disk, run continues")

    def _close_armed_window(self, elapsed: int) -> None:
        """Stop the armed window's session and fire ingestion.
        ``elapsed`` is the number of step-hook calls the window actually
        spanned (from the ``_seen`` counter) — the honest step count
        even when the run ended before the requested K, and even when
        the epoch-local step labels reset across an epoch boundary.
        Caller holds no lock; only the step thread opens/closes
        windows."""
        window = self._window
        self._window = None
        if window is None:
            return
        jax.profiler.stop_trace()
        _release_session()
        elapsed = max(0, int(elapsed))
        stop_step = window["start_step"] + elapsed
        info = {"start_step": window["start_step"], "stop_step": stop_step,
                "steps": elapsed,
                "reason": window["reason"],
                "trigger_step": window["trigger_step"]}
        log_main(f"Profiler: on-demand trace (steps "
                 f"{info['start_step']}-{stop_step}, {info['reason']}) "
                 f"written to {window['dir']}")
        self._fire_on_capture(window["dir"], info)

    # -- immediate mid-run capture ---------------------------------------

    @contextlib.contextmanager
    def capture(self, reason: str = "capture"):
        """Immediate capture: yields the trace directory, or None when a
        window/session is already open (refused-and-logged, the block
        still runs — a busy profiler must never change control flow)."""
        with self._lock:
            busy = (self._pending is not None or self._window is not None
                    or self._active
                    or self._n_captures >= self.max_captures)
        if busy or not _acquire_session(f"StepProfiler.capture:{reason}"):
            with self._lock:
                self.busy_refused += 1
            _note_busy(session_owner() or "StepProfiler", reason)
            yield None
            return
        with self._lock:
            # allocate the capture-budget slot only once the session is
            # actually ours — refusals must not burn budget
            trace_dir = self._capture_dir()
        jax.profiler.start_trace(trace_dir)
        try:
            yield trace_dir
        finally:
            try:
                jax.profiler.stop_trace()
            finally:
                _release_session()
            self._fire_on_capture(trace_dir,
                                  {"start_step": None, "stop_step": None,
                                   "steps": None, "reason": reason,
                                   "trigger_step": None})

    # -- the step hook ----------------------------------------------------

    def __call__(self, step_in_epoch: int) -> None:
        step = self._seen
        self._seen += 1
        if jax.process_index() != 0:
            return
        # armed window close (K calls after it opened)
        if self._window is not None and \
                step >= self._window["start_seen"] + self._window["steps"]:
            self._close_armed_window(step - self._window["start_seen"])
        # armed window open (a pending request from /profile or the
        # watchdog): one capture at a time, never while the static
        # window's session is open
        if self._window is None and not self._active:
            with self._lock:
                pending, self._pending = self._pending, None
            if pending is not None:
                # under the lock: _capture_dir draws from the shared
                # capture budget, and a concurrent capture() drawing at
                # the same instant would mint the same directory name
                with self._lock:
                    trace_dir = self._capture_dir()
                if _acquire_session("StepProfiler.armed"):
                    jax.profiler.start_trace(trace_dir)
                    self._window = {"dir": trace_dir,
                                    "steps": pending["steps"],
                                    "start_seen": step,
                                    "start_step": int(step_in_epoch),
                                    "reason": pending["reason"],
                                    "trigger_step": pending["trigger_step"]}
                else:   # raced by another holder between arm and open
                    with self._lock:
                        self.busy_refused += 1
                    _note_busy(session_owner() or "?", pending["reason"])
        # the static --profile-steps window (original semantics: _seen
        # indices, one window per run, replay-safe via _active/_done)
        if self._done or self.start is None:
            return
        if not self._active and self.start <= step < self.stop:
            if self._window is not None:
                return   # an armed capture is mid-flight; retry next step
            if not _acquire_session("StepProfiler.window"):
                _note_busy(session_owner() or "?", "window")
                return
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and step >= self.stop:
            jax.profiler.stop_trace()
            _release_session()
            self._active = False
            self._done = True
            log_main(f"Profiler trace (steps {self.start}-{self.stop}) "
                     f"written to {self.log_dir}")
            self._fire_on_capture(
                self.log_dir, {"start_step": self.start,
                               "stop_step": self.stop,
                               "steps": self.stop - self.start,
                               "reason": "window", "trigger_step": None})

    def close(self) -> None:
        """Stop any open trace if the run ended inside a window."""
        if self._window is not None:
            # honest truncation: count the hook calls actually spanned,
            # not the K the request asked for
            self._close_armed_window(self._seen
                                     - self._window["start_seen"])
        if self._active:
            jax.profiler.stop_trace()
            _release_session()
            self._active = False
            self._done = True
            log_main(f"Profiler trace written to {self.log_dir}")
            self._fire_on_capture(
                self.log_dir, {"start_step": self.start,
                               "stop_step": self._seen,
                               "steps": max(0, self._seen
                                            - (self.start or 0)),
                               "reason": "window", "trigger_step": None})

    # Context-manager protocol: an aborted profiled run (exception mid-
    # epoch) must not leave the jax profiler session open — a leaked
    # session makes every later start_trace in the process fail and drops
    # the partial trace on the floor. `with StepProfiler(...) as p:` closes
    # on ANY exit path.
    def __enter__(self) -> "StepProfiler":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()
