"""Config, logging, metrics, profiling — the reference's L5/L6 layers
(/root/reference/train_ddp.py:19-46, :224-262, :348-384)."""

from .logging import log_main  # noqa: F401
from .metrics import MetricsCSV, ThroughputMeter  # noqa: F401
from .config import parse_args  # noqa: F401
