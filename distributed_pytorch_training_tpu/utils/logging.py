"""Main-process-only logging — the reference's rank-0 print convention
(/root/reference/train_ddp.py:229, :326-327, :374-379). Single-writer output
is also the race-avoidance story for log files (SURVEY.md §5)."""

from __future__ import annotations

import sys

import jax


def is_main_process() -> bool:
    return jax.process_index() == 0


def log_main(*args, **kwargs) -> None:
    """print() on process 0 only (ref `if rank == 0: print(...)`)."""
    if is_main_process():
        print(*args, **kwargs)
        sys.stdout.flush()
