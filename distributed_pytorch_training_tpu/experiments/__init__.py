"""Experiment drivers — the studies the reference's README promises but never
fills in (/root/reference/README.md:25-35 "Experiments & Results": single vs
multi-device scaling, throughput vs batch size, mixed-precision speedup, and
the gradient-sync share of step time).

Run as modules, e.g.::

    python -m distributed_pytorch_training_tpu.experiments.scaling scaling
    python -m distributed_pytorch_training_tpu.experiments.scaling amp
    python -m distributed_pytorch_training_tpu.experiments.scaling gradsync
"""
