"""Gradient-sync share from jax.profiler traces.

The reference README promises "At 4 GPUs, gradient synchronization accounts
for ~X% of step time" but never measures it (/root/reference/README.md:35) —
on GPU one would read an nsys/profiler timeline. The TPU equivalent: capture
a `jax.profiler` trace of the compiled train step and sum the durations of
collective ops (the DDP all-reduce equivalents XLA scheduled) against the
total XLA-op busy time. This module parses the Chrome-trace JSON the profiler
writes (`plugins/profile/<ts>/<host>.trace.json.gz`) — no tensorboard plugin
needed.

Instruments in experiments/scaling.py `gradsync`, cross-checked three ways:
(a) measured 1-vs-N step-time delta, (b) static HLO collective census
(`collective_census` below, plus the zero1 weight-update classification
`weight_update_census`/`verify_zero1_collectives`), (c) the trace-derived
share (the profiler-timeline read-off the README placeholder calls for).
"""

from __future__ import annotations

import glob
import gzip
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# Collective op names as they appear on XLA timelines (sync form, async
# `-start` form, and CPU thunk form). `-done` events are completion markers
# whose duration is wait-not-work; skip them like the HLO census does.
_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?!.*-done)")

# Host-side runtime bookkeeping seen on CPU traces (no device lanes exist
# there); everything matching these is neither compute nor communication.
_INFRA_PREFIXES = (
    "ThreadpoolListener", "ThunkExecutor", "Wait", "Rendezvous", "PjRt",
    "CommonPjRt", "Handle inputs", "end:", "CreateOutputs", "Allocate",
    "Deallocate", "BufferAlloc", "BufferFree", "MarkDonated", "python",
    "HostCallback", "TransferTo", "TransferFrom", "CopyTo", "CopyFrom",
    "ExecuteHelper", "Execute (", "call_location",
)


def _norm(name: str) -> str:
    """'wrapped_all-reduce.3' -> 'all-reduce.3' (CPU thunks wrap op names)."""
    return name[8:] if name.startswith("wrapped_") else name


def load_trace(log_dir: str) -> Tuple[List[dict], Dict[int, str],
                                      Dict[tuple, str]]:
    """(complete events, pid -> process name, (pid, tid) -> thread name)
    from every trace.json.gz under `log_dir` (one per host). Raises
    FileNotFoundError if no trace exists."""
    paths = sorted(glob.glob(
        str(Path(log_dir) / "**" / "*.trace.json.gz"), recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {log_dir}")
    events: List[dict] = []
    pids: Dict[int, str] = {}
    tids: Dict[tuple, str] = {}
    for p in paths:
        data = json.loads(gzip.open(p).read())
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e.get("pid")] = e.get("args", {}).get("name", "")
            elif e.get("ph") == "M" and e.get("name") == "thread_name":
                tids[(e.get("pid"), e.get("tid"))] = (
                    e.get("args", {}).get("name", ""))
            elif e.get("ph") == "X" and e.get("dur", 0) > 0:
                events.append(e)
    return events, pids, tids


def xla_op_events(events: List[dict], pids: Dict[int, str],
                  tids: Dict[tuple, str]) -> List[dict]:
    """The events that represent on-device XLA op execution, counted ONCE.

    TPU/GPU traces put ops on `/device:...` process lanes, but a device pid
    carries several overlapping lanes ("XLA Modules" spans the same wall
    time as the sum of its "XLA Ops") — summing all of them double-counts
    busy time and halves the reported collective share, so restrict to the
    per-op lanes when thread names identify them. CPU traces (the test
    backend) run thunks on host threadpool lanes with no device pids; fall
    back to name-based filtering of runtime bookkeeping.
    """
    device_pids = {pid for pid, name in pids.items() if "/device:" in name}
    if device_pids:
        dev = [e for e in events if e.get("pid") in device_pids]
        op_lanes = {key for key, name in tids.items()
                    if key[0] in device_pids and "xla ops" in name.lower()}
        if op_lanes:
            return [e for e in dev
                    if (e.get("pid"), e.get("tid")) in op_lanes]
        return dev
    return [e for e in events
            if not _norm(e["name"]).startswith(_INFRA_PREFIXES)]


def collective_share(log_dir: str) -> dict:
    """Trace-derived gradient-sync share: collective time / XLA-op busy time.

    Returns {collective_us, op_us, share_pct, by_op: {name: us}} aggregated
    over every device lane in the capture window. `share_pct` is the
    fraction of device busy time spent in communication — the number the
    reference's README placeholder wants (README.md:35).
    """
    events, pids, tids = load_trace(log_dir)
    ops = xla_op_events(events, pids, tids)
    coll_us = 0.0
    op_us = 0.0
    by_op: Dict[str, float] = {}
    for e in ops:
        name = _norm(e["name"])
        dur = float(e["dur"])
        op_us += dur
        m = _COLLECTIVE_RE.match(name)
        if m:
            coll_us += dur
            key = m.group(1)
            by_op[key] = by_op.get(key, 0.0) + dur
    return {
        "collective_us": round(coll_us, 1),
        "op_us": round(op_us, 1),
        "share_pct": round(100.0 * coll_us / op_us, 2) if op_us else 0.0,
        "by_op": {k: round(v, 1) for k, v in sorted(by_op.items())},
    }


# ---------------------------------------------------------------------------
# Static HLO collective census (the compile-time half of the gradient-sync
# analysis; the trace functions above are the runtime half).
# ---------------------------------------------------------------------------

# HLO text: `%name = shape op-name(...)`. On TPU the latency-hiding scheduler
# splits collectives into async `-start`/`-done` pairs; count the `-start`
# half (and bare sync forms), never `-done`, so each collective counts once.
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?[.\w]*\(")

# One array shape inside an HLO result: "f32[1000,512]{1,0}" (possibly inside
# a tuple). Captures the bracketed dims; "f32[]" is a scalar.
_HLO_SHAPE_RE = re.compile(r"\w+\[([\d,]*)\]")

# Same shape token with the DTYPE captured instead ("f32", "bf16", "s8") —
# the wire-dtype read of `grad_sync_census`. Context/token dtypes (u32 ids
# in async tuples) ride along; the census reports all of them.
_HLO_TYPED_SHAPE_RE = re.compile(r"(\w+)\[[\d,]*\]")


def hlo_result_elements(shape_str: str) -> int:
    """Total elements across every array in an HLO result shape string
    (async collectives return tuples; sum the parts so `-start` forms
    compare like their sync equivalents)."""
    total = 0
    for m in _HLO_SHAPE_RE.finditer(shape_str):
        dims = m.group(1)
        if not dims:
            total += 1  # scalar
            continue
        n = 1
        for d in dims.split(","):
            n *= int(d)
        total += n
    return total


def collective_census(compiled_text: str) -> List[dict]:
    """Census of collective ops in optimized HLO text: op kind + result shape.

    The static half of the grad-sync analysis: what the compiler actually
    scheduled (names/shapes straight from the executable), standing in for
    the reference's promised profiler-timeline read-off (README.md:35)."""
    rows = {}
    for m in _HLO_COLLECTIVE_RE.finditer(compiled_text):
        shape, kind, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue  # the paired completion of an async -start
        key = (kind, shape)
        if key not in rows:
            rows[key] = {"op": kind, "result_shape": shape, "count": 0}
        rows[key]["count"] += 1
    return sorted(rows.values(), key=lambda r: (r["op"], r["result_shape"]))


def weight_update_census(compiled_text: str, min_elements: int = 8192) -> dict:
    """The gradient-sync subset of the census: collectives whose result
    carries at least `min_elements` elements — gradient- and parameter-sized
    transfers. Scalar psums (metric fan-in, global-norm clipping, BatchNorm
    channel stats) fall under the floor, so the returned counts isolate the
    ops that move the model: the DDP-style grad all-reduce on the replicated
    path, reduce-scatter + all-gather on the zero1 path.

    Returns {"all-reduce": n, "reduce-scatter": n, "all-gather": n,
    "rows": [...]} (other collective kinds appear only if present)."""
    counts: Dict[str, int] = {"all-reduce": 0, "reduce-scatter": 0,
                              "all-gather": 0}
    rows = []
    for c in collective_census(compiled_text):
        if hlo_result_elements(c["result_shape"]) < min_elements:
            continue
        counts[c["op"]] = counts.get(c["op"], 0) + c["count"]
        rows.append(c)
    counts["rows"] = rows
    return counts


def verify_zero1_collectives(replicated_text: str, zero1_text: str,
                             min_elements: int = 8192) -> dict:
    """The acceptance check for the zero1 mode (ISSUE 1): in the compiled
    zero1 step, gradient-sized all-reduces are REPLACED by reduce-scatter +
    all-gather. Returns the two weight-update censuses plus a verdict dict;
    raises AssertionError naming the offending ops when the replacement did
    not happen (a silent fallback to all-reduce would erase the win while
    the flag still claims it)."""
    rep = weight_update_census(replicated_text, min_elements)
    z1 = weight_update_census(zero1_text, min_elements)
    if rep["all-reduce"] == 0:
        raise AssertionError(
            "replicated step shows no gradient-sized all-reduce — the "
            f"census floor ({min_elements} elements) is above the model's "
            "gradient transfers; lower min_elements")
    problems = []
    if z1["all-reduce"]:
        problems.append(
            f"zero1 step still contains {z1['all-reduce']} gradient-sized "
            f"all-reduce(s): {[r for r in z1['rows'] if r['op'] == 'all-reduce']}")
    if not z1["reduce-scatter"]:
        problems.append("zero1 step contains no reduce-scatter")
    if not z1["all-gather"]:
        problems.append("zero1 step contains no all-gather")
    if problems:
        raise AssertionError("; ".join(problems))
    return {"replicated": rep, "zero1": z1}


def grad_sync_census(hlo_text: str, min_elements: int = 8192) -> dict:
    """Census of the gradient-sync stage in HLO text: how many gradient-
    sized collectives the step carries, and what dtype rides the wire.

    The instrument for the bucketed reducer (parallel/grad_sync.py): with
    ``bucket_cap_mb`` set, the compiled step must show
    ``ceil(total_grad_bytes / cap)`` large collectives (one per bucket)
    instead of one per leaf, and with a compressed ``wire_dtype`` their
    operands must be bf16/s8, not f32. Accepts optimized HLO
    (``compiled.as_text()``) or pre-optimization HLO (`preopt_hlo_text`):
    CPU's float-normalization pass promotes bf16 collectives to f32 in the
    OPTIMIZED text, so wire-dtype checks on the test backend read the
    pre-optimization module (TPU keeps bf16 end-to-end).

    Returns {"n_collectives", "by_op": {op: n}, "wire_dtypes": {dtype: n},
    "rows": [...]} counting only collectives whose result carries at least
    `min_elements` elements (scalar metric psums and int8 scale gathers
    fall under the floor).
    """
    by_op: Dict[str, int] = {}
    wire: Dict[str, int] = {}
    rows = []
    total = 0
    for c in collective_census(hlo_text):
        if hlo_result_elements(c["result_shape"]) < min_elements:
            continue
        total += c["count"]
        by_op[c["op"]] = by_op.get(c["op"], 0) + c["count"]
        dtypes = sorted(set(
            m.group(1)
            for m in _HLO_TYPED_SHAPE_RE.finditer(c["result_shape"])))
        for d in dtypes:
            wire[d] = wire.get(d, 0) + c["count"]
        rows.append({**c, "dtypes": dtypes})
    return {"n_collectives": total, "by_op": by_op, "wire_dtypes": wire,
            "rows": rows}


def preopt_hlo_text(lowered) -> str:
    """Pre-optimization HLO text of a ``jax.jit(...).lower(...)`` result —
    the wire-dtype read for `grad_sync_census` (see its docstring: the CPU
    backend's float-normalization rewrites bf16 collectives to f32 before
    the optimized text is printed)."""
    return lowered.compiler_ir(dialect="hlo").as_hlo_text()


def verify_grad_sync_collectives(
    optimized_text: str,
    *,
    total_grad_bytes: int,
    bucket_cap_mb: float,
    wire_dtype: str = "fp32",
    wire_text: Optional[str] = None,
    min_elements: int = 8192,
    slack: int = 2,
) -> dict:
    """The ISSUE-2 acceptance check for the bucketed reducer: the compiled
    step performs at most ``ceil(total_grad_bytes / bucket_cap) + slack``
    gradient-sized collectives, and compressed modes put bf16/int8 on the
    wire. ``wire_text`` defaults to ``optimized_text``; pass the
    pre-optimization HLO on backends that promote small floats (CPU).
    Raises AssertionError naming the violation; returns the censuses.
    """
    census = grad_sync_census(optimized_text, min_elements)
    # The SAME arithmetic as grad_sync.build_bucket_plan (which floors the
    # cap to whole fp32 elements): re-deriving it as ceil(bytes/cap_bytes)
    # would under-count buckets whenever the cap is not element-aligned and
    # flag a correctly engaged reducer.
    total_elems = int(total_grad_bytes) // 4
    cap_elems = int(bucket_cap_mb * (1024 ** 2) // 4)
    if bucket_cap_mb <= 0 or cap_elems >= total_elems:
        n_buckets = 1  # no/huge cap = one fused bucket
    else:
        n_buckets = -(-total_elems // max(cap_elems, 1))
    bound = n_buckets + slack
    if census["n_collectives"] > bound:
        raise AssertionError(
            f"bucketed step carries {census['n_collectives']} gradient-"
            f"sized collectives, more than ceil({total_grad_bytes}B / "
            f"{bucket_cap_mb}MB) + {slack} = {bound}: {census['by_op']} — "
            "bucketing is not engaged (or the census floor "
            f"min_elements={min_elements} is below scalar traffic)")
    if census["n_collectives"] == 0:
        raise AssertionError(
            "no gradient-sized collectives found — the census floor "
            f"(min_elements={min_elements}) is above the model's gradient "
            "transfers; lower it")
    wire_census = (grad_sync_census(wire_text, min_elements)
                   if wire_text is not None else census)
    expect = {"fp32": "f32", "bf16": "bf16", "int8": "s8"}[wire_dtype]
    if not wire_census["wire_dtypes"].get(expect):
        raise AssertionError(
            f"wire_dtype={wire_dtype!r} promises {expect} collective "
            f"operands on the wire, but the HLO shows "
            f"{wire_census['wire_dtypes']}")
    return {"census": census, "wire": wire_census["wire_dtypes"],
            "bound": bound}


def comm_overlap_split(log_dir: str) -> dict:
    """Exposed-vs-hidden communication time from a jax.profiler trace —
    the overlap instrument of the bucketed reducer (DDP's hooks hide comm
    behind backward compute; here the scan-body collectives have no data
    dependency on the next microbatch, and this measures how much of their
    wall time XLA actually hid).

    A collective event's duration is HIDDEN where it overlaps (same pid,
    any lane) with non-collective op execution, EXPOSED elsewhere. On TPU
    timelines async ``-start`` events span the transfer, so the split is
    honest; on the CPU test backend thunks serialize on the threadpool, so
    exposed ~= 100% — the number is only meaningful with device lanes.

    Returns {collective_us, hidden_us, exposed_us, exposed_frac_pct}.
    """
    events, pids, tids = load_trace(log_dir)
    ops = xla_op_events(events, pids, tids)
    comp_by_pid: Dict[int, List[Tuple[float, float]]] = {}
    coll = []
    for e in ops:
        iv = (float(e["ts"]), float(e["ts"]) + float(e["dur"]))
        if _COLLECTIVE_RE.match(_norm(e["name"])):
            coll.append((e.get("pid"), iv))
        else:
            comp_by_pid.setdefault(e.get("pid"), []).append(iv)
    merged: Dict[int, List[Tuple[float, float]]] = {}
    for pid, ivs in comp_by_pid.items():
        ivs.sort()
        out: List[Tuple[float, float]] = []
        for a, b in ivs:
            if out and a <= out[-1][1]:
                out[-1] = (out[-1][0], max(out[-1][1], b))
            else:
                out.append((a, b))
        merged[pid] = out
    total = hidden = 0.0
    for pid, (a, b) in coll:
        total += b - a
        for ca, cb in merged.get(pid, ()):
            if cb <= a:
                continue
            if ca >= b:
                break
            hidden += min(b, cb) - max(a, ca)
    exposed = max(0.0, total - hidden)
    return {
        "collective_us": round(total, 1),
        "hidden_us": round(hidden, 1),
        "exposed_us": round(exposed, 1),
        "exposed_frac_pct": round(100.0 * exposed / total, 2) if total
        else 0.0,
    }


def capture_step_trace(step_fn, state, batch, key, log_dir: str,
                       steps: int = 3):
    """Run `steps` executions of a compiled/jitted train step under a
    jax.profiler trace (call AFTER warmup so compile time stays out of the
    window). Returns the final state."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        metrics = None
        for _ in range(steps):
            state, metrics = step_fn(state, batch, key)
        if metrics is not None:
            jax.block_until_ready(metrics)
            float(jax.device_get(metrics["weight"]))  # true completion sync
    finally:
        jax.profiler.stop_trace()
    return state
