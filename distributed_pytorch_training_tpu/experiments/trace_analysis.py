"""Gradient-sync share from jax.profiler traces.

The reference README promises "At 4 GPUs, gradient synchronization accounts
for ~X% of step time" but never measures it (/root/reference/README.md:35) —
on GPU one would read an nsys/profiler timeline. The TPU equivalent: capture
a `jax.profiler` trace of the compiled train step and sum the durations of
collective ops (the DDP all-reduce equivalents XLA scheduled) against the
total XLA-op busy time. This module parses the Chrome-trace JSON the profiler
writes (`plugins/profile/<ts>/<host>.trace.json.gz`) — no tensorboard plugin
needed.

Instruments in experiments/scaling.py `gradsync`, cross-checked three ways:
(a) measured 1-vs-N step-time delta, (b) static HLO collective census
(`collective_census` below, plus the zero1 weight-update classification
`weight_update_census`/`verify_zero1_collectives`), (c) the trace-derived
share (the profiler-timeline read-off the README placeholder calls for).
"""

from __future__ import annotations

import glob
import gzip
import json
import re
from pathlib import Path
from typing import Dict, List, Tuple

# Collective op names as they appear on XLA timelines (sync form, async
# `-start` form, and CPU thunk form). `-done` events are completion markers
# whose duration is wait-not-work; skip them like the HLO census does —
# an async collective's `-start` span covers the transfer, so counting
# both halves of a pair would double its time. `ragged-all-to-all` (MoE
# dispatch at uneven expert loads) precedes `all-to-all` so the longer
# name keys the by_op breakdown.
_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|ragged-all-to-all|all-to-all)"
    r"(?!.*-done)")

# Host-side runtime bookkeeping seen on CPU traces (no device lanes exist
# there); everything matching these is neither compute nor communication.
_INFRA_PREFIXES = (
    "ThreadpoolListener", "ThunkExecutor", "Wait", "Rendezvous", "PjRt",
    "CommonPjRt", "Handle inputs", "end:", "CreateOutputs", "Allocate",
    "Deallocate", "BufferAlloc", "BufferFree", "MarkDonated", "python",
    "HostCallback", "TransferTo", "TransferFrom", "CopyTo", "CopyFrom",
    "ExecuteHelper", "Execute (", "call_location",
)


def _norm(name: str) -> str:
    """'wrapped_all-reduce.3' -> 'all-reduce.3' (CPU thunks wrap op names)."""
    return name[8:] if name.startswith("wrapped_") else name


def load_trace(log_dir: str) -> Tuple[List[dict], Dict[int, str],
                                      Dict[tuple, str]]:
    """(complete events, pid -> process name, (pid, tid) -> thread name)
    from every trace.json.gz under `log_dir` (one per host). Raises
    FileNotFoundError if no trace exists."""
    paths = sorted(glob.glob(
        str(Path(log_dir) / "**" / "*.trace.json.gz"), recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {log_dir}")
    events: List[dict] = []
    pids: Dict[int, str] = {}
    tids: Dict[tuple, str] = {}
    for p in paths:
        data = json.loads(gzip.open(p).read())
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e.get("pid")] = e.get("args", {}).get("name", "")
            elif e.get("ph") == "M" and e.get("name") == "thread_name":
                tids[(e.get("pid"), e.get("tid"))] = (
                    e.get("args", {}).get("name", ""))
            elif e.get("ph") == "X" and e.get("dur", 0) > 0:
                events.append(e)
    return events, pids, tids


def xla_op_events(events: List[dict], pids: Dict[int, str],
                  tids: Dict[tuple, str]) -> List[dict]:
    """The events that represent on-device XLA op execution, counted ONCE.

    TPU/GPU traces put ops on `/device:...` process lanes, but a device pid
    carries several overlapping lanes ("XLA Modules" spans the same wall
    time as the sum of its "XLA Ops") — summing all of them double-counts
    busy time and halves the reported collective share, so restrict to the
    per-op lanes when thread names identify them. CPU traces (the test
    backend) run thunks on host threadpool lanes with no device pids; fall
    back to name-based filtering of runtime bookkeeping.
    """
    device_pids = {pid for pid, name in pids.items() if "/device:" in name}
    if device_pids:
        dev = [e for e in events if e.get("pid") in device_pids]
        op_lanes = {key for key, name in tids.items()
                    if key[0] in device_pids and "xla ops" in name.lower()}
        if op_lanes:
            return [e for e in dev
                    if (e.get("pid"), e.get("tid")) in op_lanes]
        return dev
    return [e for e in events
            if not _norm(e["name"]).startswith(_INFRA_PREFIXES)]


def collective_share(log_dir: str) -> dict:
    """Trace-derived gradient-sync share: collective time / XLA-op busy time.

    Returns {collective_us, op_us, share_pct, by_op: {name: us}} aggregated
    over every device lane in the capture window. `share_pct` is the
    fraction of device busy time spent in communication — the number the
    reference's README placeholder wants (README.md:35).
    """
    events, pids, tids = load_trace(log_dir)
    ops = xla_op_events(events, pids, tids)
    coll_us = 0.0
    op_us = 0.0
    by_op: Dict[str, float] = {}
    for e in ops:
        name = _norm(e["name"])
        dur = float(e["dur"])
        op_us += dur
        m = _COLLECTIVE_RE.match(name)
        if m:
            coll_us += dur
            key = m.group(1)
            by_op[key] = by_op.get(key, 0.0) + dur
    return {
        "collective_us": round(coll_us, 1),
        "op_us": round(op_us, 1),
        "share_pct": round(100.0 * coll_us / op_us, 2) if op_us else 0.0,
        "by_op": {k: round(v, 1) for k, v in sorted(by_op.items())},
    }


# ---------------------------------------------------------------------------
# Static HLO collective census — MOVED to analysis/hlo_rules.py (ISSUE 3:
# the compile-time half of the gradient-sync analysis is now a checked
# contract subsystem, not scattered helpers). Re-exported here so existing
# callers (scaling.py, harness.py, tests, notebooks) keep working.
# ---------------------------------------------------------------------------

from ..analysis.hlo_rules import (  # noqa: E402,F401
    collective_census, grad_sync_census, hlo_result_elements,
    preopt_hlo_text, verify_grad_sync_collectives, verify_zero1_collectives,
    weight_update_census,
)


def comm_overlap_split(log_dir: str) -> dict:
    """Exposed-vs-hidden communication time from a jax.profiler trace —
    the overlap instrument of the bucketed reducer (DDP's hooks hide comm
    behind backward compute; here the scan-body collectives have no data
    dependency on the next microbatch, and this measures how much of their
    wall time XLA actually hid).

    A collective event's duration is HIDDEN where it overlaps (same pid,
    any lane) with non-collective op execution, EXPOSED elsewhere. On TPU
    timelines async ``-start`` events span the transfer, so the split is
    honest; on the CPU test backend thunks serialize on the threadpool, so
    exposed ~= 100% — the number is only meaningful with device lanes.

    Returns {collective_us, hidden_us, exposed_us, exposed_frac_pct}.
    """
    events, pids, tids = load_trace(log_dir)
    ops = xla_op_events(events, pids, tids)
    comp_by_pid: Dict[int, List[Tuple[float, float]]] = {}
    coll = []
    for e in ops:
        iv = (float(e["ts"]), float(e["ts"]) + float(e["dur"]))
        if _COLLECTIVE_RE.match(_norm(e["name"])):
            coll.append((e.get("pid"), iv))
        else:
            comp_by_pid.setdefault(e.get("pid"), []).append(iv)
    merged: Dict[int, List[Tuple[float, float]]] = {}
    for pid, ivs in comp_by_pid.items():
        ivs.sort()
        out: List[Tuple[float, float]] = []
        for a, b in ivs:
            if out and a <= out[-1][1]:
                out[-1] = (out[-1][0], max(out[-1][1], b))
            else:
                out.append((a, b))
        merged[pid] = out
    total = hidden = 0.0
    for pid, (a, b) in coll:
        total += b - a
        for ca, cb in merged.get(pid, ()):
            if cb <= a:
                continue
            if ca >= b:
                break
            hidden += min(b, cb) - max(a, ca)
    exposed = max(0.0, total - hidden)
    return {
        "collective_us": round(total, 1),
        "hidden_us": round(hidden, 1),
        "exposed_us": round(exposed, 1),
        "exposed_frac_pct": round(100.0 * exposed / total, 2) if total
        else 0.0,
    }


def device_time_split(log_dir: str) -> dict:
    """The four-way device-time attribution of one captured window
    (ISSUE 15 — the number set telemetry/device.py turns into a typed
    ``device_profile`` event):

    * ``compute_us`` — op busy time that is neither communication nor
      hidden under it,
    * ``comm_hidden_us`` — collective time overlapping compute on the
      same pid (XLA hid it),
    * ``comm_exposed_us`` — collective time nothing overlapped (the
      number that decides whether compressed sync paid off),
    * ``host_gap_us`` — wall extent of the capture minus device busy
      time (dispatch stalls, loader waits, host work).

    The four numbers are UNION wall measures per pid (compute-only wall,
    collective wall coinciding with compute, collective-only wall, idle
    wall), so ``compute + hidden + exposed + gap == window`` holds
    EXACTLY on any trace — including the CPU thunk pool, where 8 virtual
    replicas' all-reduce events overlap each other on one pid and a
    per-event sum (``comm_overlap_split``'s accounting, kept unchanged
    for the bench) can exceed the wall. ``by_op`` stays per-event op
    time (the collective rollup is op work, not wall share). On the CPU
    backend the hidden/exposed numbers measure thunk concurrency, not
    ICI overlap — the ``comm_overlap_split`` caveat applies unchanged.
    """
    events, pids, tids = load_trace(log_dir)
    ops = xla_op_events(events, pids, tids)
    coll_by_pid: Dict[int, List[Tuple[float, float]]] = {}
    comp_by_pid: Dict[int, List[Tuple[float, float]]] = {}
    by_op: Dict[str, float] = {}
    for e in ops:
        iv = (float(e["ts"]), float(e["ts"]) + float(e["dur"]))
        pid = e.get("pid")
        name = _norm(e["name"])
        m = _COLLECTIVE_RE.match(name)
        if m:
            coll_by_pid.setdefault(pid, []).append(iv)
            by_op[m.group(1)] = by_op.get(m.group(1), 0.0) + (iv[1] - iv[0])
        else:
            comp_by_pid.setdefault(pid, []).append(iv)

    def _merge(ivs: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
        ivs = sorted(ivs)
        out: List[Tuple[float, float]] = []
        for a, b in ivs:
            if out and a <= out[-1][1]:
                out[-1] = (out[-1][0], max(out[-1][1], b))
            else:
                out.append((a, b))
        return out

    def _length(ivs: List[Tuple[float, float]]) -> float:
        return sum(b - a for a, b in ivs)

    def _intersect_len(xs: List[Tuple[float, float]],
                       ys: List[Tuple[float, float]]) -> float:
        total = 0.0
        i = j = 0
        while i < len(xs) and j < len(ys):
            a = max(xs[i][0], ys[j][0])
            b = min(xs[i][1], ys[j][1])
            if b > a:
                total += b - a
            if xs[i][1] <= ys[j][1]:
                i += 1
            else:
                j += 1
        return total

    window = compute = hidden = exposed = gap = coll_total = 0.0
    for pid in set(coll_by_pid) | set(comp_by_pid):
        comp = _merge(comp_by_pid.get(pid, []))
        coll = _merge(coll_by_pid.get(pid, []))
        every = _merge(comp + coll)
        if not every:
            continue
        extent = every[-1][1] - every[0][0]
        busy = _length(every)
        c_len, k_len = _length(comp), _length(coll)
        overlap = _intersect_len(comp, coll)
        window += extent
        compute += c_len - overlap
        hidden += overlap
        exposed += k_len - overlap
        gap += extent - busy
        coll_total += k_len
    return {
        "window_us": round(window, 1),
        "compute_us": round(compute, 1),
        "comm_hidden_us": round(hidden, 1),
        "comm_exposed_us": round(exposed, 1),
        "host_gap_us": round(gap, 1),
        "collective_us": round(coll_total, 1),
        "exposed_frac_pct": round(100.0 * exposed / coll_total, 2)
        if coll_total else 0.0,
        "by_op": {k: round(v, 1) for k, v in sorted(by_op.items())},
        "n_device_lanes": len(set(coll_by_pid) | set(comp_by_pid)),
    }


def capture_step_trace(step_fn, state, batch, key, log_dir: str,
                       steps: int = 3):
    """Run `steps` executions of a compiled/jitted train step under a
    jax.profiler trace (call AFTER warmup so compile time stays out of the
    window). Returns the final state. Rides utils/profiling's session
    guard: a concurrently-open session refuses loudly instead of raising
    from deep inside jax."""
    import jax

    from ..utils.profiling import trace_session

    with trace_session(log_dir, owner="capture_step_trace") as started:
        if not started:
            raise RuntimeError(
                "capture_step_trace: a jax profiler session is already "
                "open in this process — stop it (StepProfiler window / "
                "on-demand capture) before capturing a bench trace")
        metrics = None
        for _ in range(steps):
            state, metrics = step_fn(state, batch, key)
        if metrics is not None:
            jax.block_until_ready(metrics)
            float(jax.device_get(metrics["weight"]))  # true completion sync
    return state
