"""Gradient-sync share from jax.profiler traces.

The reference README promises "At 4 GPUs, gradient synchronization accounts
for ~X% of step time" but never measures it (/root/reference/README.md:35) —
on GPU one would read an nsys/profiler timeline. The TPU equivalent: capture
a `jax.profiler` trace of the compiled train step and sum the durations of
collective ops (the DDP all-reduce equivalents XLA scheduled) against the
total XLA-op busy time. This module parses the Chrome-trace JSON the profiler
writes (`plugins/profile/<ts>/<host>.trace.json.gz`) — no tensorboard plugin
needed.

Instruments in experiments/scaling.py `gradsync`, cross-checked three ways:
(a) measured 1-vs-N step-time delta, (b) static HLO collective census
(`collective_census` below, plus the zero1 weight-update classification
`weight_update_census`/`verify_zero1_collectives`), (c) the trace-derived
share (the profiler-timeline read-off the README placeholder calls for).
"""

from __future__ import annotations

import glob
import gzip
import json
import re
from pathlib import Path
from typing import Dict, List, Tuple

# Collective op names as they appear on XLA timelines (sync form, async
# `-start` form, and CPU thunk form). `-done` events are completion markers
# whose duration is wait-not-work; skip them like the HLO census does.
_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?!.*-done)")

# Host-side runtime bookkeeping seen on CPU traces (no device lanes exist
# there); everything matching these is neither compute nor communication.
_INFRA_PREFIXES = (
    "ThreadpoolListener", "ThunkExecutor", "Wait", "Rendezvous", "PjRt",
    "CommonPjRt", "Handle inputs", "end:", "CreateOutputs", "Allocate",
    "Deallocate", "BufferAlloc", "BufferFree", "MarkDonated", "python",
    "HostCallback", "TransferTo", "TransferFrom", "CopyTo", "CopyFrom",
    "ExecuteHelper", "Execute (", "call_location",
)


def _norm(name: str) -> str:
    """'wrapped_all-reduce.3' -> 'all-reduce.3' (CPU thunks wrap op names)."""
    return name[8:] if name.startswith("wrapped_") else name


def load_trace(log_dir: str) -> Tuple[List[dict], Dict[int, str],
                                      Dict[tuple, str]]:
    """(complete events, pid -> process name, (pid, tid) -> thread name)
    from every trace.json.gz under `log_dir` (one per host). Raises
    FileNotFoundError if no trace exists."""
    paths = sorted(glob.glob(
        str(Path(log_dir) / "**" / "*.trace.json.gz"), recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {log_dir}")
    events: List[dict] = []
    pids: Dict[int, str] = {}
    tids: Dict[tuple, str] = {}
    for p in paths:
        data = json.loads(gzip.open(p).read())
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e.get("pid")] = e.get("args", {}).get("name", "")
            elif e.get("ph") == "M" and e.get("name") == "thread_name":
                tids[(e.get("pid"), e.get("tid"))] = (
                    e.get("args", {}).get("name", ""))
            elif e.get("ph") == "X" and e.get("dur", 0) > 0:
                events.append(e)
    return events, pids, tids


def xla_op_events(events: List[dict], pids: Dict[int, str],
                  tids: Dict[tuple, str]) -> List[dict]:
    """The events that represent on-device XLA op execution, counted ONCE.

    TPU/GPU traces put ops on `/device:...` process lanes, but a device pid
    carries several overlapping lanes ("XLA Modules" spans the same wall
    time as the sum of its "XLA Ops") — summing all of them double-counts
    busy time and halves the reported collective share, so restrict to the
    per-op lanes when thread names identify them. CPU traces (the test
    backend) run thunks on host threadpool lanes with no device pids; fall
    back to name-based filtering of runtime bookkeeping.
    """
    device_pids = {pid for pid, name in pids.items() if "/device:" in name}
    if device_pids:
        dev = [e for e in events if e.get("pid") in device_pids]
        op_lanes = {key for key, name in tids.items()
                    if key[0] in device_pids and "xla ops" in name.lower()}
        if op_lanes:
            return [e for e in dev
                    if (e.get("pid"), e.get("tid")) in op_lanes]
        return dev
    return [e for e in events
            if not _norm(e["name"]).startswith(_INFRA_PREFIXES)]


def collective_share(log_dir: str) -> dict:
    """Trace-derived gradient-sync share: collective time / XLA-op busy time.

    Returns {collective_us, op_us, share_pct, by_op: {name: us}} aggregated
    over every device lane in the capture window. `share_pct` is the
    fraction of device busy time spent in communication — the number the
    reference's README placeholder wants (README.md:35).
    """
    events, pids, tids = load_trace(log_dir)
    ops = xla_op_events(events, pids, tids)
    coll_us = 0.0
    op_us = 0.0
    by_op: Dict[str, float] = {}
    for e in ops:
        name = _norm(e["name"])
        dur = float(e["dur"])
        op_us += dur
        m = _COLLECTIVE_RE.match(name)
        if m:
            coll_us += dur
            key = m.group(1)
            by_op[key] = by_op.get(key, 0.0) + dur
    return {
        "collective_us": round(coll_us, 1),
        "op_us": round(op_us, 1),
        "share_pct": round(100.0 * coll_us / op_us, 2) if op_us else 0.0,
        "by_op": {k: round(v, 1) for k, v in sorted(by_op.items())},
    }


# ---------------------------------------------------------------------------
# Static HLO collective census (the compile-time half of the gradient-sync
# analysis; the trace functions above are the runtime half).
# ---------------------------------------------------------------------------

# HLO text: `%name = shape op-name(...)`. On TPU the latency-hiding scheduler
# splits collectives into async `-start`/`-done` pairs; count the `-start`
# half (and bare sync forms), never `-done`, so each collective counts once.
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?[.\w]*\(")

# One array shape inside an HLO result: "f32[1000,512]{1,0}" (possibly inside
# a tuple). Captures the bracketed dims; "f32[]" is a scalar.
_HLO_SHAPE_RE = re.compile(r"\w+\[([\d,]*)\]")


def hlo_result_elements(shape_str: str) -> int:
    """Total elements across every array in an HLO result shape string
    (async collectives return tuples; sum the parts so `-start` forms
    compare like their sync equivalents)."""
    total = 0
    for m in _HLO_SHAPE_RE.finditer(shape_str):
        dims = m.group(1)
        if not dims:
            total += 1  # scalar
            continue
        n = 1
        for d in dims.split(","):
            n *= int(d)
        total += n
    return total


def collective_census(compiled_text: str) -> List[dict]:
    """Census of collective ops in optimized HLO text: op kind + result shape.

    The static half of the grad-sync analysis: what the compiler actually
    scheduled (names/shapes straight from the executable), standing in for
    the reference's promised profiler-timeline read-off (README.md:35)."""
    rows = {}
    for m in _HLO_COLLECTIVE_RE.finditer(compiled_text):
        shape, kind, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue  # the paired completion of an async -start
        key = (kind, shape)
        if key not in rows:
            rows[key] = {"op": kind, "result_shape": shape, "count": 0}
        rows[key]["count"] += 1
    return sorted(rows.values(), key=lambda r: (r["op"], r["result_shape"]))


def weight_update_census(compiled_text: str, min_elements: int = 8192) -> dict:
    """The gradient-sync subset of the census: collectives whose result
    carries at least `min_elements` elements — gradient- and parameter-sized
    transfers. Scalar psums (metric fan-in, global-norm clipping, BatchNorm
    channel stats) fall under the floor, so the returned counts isolate the
    ops that move the model: the DDP-style grad all-reduce on the replicated
    path, reduce-scatter + all-gather on the zero1 path.

    Returns {"all-reduce": n, "reduce-scatter": n, "all-gather": n,
    "rows": [...]} (other collective kinds appear only if present)."""
    counts: Dict[str, int] = {"all-reduce": 0, "reduce-scatter": 0,
                              "all-gather": 0}
    rows = []
    for c in collective_census(compiled_text):
        if hlo_result_elements(c["result_shape"]) < min_elements:
            continue
        counts[c["op"]] = counts.get(c["op"], 0) + c["count"]
        rows.append(c)
    counts["rows"] = rows
    return counts


def verify_zero1_collectives(replicated_text: str, zero1_text: str,
                             min_elements: int = 8192) -> dict:
    """The acceptance check for the zero1 mode (ISSUE 1): in the compiled
    zero1 step, gradient-sized all-reduces are REPLACED by reduce-scatter +
    all-gather. Returns the two weight-update censuses plus a verdict dict;
    raises AssertionError naming the offending ops when the replacement did
    not happen (a silent fallback to all-reduce would erase the win while
    the flag still claims it)."""
    rep = weight_update_census(replicated_text, min_elements)
    z1 = weight_update_census(zero1_text, min_elements)
    if rep["all-reduce"] == 0:
        raise AssertionError(
            "replicated step shows no gradient-sized all-reduce — the "
            f"census floor ({min_elements} elements) is above the model's "
            "gradient transfers; lower min_elements")
    problems = []
    if z1["all-reduce"]:
        problems.append(
            f"zero1 step still contains {z1['all-reduce']} gradient-sized "
            f"all-reduce(s): {[r for r in z1['rows'] if r['op'] == 'all-reduce']}")
    if not z1["reduce-scatter"]:
        problems.append("zero1 step contains no reduce-scatter")
    if not z1["all-gather"]:
        problems.append("zero1 step contains no all-gather")
    if problems:
        raise AssertionError("; ".join(problems))
    return {"replicated": rep, "zero1": z1}


def capture_step_trace(step_fn, state, batch, key, log_dir: str,
                       steps: int = 3):
    """Run `steps` executions of a compiled/jitted train step under a
    jax.profiler trace (call AFTER warmup so compile time stays out of the
    window). Returns the final state."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        metrics = None
        for _ in range(steps):
            state, metrics = step_fn(state, batch, key)
        if metrics is not None:
            jax.block_until_ready(metrics)
            float(jax.device_get(metrics["weight"]))  # true completion sync
    finally:
        jax.profiler.stop_trace()
    return state
