"""FLOPs accounting, chip peak detection, and MFU.

The reference's throughput meter reports samples/s with no notion of how much
compute a sample costs (/root/reference/train_ddp.py:224-243), so its numbers
cannot be sanity-checked against hardware. Here every benchmark result carries
model-FLOPs utilization (MFU): a samples/s claim that implies more FLOP/s than
the chip's MXU peak is a broken measurement, and `check_mfu` fails loudly
instead of reporting it.

Two independent FLOPs instruments, cross-checked against each other:

1. ``xla_flops_per_step`` — XLA's own cost analysis of the *compiled* train
   step (what the hardware will actually execute, post-fusion).
2. ``jaxpr_matmul_flops`` — an analytic matmul/conv model: walk the traced
   jaxpr and sum ``2*M*N*K``-style FLOPs for every ``dot_general`` /
   ``conv_general_dilated``, recursing into scan/pjit/remat sub-jaxprs
   (scan bodies multiplied by trip count). This is the "pen-and-paper" count
   a performance engineer would do — independent of XLA's bookkeeping.

A train step should cost ~3x the forward pass (backward = 2 matmuls per
forward matmul), so ``xla(train) / analytic(forward)`` is expected in [2.5, 4]
for matmul-dominated models; elementwise-heavy models (BatchNorm ResNets at
tiny images) run higher.
"""

from __future__ import annotations

import math
import os
from typing import Any, Optional

import jax
import numpy as np

# Peak dense bf16 TFLOP/s per JAX device, keyed by `jax.Device.device_kind`.
# NOTE v2/v3 expose one device per TensorCore (2 per chip); v4+ expose one
# device per chip (megacore). Values are per *device* so MFU math needs no
# core-vs-chip special case. Public figures (cloud.google.com/tpu/docs).
CHIP_PEAK_TFLOPS_BF16 = {
    "TPU v2": 22.5,
    "TPU v3": 61.25,
    "TPU v4": 275.0,
    "TPU v4 lite": 137.5,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5": 459.0,        # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,   # v6e / Trillium
    "TPU v6e": 918.0,
}

PEAK_ENV_VAR = "DPT_CHIP_PEAK_TFLOPS"


def chip_peak_tflops(device: Optional[jax.Device] = None) -> Optional[float]:
    """Per-device peak dense bf16 TFLOP/s, or None when unknown.

    ``DPT_CHIP_PEAK_TFLOPS`` overrides the lookup (new chip generations land
    before this table learns about them).
    """
    override = os.environ.get(PEAK_ENV_VAR)
    if override:
        return float(override)
    if device is None:
        device = jax.devices()[0]
    if device.platform != "tpu":
        return None  # CPU/GPU test backends: MFU not meaningful here
    return CHIP_PEAK_TFLOPS_BF16.get(device.device_kind)


def xla_flops_per_step(compiled) -> Optional[float]:
    """FLOPs of one execution of a compiled (lowered+compiled) computation,
    from XLA's cost analysis. None if the backend does not report it."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):  # older jax returned [dict]
        cost = cost[0] if cost else {}
    flops = cost.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


# -- analytic matmul/conv model (jaxpr walk) --------------------------------

def _dot_general_flops(eqn) -> float:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    (lhs_c, rhs_c), (lhs_b, _) = dnums
    batch = math.prod(lhs.shape[d] for d in lhs_b)
    contract = math.prod(lhs.shape[d] for d in lhs_c)
    m = math.prod(lhs.shape[d] for d in range(len(lhs.shape))
                  if d not in lhs_c and d not in lhs_b)
    n = math.prod(rhs.shape[d] for d in range(len(rhs.shape))
                  if d not in rhs_c and d not in dnums[1][1])
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel
    dnums = eqn.params["dimension_numbers"]
    out_spatial = math.prod(out.shape[d] for d in dnums.out_spec[2:])
    out_ch = out.shape[dnums.out_spec[1]]
    batch = out.shape[dnums.out_spec[0]]
    kernel_spatial = math.prod(rhs.shape[d] for d in dnums.rhs_spec[2:])
    in_ch = rhs.shape[dnums.rhs_spec[1]]  # per feature group
    return 2.0 * batch * out_spatial * out_ch * kernel_spatial * in_ch


def _jaxpr_flops(jaxpr) -> float:
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            body = eqn.params["jaxpr"].jaxpr
            total += eqn.params["length"] * _jaxpr_flops(body)
        elif name == "while":
            # trip count unknown statically; count one iteration (lower bound)
            total += _jaxpr_flops(eqn.params["body_jaxpr"].jaxpr)
        elif name == "pallas_call":
            # Prefer the kernel author's exact CostEstimate: our flash
            # kernels pass causal-aware counts (live diagonal blocks only).
            # Fallback — scale ONE tile's kernel body by the grid size, or
            # the kernel's matmuls vanish from the count; this overcounts
            # causal kernels ~2x (pl.when-skipped blocks), which is why the
            # estimate channel exists.
            ce = eqn.params.get("cost_estimate")
            ce_flops = getattr(ce, "flops", None) if ce is not None else None
            if ce_flops:
                total += float(ce_flops)
                continue
            grid = ()
            gm = eqn.params.get("grid_mapping")
            if gm is not None:
                grid = getattr(gm, "grid", ())
            body = eqn.params.get("jaxpr")
            if body is not None:
                tile = _jaxpr_flops(getattr(body, "jaxpr", body))
                total += tile * math.prod(int(g) for g in grid if
                                          isinstance(g, int))
        else:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr"):
                sub = eqn.params.get(key) if eqn.params else None
                if sub is not None:
                    total += _jaxpr_flops(getattr(sub, "jaxpr", sub))
            for key in ("branches",):
                subs = eqn.params.get(key) if eqn.params else None
                if subs:
                    # max over branches (cond executes one)
                    total += max(_jaxpr_flops(getattr(s, "jaxpr", s))
                                 for s in subs)
    return total


def jaxpr_matmul_flops(fn, *args, **kwargs) -> float:
    """Analytic matmul+conv FLOPs of `fn(*args)` — trace and walk the jaxpr."""
    jaxpr = jax.make_jaxpr(fn, **kwargs)(*args)
    return _jaxpr_flops(jaxpr.jaxpr)


# -- MFU --------------------------------------------------------------------

def mfu_pct(flops_per_step: Optional[float], steps_per_sec: float,
            peak_tflops: Optional[float]) -> Optional[float]:
    if not flops_per_step or not peak_tflops:
        return None
    return 100.0 * flops_per_step * steps_per_sec / (peak_tflops * 1e12)


class MeasurementError(RuntimeError):
    """A benchmark number that cannot be true (e.g. implied FLOP/s > peak)."""


def check_mfu(mfu: Optional[float], context: str = "") -> Optional[str]:
    """Validate an MFU claim. Returns a warning string for suspicious-but-
    possible values; raises MeasurementError for impossible ones (>100% of
    the MXU peak means the timing or the FLOPs model is broken — the r2
    failure mode where 484 TFLOP/s was reported on a 197 TFLOP/s chip)."""
    if mfu is None:
        return None
    if mfu > 100.0:
        raise MeasurementError(
            f"measured MFU {mfu:.1f}% exceeds hardware peak ({context}); "
            "the timing harness or FLOPs model is broken — refusing to "
            "report an impossible number")
    if mfu > 60.0:
        return (f"MFU {mfu:.1f}% is above the ~60% typically achievable "
                f"({context}); verify the chip-peak table and timing")
    return None
