"""Plot generation for the experiment CSVs — the "plots" half of the
reference README's promised "Tables + plots" (/root/reference/README.md:27-31,
an empty outline there; the tables come from experiments/scaling.py --csv).

Usage:
    python -m distributed_pytorch_training_tpu.experiments.plots \
        results.csv --out scaling.png [--kind scaling] [--dark]

The kind is auto-detected from the CSV columns when not given. One figure per
CSV: scaling (throughput + efficiency vs chips), batch (throughput vs
per-device batch), amp (fp32 vs bf16 bars), gradsync (share bars), pipeline
(throughput vs microbatches with the predicted-bubble ceiling).

Style notes: single measure -> single hue (no legend needed — the title names
the series); values are direct-labeled selectively (ends/extremes); grids and
axes stay recessive so the data ink dominates. The hues are the validated
defaults from the dataviz reference palette (light surface).
"""

from __future__ import annotations

import argparse
import csv
import sys
from pathlib import Path
from typing import Dict, List

# Validated default palette (light mode): slot-1 blue for the primary series,
# slot-2 orange only when a genuinely second series exists.
BLUE = "#2a78d6"
ORANGE = "#eb6834"
INK = "#1f2430"
MUTED = "#6b7280"
GRID = "#e5e7eb"


def _read(csv_path: str) -> List[Dict[str, str]]:
    with open(csv_path, newline="") as f:
        return list(csv.DictReader(f))


def detect_kind(rows: List[Dict[str, str]]) -> str:
    cols = set(rows[0].keys())
    if "scaling_efficiency_pct" in cols:
        return "scaling"
    if "bubble_predicted_pct" in cols:
        return "pipeline"
    if "precision" in cols:
        return "amp"
    if "per_device_batch" in cols:
        return "batch"
    if "measurement" in cols:
        return "gradsync"
    raise ValueError(f"cannot detect experiment kind from columns {cols}")


def _style(ax):
    ax.spines["top"].set_visible(False)
    ax.spines["right"].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=MUTED, labelsize=9)
    ax.grid(True, axis="y", color=GRID, linewidth=0.8)
    ax.set_axisbelow(True)


def _latest(rows: List[Dict[str, str]], kind: str) -> List[Dict[str, str]]:
    """Keep the LAST row per x-key: the documented workflow APPENDS rows
    across runs (scaling.py's --csv opens in append mode), so a re-run CSV
    holds several sweeps — plots reflect the most recent one, in its order,
    instead of zigzagging across all of them."""
    keys = {
        "scaling": lambda r: r["chips"],
        "batch": lambda r: r["per_device_batch"],
        "amp": lambda r: r["precision"],
        "gradsync": lambda r: r["measurement"],
        "pipeline": lambda r: (r["config"], r["microbatches"]),
    }[kind]
    latest: Dict = {}
    for r in rows:  # dict preserves first-seen order; overwrite keeps order
        k = keys(r)
        if k in latest:
            del latest[k]  # re-append so the NEW run's ordering wins
        latest[k] = r
    return list(latest.values())


def _fig(title: str, ylabel: str, xlabel: str):
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(6.4, 4.0), dpi=144)
    ax.set_title(title, color=INK, fontsize=11, loc="left", pad=12)
    ax.set_ylabel(ylabel, color=MUTED, fontsize=9)
    ax.set_xlabel(xlabel, color=MUTED, fontsize=9)
    _style(ax)
    return fig, ax


def plot_scaling(rows, out: str) -> None:
    xs = [int(r["chips"]) for r in rows]
    ys = [float(r["global_samples_per_s"]) for r in rows]
    eff = [float(r["scaling_efficiency_pct"]) for r in rows]
    fig, ax = _fig("Data-parallel scaling — global throughput",
                   "samples / s", "chips")
    ideal = [ys[0] * x / xs[0] for x in xs]
    ax.plot(xs, ideal, color=GRID, linewidth=2, linestyle="--", zorder=1)
    ax.annotate("ideal linear", (xs[-1], ideal[-1]), color=MUTED, fontsize=8,
                ha="right", va="bottom")
    ax.plot(xs, ys, color=BLUE, linewidth=2, marker="o", markersize=5,
            zorder=3)
    ax.annotate(f"{eff[-1]:.0f}% efficiency", (xs[-1], ys[-1]), color=INK,
                fontsize=9, ha="right", va="top", xytext=(0, -10),
                textcoords="offset points")
    ax.set_xscale("log", base=2)
    ax.set_xticks(xs, [str(x) for x in xs])
    fig.savefig(out, bbox_inches="tight")


def plot_batch(rows, out: str) -> None:
    xs = [int(r["per_device_batch"]) for r in rows]
    ys = [float(r["global_samples_per_s"]) for r in rows]
    fig, ax = _fig("Throughput vs per-device batch size", "samples / s",
                   "per-device batch")
    ax.plot(xs, ys, color=BLUE, linewidth=2, marker="o", markersize=5)
    ax.annotate(f"{ys[-1]:,.0f}", (xs[-1], ys[-1]), color=INK, fontsize=9,
                ha="left", va="center", xytext=(6, 0),
                textcoords="offset points")
    ax.set_xscale("log", base=2)
    ax.set_xticks(xs, [str(x) for x in xs])
    fig.savefig(out, bbox_inches="tight")


def plot_amp(rows, out: str) -> None:
    pairs = [(r["precision"], float(r["global_samples_per_s"]))
             for r in rows if r["precision"] in ("fp32", "bf16")]
    speed = [float(r["global_samples_per_s"]) for r in rows
             if r["precision"] == "bf16_speedup"]
    fig, ax = _fig("Mixed precision — bf16 vs true fp32 throughput",
                   "samples / s", "")
    names = [p[0] for p in pairs]
    vals = [p[1] for p in pairs]
    bars = ax.bar(names, vals, color=BLUE, width=0.55, zorder=3)
    for b, v in zip(bars, vals):
        ax.annotate(f"{v:,.0f}", (b.get_x() + b.get_width() / 2, v),
                    ha="center", va="bottom", color=INK, fontsize=9,
                    xytext=(0, 3), textcoords="offset points")
    if speed:
        ax.set_title(f"Mixed precision — bf16 is {speed[0]:.2f}x fp32 "
                     "(HIGHEST-precision matmuls)", color=INK, fontsize=11,
                     loc="left", pad=12)
    fig.savefig(out, bbox_inches="tight")


def plot_gradsync(rows, out: str) -> None:
    vals = {r["measurement"]: float(r["value"]) for r in rows}
    keys = [k for k in ("grad_sync_share_1vsN_pct",
                        "grad_sync_share_trace_pct") if k in vals]
    labels = {"grad_sync_share_1vsN_pct": "1-vs-N step time",
              "grad_sync_share_trace_pct": "profiler trace"}
    fig, ax = _fig("Gradient-sync share of step time — two instruments",
                   "% of step time", "")
    names = [labels[k] for k in keys]
    ys = [vals[k] for k in keys]
    bars = ax.bar(names, ys, color=BLUE, width=0.5, zorder=3)
    for b, v in zip(bars, ys):
        ax.annotate(f"{v:.1f}%", (b.get_x() + b.get_width() / 2, v),
                    ha="center", va="bottom", color=INK, fontsize=9,
                    xytext=(0, 3), textcoords="offset points")
    fig.savefig(out, bbox_inches="tight")


def plot_pipeline(rows, out: str) -> None:
    base = [r for r in rows if r["microbatches"] == "-"]
    pipe = [r for r in rows if r["microbatches"] != "-"]
    xs = [int(r["microbatches"]) for r in pipe]
    ys = [float(r["samples_per_s"]) for r in pipe]
    fig, ax = _fig("GPipe throughput vs microbatches", "samples / s",
                   "microbatches (bubble = (P-1)/(M+P-1))")
    if base:
        b = float(base[0]["samples_per_s"])
        ax.axhline(b, color=GRID, linewidth=2, linestyle="--", zorder=1)
        ax.annotate("pure-DP baseline", (xs[-1], b), color=MUTED, fontsize=8,
                    ha="right", va="bottom")
    ax.plot(xs, ys, color=BLUE, linewidth=2, marker="o", markersize=5,
            zorder=3)
    for x, y, r in zip(xs, ys, pipe):
        ax.annotate(f"{float(r['bubble_predicted_pct']):.0f}% bubble",
                    (x, y), color=MUTED, fontsize=8, ha="center", va="top",
                    xytext=(0, -8), textcoords="offset points")
    ax.set_xticks(xs, [str(x) for x in xs])
    fig.savefig(out, bbox_inches="tight")


PLOTTERS = {"scaling": plot_scaling, "batch": plot_batch, "amp": plot_amp,
            "gradsync": plot_gradsync, "pipeline": plot_pipeline}


def main(argv=None):
    try:
        import matplotlib
    except ImportError as e:  # an optional extra, not a core dependency
        raise SystemExit(
            "plots need matplotlib: pip install "
            "'distributed-pytorch-training-tpu[plots]'") from e

    matplotlib.use("Agg")  # headless: bench hosts have no display

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("csv", help="CSV from experiments/scaling.py --csv")
    p.add_argument("--kind", choices=sorted(PLOTTERS), default=None)
    p.add_argument("--out", default=None, help="output PNG path")
    args = p.parse_args(argv)

    rows = _read(args.csv)
    if not rows:
        raise SystemExit(f"{args.csv}: empty CSV")
    kind = args.kind or detect_kind(rows)
    out = args.out or str(Path(args.csv).with_suffix(f".{kind}.png"))
    PLOTTERS[kind](_latest(rows, kind), out)
    print(f"wrote {out}")


if __name__ == "__main__":
    main(sys.argv[1:])
