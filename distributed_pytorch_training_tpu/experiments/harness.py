"""Shared measurement harness for bench.py and experiments/scaling.py.

One copy of the recipe (build trainer -> synthetic device batch -> warmup ->
median-of-repeats timed steps) so the headline bench and the experiment
tables stay comparable — the throughput-meter role of the reference
(/root/reference/train_ddp.py:224-243), done without host syncs in the loop.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def build_image_trainer(devices: Sequence[jax.Device], bf16: bool,
                        model_name: str = "resnet18", image_hw: int = 32,
                        num_classes: int = 10):
    """(trainer, state, mesh) for an image-classification config on a pure-DP
    mesh over `devices` (the benchmark workload, BASELINE.json:8)."""
    from ..data import CIFAR10_MEAN, CIFAR10_STD
    from ..models import get_model
    from ..parallel import MeshSpec, build_mesh
    from ..training import TrainConfig, Trainer
    from ..training.optim import sgd
    from ..training.tasks import ImageClassificationTask

    mesh = build_mesh(MeshSpec(data=len(devices)), devices=list(devices))
    dtype = jnp.bfloat16 if bf16 else jnp.float32
    model = get_model(model_name, num_classes=num_classes, dtype=dtype)
    task = ImageClassificationTask(mean=CIFAR10_MEAN, std=CIFAR10_STD,
                                   augment=True, compute_dtype=dtype)
    trainer = Trainer(task, mesh, TrainConfig(seed=0, bf16=bf16))
    state = trainer.init_state(
        model, np.zeros((1, image_hw, image_hw, 3), np.float32),
        sgd(0.1, momentum=0.9, weight_decay=5e-4), jax.random.PRNGKey(0))
    return trainer, state, mesh


def synth_image_batch(mesh, per_device_batch: int, image_hw: int = 32,
                      num_classes: int = 10):
    """(sharded_batch, global_batch): deterministic uint8 batch on the mesh."""
    from ..parallel import shard_batch
    from ..parallel.mesh import batch_shard_count

    global_batch = per_device_batch * batch_shard_count(mesh)
    rng = np.random.RandomState(0)
    batch = shard_batch({
        "image": rng.randint(0, 256, (global_batch, image_hw, image_hw, 3)
                             ).astype(np.uint8),
        "label": rng.randint(0, num_classes, global_batch).astype(np.int32),
        "weight": np.ones(global_batch, np.float32),
    }, mesh)
    return batch, global_batch


def timed_steps(step_fn: Callable, state, batch, global_batch: int,
                steps: int, repeats: int = 3,
                warmup: int = 3) -> Tuple[float, float]:
    """Median (steps/sec, samples/sec) of `repeats` timing windows.

    `step_fn(state, batch, key) -> (state, metrics)` may be a jitted function
    or an AOT-compiled executable. Warmup covers compile + autotuning."""
    key = jax.random.PRNGKey(0)
    for _ in range(warmup):
        state, metrics = step_fn(state, batch, key)
    if warmup:  # warmup=0 leaves `metrics` unbound; nothing to wait on
        jax.block_until_ready(metrics["weight"])
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, batch, key)
        jax.block_until_ready(metrics["weight"])
        rates.append(steps / (time.perf_counter() - t0))
    sps = float(np.median(rates))
    return sps, sps * global_batch
